"""Disruption engine: emptiness / drift / consolidation.

Counterpart of pkg/controllers/disruption (13.5k LoC): a polling
controller that gathers disruptable candidates, applies cron-window
budgets, and tries each Method in order — Emptiness, Drift,
MultiNodeConsolidation, SingleNodeConsolidation — first success wins
(controller.go:98-176). Consolidation decisions re-run the provisioning
scheduler with candidates excluded (SimulateScheduling, helpers.go:52)
and compare replacement price against the candidates' current price,
including the spot-to-spot flexibility floor (consolidation.go:237-311).

The multi-node search keeps the reference's binary-search-over-prefix
shape (multinodeconsolidation.go:116-169); each probe is one batched
solver call, so a full search is O(log N) solver launches instead of
O(log N) sequential Go scheduling loops.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    CAPACITY_TYPE_SPOT,
    DISRUPTED_NO_SCHEDULE_TAINT,
    DO_NOT_DISRUPT_ANNOTATION,
    INSTANCE_TYPE_LABEL,
    NODEPOOL_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_CONSOLIDATABLE,
    COND_DISRUPTION_REASON,
    COND_DRIFTED,
    NodeClaim,
)
from karpenter_tpu.apis.v1.nodepool import (
    CONSOLIDATION_WHEN_EMPTY,
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_UNDERUTILIZED,
    NodePool,
)
from karpenter_tpu.cloudprovider.types import CloudProvider, effective_price
from karpenter_tpu import explain
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.metrics.store import (
    DISRUPTION_EVALUATION_DURATION,
    DISRUPTION_PROBE_STARVATION,
    DISRUPTION_SNAPSHOT,
    NODECLAIMS_DISRUPTED,
)
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.provisioning.provisioner import Provisioner
from karpenter_tpu.provisioning.scheduler import (
    Scheduler,
    SchedulerResults,
    _state_node_key,
)
from karpenter_tpu.state.cluster import Cluster, StateNode
from karpenter_tpu.utils.pdb import PdbLimits

log = logging.getLogger("karpenter.disruption")

# consolidation constants (consolidation.go:46-49)
SPOT_TO_SPOT_MIN_TYPES = 15
MULTI_NODE_MAX_CANDIDATES = 100  # multinodeconsolidation.go:86
COMMAND_TIMEOUT_SECONDS = 10 * 60  # orchestration retry deadline (queue.go:86)
# method wall-clock bounds: the multi-node search keeps the last valid
# command when time runs out (multinodeconsolidation.go:35,116-169);
# single-node stops mid-scan (singlenodeconsolidation.go:34)
MULTI_NODE_TIMEOUT_SECONDS = 60.0
SINGLE_NODE_TIMEOUT_SECONDS = 3 * 60.0
# candidate cap for the one-shot global repack: bounds the cost solve
# the way the prefix search caps at 100 (multinodeconsolidation.go:86)
# while letting the batched objective see far more of the fleet
GLOBAL_REPACK_MAX_CANDIDATES = 500
# extra prefixes probed above the binary-search result (largest first):
# the amortized-merge payoff concentrates just above the failing
# midpoint, and an uncapped sweep would burn the whole timeout on O(N)
# device solves every round when no larger merge exists
MULTI_NODE_SWEEP_PROBES = 8


@dataclass
class Candidate:
    """One disruptable node (disruption/types.go:73-121)."""

    state_node: StateNode
    node_pool: NodePool
    reschedulable_pods: list[Pod]
    instance_type_name: str
    capacity_type: str
    zone: str
    price: float
    disruption_cost: float


@dataclass
class _CandidateCore:
    """Retained per-node candidate-scan material (see
    DisruptionEngine._candidate_core). PDB verdicts are deliberately
    NOT cached: disruptions_allowed derives from the whole selected
    pod population's live health, which pod events on OTHER nodes
    change without touching this node's dirt — the scan re-asks
    can_evict per pod against a per-scan allowance-memoized PdbLimits
    instead."""

    ver: tuple
    # [(pod, is_daemon)] over the node's bound pods, sorted by pod key
    pod_info: list
    labels: dict
    price_fp: object = None   # catalog fingerprint price resolved at
    price: Optional[float] = None


@dataclass
class Command:
    """A decided disruption (types.go:129)."""

    reason: str
    candidates: list[Candidate]
    results: Optional[SchedulerResults] = None  # replacement plans
    started_at: float = 0.0

    @property
    def replacement_count(self) -> int:
        return len(self.results.new_node_plans) if self.results else 0


def pod_disruption_cost(pod: Pod) -> float:
    """EvictionCost (utils/disruption/disruption.go): base 1.0, plus
    the pod-deletion-cost annotation scaled by 2^27 (min cost ~ -15
    pods, max ~ +17) and the scheduling priority scaled by 2^25,
    clamped to [-10, 10]."""
    cost = 1.0
    raw = pod.metadata.annotations.get(
        "controller.kubernetes.io/pod-deletion-cost"
    )
    if raw is not None:
        try:
            cost += float(raw) / 2.0**27
        except ValueError:
            log.warning("bad pod-deletion-cost %r on %s", raw, pod.key)
    if pod.spec.priority:
        cost += float(pod.spec.priority) / 2.0**25
    return max(-10.0, min(10.0, cost))


class DisruptionEngine:
    def __init__(
        self,
        kube: KubeClient,
        cluster: Cluster,
        cloud: CloudProvider,
        provisioner: Provisioner,
        queue: Optional["OrchestrationQueue"] = None,
        seed: int = 0,
        options=None,
        clock=None,
        recorder=None,
    ):
        from karpenter_tpu.operator.options import Options

        self.clock = clock if clock is not None else time.monotonic
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.provisioner = provisioner
        self.queue = queue or OrchestrationQueue(
            kube, cluster, provisioner, recorder=recorder
        )
        self.options = options or Options()
        self._rng = random.Random(seed)
        # per-round offering price index; reset by get_candidates
        self._price_index: dict[str, dict[tuple[str, str, str], float]] = {}
        # batched-probe state: a thread-local probe cache — the search
        # methods prime it, simulate_scheduling consults it
        self._probe_tls = threading.local()
        # the retained-inputs seam (ISSUE 15): every fleet snapshot a
        # scan or simulation consumes comes from here, O(dirty) —
        # candidate scans additionally retain per-node cores (pod
        # lists, PDB verdicts, labels, prices) stamped with the seam's
        # dirt generations
        from karpenter_tpu.state.retained import RetainedFleetSeam

        self.fleet_seam = RetainedFleetSeam(
            kube, cluster,
            pools_fn=provisioner.ready_pools_with_types,
            options=self.options,
        )
        self._cand_cores: dict[str, "_CandidateCore"] = {}
        self._cand_scans = 0
        self._audit_scan = False
        self._retain_cores = True
        self._core_hits = self._core_rebuilds = 0
        from karpenter_tpu.disruption.validation import Validator

        self.queue.validator = Validator(self)

    # -- batched probes (solver/consolidation_batch.py) ------------------------

    def _get_probe_cache(self) -> Optional[dict]:
        return getattr(self._probe_tls, "cache", None)

    def _set_probe_cache(self, value: Optional[dict]) -> None:
        self._probe_tls.cache = value

    def _get_probe_pruner(self):
        return getattr(self._probe_tls, "pruner", None)

    def _set_probe_pruner(self, value) -> None:
        self._probe_tls.pruner = value

    @staticmethod
    def probe_pruning_enabled() -> bool:
        return os.environ.get(
            "KARPENTER_LP_PRUNE", "1"
        ).lower() not in ("0", "false", "off")

    def batch_probes_enabled(self) -> bool:
        return os.environ.get(
            "KARPENTER_BATCH_PROBES", "1"
        ).lower() not in ("0", "false", "off")

    def _probe_solver(self):
        """A fresh shared-snapshot BatchProbeSolver per SEARCH METHOD
        (not per reconcile round): watch events land on the cluster
        mirror concurrently, so a snapshot shared across methods could
        serve drift-era verdicts to the single-node scan. One snapshot
        per ladder keeps freshness within the same window a sequential
        scan has, while still amortizing deep_copy_nodes()/Scheduler/
        encode across every probe of that ladder."""
        return self._build_probe_solver()

    def _build_probe_solver(self):
        if not self.batch_probes_enabled():
            return None
        # the sequential probe aborts per-call while capacity is still
        # materializing; skipping the batch reproduces that verdict
        # through the unchanged sequential path
        if self.has_uninitialized_capacity():
            return None
        # device breaker open: don't even pay the snapshot + Scheduler
        # + encode setup for a batch that would only re-fault — the
        # sequential probes' own solves ride the resilience ladder to
        # whichever rung still works (usable() re-checks post-build
        # for the race where the breaker opens during setup)
        from karpenter_tpu.solver import resilience

        if resilience.shared().breaker("device").is_open():
            log.warning(
                "device breaker open; skipping batched probe setup for "
                "this ladder")
            return None
        from karpenter_tpu.solver.consolidation_batch import BatchProbeSolver

        try:
            # the retained seam serves the ladder's shared snapshot;
            # the batch never mutates its rows (lanes are evaluated
            # against encoded arrays), so a whole probe ladder costs
            # zero re-copies
            snapshot, input_cache = self.fleet_seam.fleet_snapshot()
            solver = BatchProbeSolver(
                pools_with_types=self.provisioner.ready_pools_with_types(),
                snapshot=snapshot,
                daemonsets=self.cluster.daemonsets(),
                cluster_pods=self.kube.pods(),
                pending_pods=self.provisioner.get_pending_pods(),
                options=self.options,
                kube=self.kube,
                clock=self.clock,
                compat_cache=self.provisioner.encode_cache,
                existing_input_cache=input_cache,
            )
        except Exception:
            log.exception("probe batch setup failed; probing sequentially")
            return None
        return solver if solver.usable() else None

    def _probe_primer(self, lane_specs: list) -> "_ProbePrimer":
        return _ProbePrimer(self, lane_specs)

    # -- candidates (helpers.go:174-193) ---------------------------------------

    def get_candidates(self, reason: str, now: float) -> list[Candidate]:
        out = []
        # allowance memoized per SCAN: disruptions_allowed walks the
        # namespace's whole pod population per selecting PDB, and a
        # read-only scan over a fixed population sees one answer per
        # PDB — per-pod recomputation was the dominant scan cost
        pdb = PdbLimits(self.kube, memoize_allowance=True)
        # price lookups hit a per-round offering index instead of
        # re-fetching the full catalog per candidate (O(candidates ×
        # catalog) otherwise; the reference resolves prices from the
        # instance types already fetched for the scheduling run)
        self._price_index = {}
        protected = self.queue.protected_claim_names()
        # retained candidate cores (ISSUE 15): the per-node pod list,
        # PDB verdicts, labels and price survive across scans and
        # methods, refreshed only for keys the seam's watch dirt
        # names; every Nth scan is an identity audit against the
        # from-scratch derivation
        self.fleet_seam.sync()
        self._cand_scans += 1
        audit_every = self.fleet_seam.audit_every
        self._audit_scan = (
            audit_every > 0 and self._cand_scans % audit_every == 0
        )
        from karpenter_tpu.state.retained import retained_enabled

        self._retain_cores = retained_enabled()
        self._core_hits = self._core_rebuilds = 0
        catalog_fp = self._candidate_catalog_fp()
        for node in self.cluster.nodes():
            candidate = self._build_candidate(node, reason, pdb, now,
                                              protected,
                                              catalog_fp=catalog_fp)
            if candidate is not None:
                out.append(candidate)
        self._audit_scan = False
        # metric increments batched per scan (a per-node inc was
        # measurable against the scan wall the cores exist to shrink)
        if self._core_hits:
            DISRUPTION_SNAPSHOT.inc(
                {"outcome": "hit"}, value=float(self._core_hits)
            )
            self.fleet_seam.hits += self._core_hits
        if self._core_rebuilds:
            DISRUPTION_SNAPSHOT.inc(
                {"outcome": "rebuild"}, value=float(self._core_rebuilds)
            )
            self.fleet_seam.rebuilds += self._core_rebuilds
        return out

    def _candidate_catalog_fp(self):
        """Cheap catalog identity stamping the cores' cached prices —
        a reprice/overlay/ICE flip re-resolves them, nothing else
        does. None (fetch hiccup) disables price caching this scan."""
        try:
            from karpenter_tpu.solver.incremental import (
                catalog_fingerprint,
            )

            return catalog_fingerprint(
                self.provisioner.ready_pools_with_types()
            )
        except Exception:
            return None

    def _candidate_core(
        self, node: StateNode, pdb: PdbLimits, catalog_fp,
    ) -> "_CandidateCore":
        """The retained expensive half of one node's candidate scan:
        pod fetches, PDB matching, the label merge and the price
        lookup. Stamped with the seam's dirt generations; a stale (or
        audit-scan) core rebuilds from scratch, and an audit mismatch
        invalidates every core."""
        key = _state_node_key(node)
        ver = self.fleet_seam.node_version(key) + (
            self.fleet_seam.pdb_epoch,
        )
        core = self._cand_cores.get(key) if key else None
        retain = self._retain_cores and bool(key)
        if core is not None and core.ver == ver and not self._audit_scan:
            self._core_hits += 1
            return core
        fresh = _CandidateCore(ver=ver, pod_info=[],
                               labels=dict(node.labels()))
        for pod_key in sorted(node.pod_keys):
            pod = self.kube.get_pod(*pod_key.split("/", 1))
            if pod is None:
                continue
            fresh.pod_info.append((
                pod,
                pod.owner_kind() == "DaemonSet",
            ))
        if core is not None and core.ver == ver and self._audit_scan:
            # decision-identity oracle: the retained core must match
            # the from-scratch derivation field for field
            DISRUPTION_SNAPSHOT.inc({"outcome": "audit"})
            same = (
                core.labels == fresh.labels
                and len(core.pod_info) == len(fresh.pod_info)
                and all(
                    a[0] is b[0] and a[1] == b[1]
                    for a, b in zip(core.pod_info, fresh.pod_info)
                )
            )
            if not same:
                DISRUPTION_SNAPSHOT.inc({"outcome": "divergence"})
                log.error(
                    "retained candidate core for %s diverged from the "
                    "from-scratch scan; invalidating candidate cores",
                    key,
                )
                self._cand_cores.clear()
            else:
                fresh.price_fp = core.price_fp
                fresh.price = core.price
        else:
            self._core_rebuilds += 1
        if retain:
            self._cand_cores[key] = fresh
        return fresh

    def _build_candidate(
        self, node: StateNode, reason: str, pdb: PdbLimits, now: float,
        protected: frozenset = frozenset(),
        catalog_fp=None,
    ) -> Optional[Candidate]:
        # Every node the scan rejects for a POLICY reason gets a
        # structured verdict in the explain plane (`kept:<reason>`) —
        # the answer to "why is this node still here". Mechanical
        # skips (deleting, unmanaged, static pools, not-drifted in a
        # drift scan) stay silent: they are the normal state of most
        # of the fleet, not a decision worth a record.
        if node.deleting():
            return None
        if node.nominated(now):
            explain.note_candidate(node.name, explain.KEPT_NOMINATED)
            return None
        disruptable_err = node.validate_node_disruptable()
        if disruptable_err is not None:
            if "do-not-disrupt" in disruptable_err:
                explain.note_candidate(
                    node.name, explain.KEPT_DO_NOT_DISRUPT, source="node"
                )
            return None
        claim = node.node_claim
        if claim is None:
            return None
        if claim.metadata.name in protected:
            return None  # an in-flight command's replacement
        from karpenter_tpu.apis.v1.nodeclaim import COND_INTERRUPTED

        if claim.status_conditions.is_true(COND_INTERRUPTED):
            # holding a cloud interruption notice: the interruption
            # controller owns this node's replacement — a concurrent
            # consolidation command would race the drain
            explain.note_candidate(node.name, explain.KEPT_INTERRUPTED)
            return None
        pool = self.kube.get_node_pool(node.nodepool_name())
        if pool is None or pool.is_static():
            return None
        # method eligibility via conditions
        if reason == REASON_EMPTY or reason == REASON_UNDERUTILIZED:
            if not claim.status_conditions.is_true(COND_CONSOLIDATABLE):
                explain.note_candidate(
                    node.name, explain.KEPT_NOT_CONSOLIDATABLE, weak=True
                )
                return None
            if (
                reason == REASON_UNDERUTILIZED
                and pool.spec.disruption.consolidation_policy == CONSOLIDATION_WHEN_EMPTY
            ):
                return None
        if reason == REASON_DRIFTED and not claim.status_conditions.is_true(COND_DRIFTED):
            return None
        # Drift is the EVENTUAL disruption class (drift.go:111): with a
        # TerminationGracePeriod on the claim, pod-block errors — the
        # do-not-disrupt annotation and zero-budget PDBs — do NOT
        # disqualify the candidate (types.go:115-121), because the
        # drain is bounded: termination force-completes at the TGP
        # deadline. Consolidation/emptiness are GRACEFUL and always
        # respect blocking pods.
        eventual = (
            reason == REASON_DRIFTED
            and claim.spec.termination_grace_period is not None
        )
        # pods must be evictable (ValidatePodsDisruptable
        # statenode.go:234): the do-not-disrupt check covers every
        # ACTIVE pod (mirror and daemonset pods may block with the
        # annotation too); the PDB check self-gates on evictability
        # (mirror pods bypass it, daemonset pods do not)
        core = self._candidate_core(node, pdb, catalog_fp)
        pods = []
        for pod, is_daemon in core.pod_info:
            # terminal-state, annotation and PDB-budget reads stay
            # LIVE per scan (the budget depends on OTHER nodes' pod
            # health; the allowance memo on `pdb` bounds its cost to
            # once per PDB per scan) — the store lookups and label
            # merges are what the core retains
            if pod.is_terminal() or pod.is_terminating():
                continue
            if (
                pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION)
                == "true"
                and not eventual
            ):
                explain.note_candidate(
                    node.name, explain.KEPT_DO_NOT_DISRUPT, pod=pod.key
                )
                return None
            if pdb.can_evict(pod) is not None and not eventual:
                explain.note_candidate(
                    node.name, explain.KEPT_PDB_BLOCKED, pod=pod.key
                )
                return None
            if is_daemon:
                continue
            pods.append(pod)
        labels = core.labels
        if catalog_fp is not None and core.price_fp == catalog_fp:
            price = core.price
        else:
            price = self._node_price(labels)
            if catalog_fp is not None:
                core.price_fp = catalog_fp
                core.price = price
        if price is None:
            if reason == REASON_UNDERUTILIZED:
                # unpriceable candidates are excluded from consolidation
                # rather than priced at 0, which would poison the
                # cheaper-than comparison (getCandidatePrices errors
                # skip the candidate)
                log.warning(
                    "no offering price for node %s; skipping candidate",
                    node.name,
                )
                explain.note_candidate(node.name, explain.KEPT_UNPRICED)
                return None
            # emptiness/drift never price-compare: a candidate with a
            # missing/unresolvable instance type is still disruptable
            # (types.go:107-108 resolves the type best-effort)
            price = 0.0
        lifetime_factor = 1.0
        from karpenter_tpu.utils.duration import parse_duration

        lifetime = parse_duration(claim.spec.expire_after)
        if lifetime:
            remaining = max(0.0, 1.0 - (now - claim.metadata.creation_timestamp) / lifetime)
            lifetime_factor = remaining
        return Candidate(
            state_node=node,
            node_pool=pool,
            reschedulable_pods=pods,
            instance_type_name=labels.get(INSTANCE_TYPE_LABEL, ""),
            capacity_type=labels.get(CAPACITY_TYPE_LABEL, ""),
            zone=labels.get(TOPOLOGY_ZONE_LABEL, ""),
            price=price,
            disruption_cost=sum(pod_disruption_cost(p) for p in pods) * lifetime_factor,
        )

    def offering_price_index(
        self, pool_name: str, available_only: bool = False
    ) -> dict[tuple[str, str, str], float]:
        """(instance-type, zone, capacity-type) -> price for one pool's
        current catalog. Shared by candidate pricing and execution-time
        validation; fetch errors raise so callers decide whether the
        failure is skippable (candidate pricing) or retryable
        (validation)."""
        prices: dict[tuple[str, str, str], float] = {}
        pool = self.kube.get_node_pool(pool_name)
        if pool is None:
            return prices
        for it in self.cloud.get_instance_types(pool):
            for off in it.offerings:
                if available_only and not off.available:
                    continue
                prices[(it.name, off.zone, off.capacity_type)] = off.price
        return prices

    def _node_price(self, labels: dict[str, str]) -> Optional[float]:
        it_name = labels.get(INSTANCE_TYPE_LABEL, "")
        zone = labels.get(TOPOLOGY_ZONE_LABEL, "")
        captype = labels.get(CAPACITY_TYPE_LABEL, "")
        pool_name = labels.get(NODEPOOL_LABEL, "")
        index = self._price_index
        if pool_name not in index:
            try:
                index[pool_name] = self.offering_price_index(pool_name)
            except Exception as err:
                log.warning("price catalog fetch failed for pool %s: %s",
                            pool_name, err)
                index[pool_name] = {}
        return index[pool_name].get((it_name, zone, captype))

    # -- budgets (helpers.go:231-280) ------------------------------------------

    def budget_mapping(self, reason: str, now: float,
                       exclude_names: frozenset = frozenset()) -> dict[str, int]:
        """helpers.go BuildDisruptionBudgetMapping: the TOTAL counts
        only managed + initialized nodes whose claims are not
        InstanceTerminating (uninitialized replacements padding the
        percentage denominator would allow extra disruption of active
        nodes); NotReady and marked/deleting nodes then CONSUME
        allowance, floored at zero. `exclude_names` are nodes whose
        disruption is the QUESTION being asked (an in-flight command's
        own candidates at validation time): they count in the total
        but never as consumers, so a command can't collide with its
        own marks."""
        from karpenter_tpu.apis.v1.nodeclaim import COND_INSTANCE_TERMINATING

        num: dict[str, int] = {}
        disrupting: dict[str, int] = {}
        for n in self.cluster.nodes():
            if not n.managed() or not n.initialized():
                continue
            claim = n.node_claim
            if claim is not None and claim.status_conditions.is_true(
                COND_INSTANCE_TERMINATING
            ):
                continue
            pool_name = n.nodepool_name()
            if not pool_name:
                continue
            num[pool_name] = num.get(pool_name, 0) + 1
            if n.name in exclude_names:
                continue
            not_ready = n.node is not None and not n.node.is_ready()
            if not_ready or n.deleting():
                disrupting[pool_name] = disrupting.get(pool_name, 0) + 1
        out = {}
        for pool in self.kube.node_pools():
            name = pool.metadata.name
            allowed = pool.must_get_allowed_disruptions(
                now, num.get(name, 0), reason
            )
            out[name] = max(0, allowed - disrupting.get(name, 0))
        return out

    def _budget_filter(
        self, candidates: list[Candidate], budgets: dict[str, int]
    ) -> list[Candidate]:
        taken: dict[str, int] = {}
        out = []
        for c in candidates:
            pool = c.node_pool.metadata.name
            if taken.get(pool, 0) < budgets.get(pool, 0):
                taken[pool] = taken.get(pool, 0) + 1
                out.append(c)
            else:
                explain.note_candidate(
                    c.state_node.name, explain.KEPT_BUDGET,
                    weak=True, pool=pool, allowed=budgets.get(pool, 0),
                )
        return out

    # -- simulation (helpers.go:52-143) ----------------------------------------

    def simulate_scheduling(
        self, candidates: Sequence[Candidate], objective: str = "ffd",
        include_pending: bool = True,
    ) -> tuple[SchedulerResults, bool]:
        """Re-run the scheduler with candidates removed. Returns
        (results, all_pods_scheduled). `include_pending=False` solves
        the candidates' pods alone — execution-time validation uses it
        so an unrelated pending pod forcing a new node can't be
        mistaken for the command going stale.

        The snapshot-once/probe-many path: while a search method has a
        primed probe cache active (multi-node's prefix ladder, the
        single-node rotation, drift's ranked scan — all evaluated as
        lanes of ONE batched device solve against ONE shared
        `deep_copy_nodes()` snapshot), a probe for a cached candidate
        subset is a dict lookup; only cache misses (lanes the batch
        could not reproduce exactly) pay the per-probe deep copy +
        Scheduler below."""
        cache = self._get_probe_cache()
        if cache is not None and objective == "ffd" and include_pending:
            thunk = cache.get(frozenset(c.state_node.name for c in candidates))
            # capacity that started materializing AFTER the batch's
            # snapshot must abort a cached probe exactly as the
            # sequential path's per-probe guard would — the check is a
            # cheap live-state scan, so cached verdicts keep the same
            # uninitialized-node semantics as fresh ones
            if thunk is not None and not self.has_uninitialized_capacity():
                # lazily decoded: the batch shipped every lane in one
                # device fetch, but per-lane decode runs only for the
                # subsets the search actually consults. A lane that
                # decodes to None needed sequential-only machinery —
                # fall through to the per-probe path below.
                hit = thunk()
                if hit is not None:
                    return hit
        deleting_names = {c.state_node.name for c in candidates}
        # the retained seam serves the snapshot rows + input cache; the
        # Scheduler below commits displaced pods onto the served rows,
        # so the touched keys are reported back (note_mutated) and
        # re-copied before the next serve
        rows, input_cache = self.fleet_seam.fleet_snapshot()
        snapshot = []
        for node in rows:
            if node.name in deleting_names:
                continue
            # uninitialized-node guard (helpers.go:122-141): abort while
            # other capacity is still materializing — its eventual pod
            # load is unknown, so a consolidation decision against it
            # would be built on sand
            if node.managed() and not node.initialized() and not node.deleting():
                return (
                    SchedulerResults(new_node_plans=[], existing_assignments={}),
                    False,
                )
            snapshot.append(node)
        results, all_ok = self._simulate_on_snapshot(
            candidates, snapshot, objective, include_pending,
            existing_input_cache=input_cache,
        )
        self.fleet_seam.note_mutated(results.existing_assignments.keys())
        return results, all_ok

    def has_uninitialized_capacity(
        self, exclude_names: Optional[set] = None
    ) -> bool:
        """True while any managed node outside `exclude_names` is still
        materializing — the condition under which the uninitialized-node
        guard aborts a simulation. Execution-time validation checks it
        FIRST so the transient abort maps to retry, not rollback."""
        exclude = exclude_names or set()
        return any(
            node.managed() and not node.initialized() and not node.deleting()
            for node in self.cluster.nodes()
            if node.name not in exclude
        )

    def _simulate_on_snapshot(
        self, candidates: Sequence[Candidate], snapshot: list,
        objective: str, include_pending: bool,
        existing_input_cache: Optional[dict] = None,
    ) -> tuple[SchedulerResults, bool]:
        pods = [p for c in candidates for p in c.reschedulable_pods]
        pending = self.provisioner.get_pending_pods() if include_pending else []
        scheduler = Scheduler(
            existing_input_cache=existing_input_cache,
            pools_with_types=self.provisioner.ready_pools_with_types(),
            state_nodes=snapshot,
            daemonsets=self.cluster.daemonsets(),
            cluster_pods=self.kube.pods(),
            allow_reserved=self.options.feature_gates.reserved_capacity,
            min_values_policy=self.options.min_values_policy,
            ignore_dra_requests=self.options.ignore_dra_requests,
            metrics_controller="disruption",
            kube=self.kube,
            clock=self.clock,
            objective=objective,
            # share the provisioner's encoder cache: simulation rounds
            # re-encode the same pod shapes against the same catalog,
            # so only genuinely new signatures pay compat evaluation
            compat_cache=self.provisioner.encode_cache,
        )
        results = scheduler.solve(pods + pending)
        scheduled_keys = {
            p.key for plan in results.new_node_plans for p in plan.pods
        } | {p.key for ps in results.existing_assignments.values() for p in ps}
        all_ok = all(p.key in scheduled_keys for p in pods)
        if all_ok and pods and pending:
            # priority-aware disruption (ISSUE 8): a command must not
            # retire capacity while a PENDING pod of strictly higher
            # priority than the pods it would displace is left
            # capacity-unschedulable by the very same simulation —
            # whether by catalog capacity (the solve's own error) or by
            # NodePool limits (enforced at claim creation; simulated
            # here the way the provisioner's admission loop does). The
            # cluster would be churning low-priority workload for
            # price while outranking demand starves. Uniform-priority
            # clusters (everything 0) are unaffected: 0 > 0 never
            # holds, and a pending pod at the candidates' own priority
            # was unschedulable with the candidates present too.
            from karpenter_tpu.provisioning.priority import (
                NO_CAPACITY_ERROR,
            )

            floor = min(p.spec.priority for p in pods)
            pending_by_key = {p.key: p for p in pending}
            starved_keys = {
                key for key, error in results.errors.items()
                if error == NO_CAPACITY_ERROR
            }
            for plan in self.provisioner._plans_over_limits(
                results.new_node_plans
            ):
                starved_keys.update(p.key for p in plan.pods)
            for key in sorted(starved_keys):
                starved = pending_by_key.get(key)
                if starved is not None and starved.spec.priority > floor:
                    log.info(
                        "disruption simulation vetoed: pending pod %s "
                        "(priority %d) would stay unschedulable while "
                        "pods of priority %d are displaced",
                        key, starved.spec.priority, floor,
                    )
                    for c in candidates:
                        explain.note_candidate(
                            c.state_node.name, explain.KEPT_PRIORITY_VETO,
                            starved_pod=key,
                            starved_priority=int(starved.spec.priority),
                            displaced_priority=int(floor),
                        )
                    all_ok = False
                    break
        return results, all_ok

    # -- consolidation decision (consolidation.go:137-311) ---------------------

    def compute_consolidation(
        self, candidates: list[Candidate]
    ) -> Optional[Command]:
        # dual-based probe pruning (ISSUE 12): while a search ladder
        # has a primed dual certificate, a candidate set whose pods'
        # certified dual value exceeds its price — even after every
        # other node's free capacity and the reservation budget absorb
        # their share — cannot be replaced strictly cheaper, so the
        # probe could only return None. Skipping it is
        # decision-identical (weak duality, conservative margin) and
        # saves the simulation outright.
        pruner = self._get_probe_pruner()
        if pruner is not None and self.probe_pruning_enabled():
            try:
                pruned = pruner.cannot_pay(candidates)
            except Exception:
                log.exception("probe pruning failed; probing")
                pruned = False
            if pruned:
                from karpenter_tpu import tracing
                from karpenter_tpu.metrics.store import SOLVER_PROBE_PRUNED

                SOLVER_PROBE_PRUNED.inc()
                tracing.add_event(
                    "probe_pruned", candidates=len(candidates)
                )
                # the certificate IS the explanation — "kept because
                # no replacement can beat $X/hr", with the weak-
                # duality numbers attached (λ'·d bound vs price)
                cert = getattr(pruner, "last", None) or {}
                for c in candidates:
                    explain.note_candidate(
                        c.state_node.name, explain.KEPT_LP_PRUNE, **cert
                    )
                return None
        results, all_ok = self.simulate_scheduling(candidates)
        if not all_ok:
            for c in candidates:
                explain.note_candidate(
                    c.state_node.name, explain.KEPT_SIMULATION, weak=True
                )
            return None
        if len(results.new_node_plans) > 1:
            for c in candidates:
                explain.note_candidate(
                    c.state_node.name, explain.KEPT_NEEDS_MULTIPLE,
                    weak=True, replacement_nodes=len(results.new_node_plans),
                )
            return None
        current_price = sum(c.price for c in candidates)
        if not results.new_node_plans:
            return Command(reason=REASON_EMPTY if not any(
                c.reschedulable_pods for c in candidates
            ) else REASON_UNDERUTILIZED, candidates=candidates, results=results)
        plan = results.new_node_plans[0]
        # replacement must be strictly cheaper: filter offerings by
        # price — spot offerings judged at their interruption-penalized
        # effective price (cloudprovider.types.effective_price), so
        # consolidation stops churning workloads onto capacity the
        # interruption regime is about to reclaim
        cheaper = [o for o in plan.offerings if effective_price(o) < current_price]
        if not cheaper:
            cheapest = (
                min(effective_price(o) for o in plan.offerings)
                if plan.offerings else None
            )
            for c in candidates:
                explain.note_candidate(
                    c.state_node.name, explain.KEPT_NOT_CHEAPER,
                    current_price=round(current_price, 6),
                    replacement_price=(
                        round(cheapest, 6) if cheapest is not None else None
                    ),
                )
            return None
        all_spot = all(c.capacity_type == CAPACITY_TYPE_SPOT for c in candidates)
        # the launch resolves to the cheapest surviving offering (raw
        # price — what the provider actually picks), so THAT offering's
        # capacity type is the replacement's: a ~free reserved offering
        # beating the spot candidates must route through the normal
        # path, not the spot-to-spot gate
        replacement_ct = min(cheaper, key=lambda o: o.price).capacity_type
        spot_replacement = replacement_ct == CAPACITY_TYPE_SPOT
        if all_spot and spot_replacement:
            # spot-to-spot (consolidation.go:233-311): gated; replacement
            # forced to spot; single-node additionally demands >=15
            # cheaper instance types and truncates the launch set to 15
            if not self.options.feature_gates.spot_to_spot_consolidation:
                self._note_spot_gated(candidates, "feature-gate-off")
                return None
            spot_offerings = [
                o for o in cheaper if o.capacity_type == CAPACITY_TYPE_SPOT
            ]
            type_names = []
            for o in spot_offerings:
                for it in plan.instance_types:
                    if o in it.offerings and it.name not in type_names:
                        type_names.append(it.name)
            if not type_names:
                self._note_spot_gated(candidates, "no-cheaper-spot-types")
                return None
            if len(candidates) == 1:
                if len(type_names) < SPOT_TO_SPOT_MIN_TYPES:
                    self._note_spot_gated(
                        candidates,
                        f"{len(type_names)}<{SPOT_TO_SPOT_MIN_TYPES}"
                        " flexible types",
                    )
                    return None
                type_names = type_names[:SPOT_TO_SPOT_MIN_TYPES]
            keep = set(type_names)
            plan.instance_types = [it for it in plan.instance_types if it.name in keep]
            plan.offerings = [
                o for o in spot_offerings
                if any(o in it.offerings for it in plan.instance_types)
            ]
        else:
            # the cheaper-than filter assumed the cheapest variant
            # launches, so when several capacity types remain and one
            # of them is spot, pin the replacement to the capacity type
            # the launch resolves to (consolidation.go:215-223 pins
            # OD -> [OD, spot] to spot; a cheaper reserved offering
            # pins to reserved the same way)
            captypes = {o.capacity_type for o in cheaper}
            if CAPACITY_TYPE_SPOT in captypes and len(captypes) > 1:
                cheaper = [
                    o for o in cheaper if o.capacity_type == replacement_ct
                ]
            plan.offerings = cheaper
            names = set()
            for o in cheaper:
                for it in plan.instance_types:
                    if o in it.offerings:
                        names.add(it.name)
            plan.instance_types = [it for it in plan.instance_types if it.name in names]
        if not plan.instance_types:
            for c in candidates:
                explain.note_candidate(
                    c.state_node.name, explain.KEPT_NOT_CHEAPER, weak=True
                )
            return None
        plan.price = min(o.price for o in plan.offerings)
        return Command(reason=REASON_UNDERUTILIZED, candidates=candidates, results=results)

    @staticmethod
    def _note_spot_gated(candidates: list[Candidate], why: str) -> None:
        for c in candidates:
            explain.note_candidate(
                c.state_node.name, explain.KEPT_SPOT_GATED, gate=why
            )

    # -- methods ---------------------------------------------------------------

    def emptiness(self, now: float) -> Optional[Command]:
        """Delete empty consolidatable nodes (emptiness.go:42-113)."""
        candidates = [
            c for c in self.get_candidates(REASON_EMPTY, now) if not c.reschedulable_pods
        ]
        if not candidates:
            return None
        budgets = self.budget_mapping(REASON_EMPTY, now)
        allowed = self._budget_filter(candidates, budgets)
        if not allowed:
            return None
        return Command(reason=REASON_EMPTY, candidates=allowed)

    def drift(self, now: float) -> Optional[Command]:
        """Replace drifted nodes (drift.go:55-115); one at a time. The
        ranked candidates are simulated as lanes of one batched probe
        solve; the scan below consults the primed verdicts in order."""
        candidates = self.get_candidates(REASON_DRIFTED, now)
        if not candidates:
            return None
        budgets = self.budget_mapping(REASON_DRIFTED, now)
        allowed = self._budget_filter(candidates, budgets)
        # empty drifted nodes first (no disruption at all)
        allowed.sort(key=lambda c: (len(c.reschedulable_pods), -c.disruption_cost))
        primer = self._probe_primer([[c] for c in allowed])
        self._set_probe_cache({})
        try:
            for candidate in allowed:
                primer.ensure([candidate])
                results, ok = self.simulate_scheduling([candidate])
                if ok:
                    return Command(reason=REASON_DRIFTED, candidates=[candidate],
                                   results=results)
            return None
        finally:
            self._set_probe_cache(None)
            self._set_probe_pruner(None)

    def global_repack_consolidation(self, now: float) -> Optional[Command]:
        """One cost-objective re-solve of the whole candidate set — the
        batched-device generalization of the reference's prefix binary
        search (multinodeconsolidation.go:116-169). Where the prefix
        search can only merge a disruption-cost-ordered prefix into a
        SINGLE replacement node, this method hands every budget-allowed
        candidate's workload to the LP cost objective at once and keeps
        the resulting multi-node plan when the replacement fleet is
        strictly cheaper than the candidates it retires. The command is
        re-validated against fresh state before execution like every
        other (validation.go:152-280)."""
        candidates = self.get_candidates(REASON_UNDERUTILIZED, now)
        if len(candidates) < 2:
            return None
        candidates.sort(key=lambda c: c.disruption_cost)
        budgets = self.budget_mapping(REASON_UNDERUTILIZED, now)
        candidates = self._budget_filter(candidates, budgets)
        candidates = candidates[:GLOBAL_REPACK_MAX_CANDIDATES]
        if len(candidates) < 2:
            return None
        results, all_ok = self.simulate_scheduling(candidates, objective="cost")
        if not all_ok:
            return None
        current_price = sum(c.price for c in candidates)
        all_spot = all(
            c.capacity_type == CAPACITY_TYPE_SPOT for c in candidates
        )
        for plan in results.new_node_plans:
            captypes = {o.capacity_type for o in plan.offerings}
            if CAPACITY_TYPE_SPOT in captypes:
                # the launch resolves to the cheapest surviving offering
                # (raw price — what the provider actually picks), so
                # THAT capacity type is the replacement's: a ~free
                # reserved offering beating the spot candidates routes
                # through the normal path, exactly as in the
                # single-node path above
                replacement_ct = min(
                    plan.offerings, key=lambda o: o.price
                ).capacity_type
                # spot-to-spot churn is gated (consolidation.go:233-311);
                # the >=2-candidate set is exempt from the 15-type floor
                # exactly as the reference's multi-node path is
                if (
                    all_spot
                    and replacement_ct == CAPACITY_TYPE_SPOT
                    and not self.options.feature_gates.spot_to_spot_consolidation
                ):
                    return None
                if len(captypes) > 1:
                    # the price estimate assumes the cheapest offering
                    # launches, so pin the plan to its capacity type
                    # (consolidation.go:215-223)
                    plan.offerings = [
                        o for o in plan.offerings
                        if o.capacity_type == replacement_ct
                    ]
                    names = {
                        it.name for it in plan.instance_types
                        if any(o in it.offerings for o in plan.offerings)
                    }
                    plan.instance_types = [
                        it for it in plan.instance_types if it.name in names
                    ]
                    if not plan.instance_types:
                        return None
            plan.price = min(o.price for o in plan.offerings)
        # decide on interruption-penalized prices (spot offerings carry
        # their expected reclaim cost) while plan.price stays the raw
        # launch price
        new_price = sum(
            min(effective_price(o) for o in p.offerings)
            for p in results.new_node_plans
        )
        if new_price >= current_price:
            return None
        # Price-prune each plan's fallback offerings the way
        # compute_consolidation prunes its single replacement's
        # (consolidation.go:190-214): a launch can land on any offering
        # the claim keeps, so distribute the saving slack across plans
        # and cap every plan's offerings below its share — then even if
        # EVERY plan falls back to its most expensive surviving
        # offering, the total stays strictly under the retired price.
        plans = results.new_node_plans
        if plans:
            share = (current_price - new_price) / len(plans)
            for plan in plans:
                # cap in the same effective-price domain the decision
                # used, so spot fallbacks keep their reclaim penalty
                cap = min(effective_price(o) for o in plan.offerings) + share
                plan.offerings = [
                    o for o in plan.offerings if effective_price(o) < cap
                ]
                names = {
                    it.name for it in plan.instance_types
                    if any(o in it.offerings for o in plan.offerings)
                }
                plan.instance_types = [
                    it for it in plan.instance_types if it.name in names
                ]
                if not plan.instance_types:
                    return None
        return Command(
            reason=REASON_UNDERUTILIZED, candidates=candidates, results=results
        )

    def multi_node_consolidation(self, now: float) -> Optional[Command]:
        """Binary search the largest prefix replaceable by <=1 node
        (multinodeconsolidation.go:51-225). The WHOLE prefix ladder is
        submitted up front as lanes of one batched device solve (one
        shared snapshot, one encode); the search below then consults
        the primed verdicts, so its control flow — full-prefix probe,
        binary search, non-monotone sweep, wall-clock bound — is
        unchanged while each probe costs a dict lookup instead of a
        snapshot + Scheduler + solve."""
        candidates = self.get_candidates(REASON_UNDERUTILIZED, now)
        candidates.sort(key=lambda c: c.disruption_cost)
        budgets = self.budget_mapping(REASON_UNDERUTILIZED, now)
        candidates = self._budget_filter(candidates, budgets)
        candidates = candidates[:MULTI_NODE_MAX_CANDIDATES]
        if len(candidates) < 2:
            return None
        # minimum prefix is 2: single-node consolidation handles the rest
        # (multinodeconsolidation.go:118-121)
        deadline = self.clock() + MULTI_NODE_TIMEOUT_SECONDS
        primer = self._probe_primer(
            [candidates[:n] for n in range(2, len(candidates) + 1)]
        )
        self._set_probe_cache({})
        try:
            primer.prime_all()
            best = self._multi_node_search(candidates, deadline)
        finally:
            self._set_probe_cache(None)
            self._set_probe_pruner(None)
        if best is not None and len(best.candidates) >= 2:
            if not self._same_type_guard(best):
                # N same-type nodes would churn into one node of their
                # own type with no launchable alternative — anti-churn
                names = {c.instance_type_name for c in best.candidates}
                for c in best.candidates:
                    explain.note_candidate(
                        c.state_node.name, explain.KEPT_SAME_TYPE,
                        instance_type=sorted(names)[0] if names else "",
                    )
                return None
            return best
        return None

    def _multi_node_search(self, candidates: list[Candidate],
                           deadline: float) -> Optional[Command]:
        # The valid-prefix set is NOT monotone: replacing 2 small nodes
        # can cost more than their price while replacing all 3 is
        # cheaper (the shared replacement amortizes). The reference's
        # binary search assumes monotonicity and misses such merges;
        # probe the FULL prefix first (the largest possible saving),
        # fall back to the reference-style binary search, and finish
        # with a descending sweep over prefixes neither covered — all
        # under the method's wall-clock bound.
        best = self.compute_consolidation(candidates)
        if best is not None:
            return best
        lo, hi = 2, len(candidates) - 1
        probed = set()
        timed_out = False
        while lo <= hi:
            if self.clock() > deadline:
                log.warning("multi-node consolidation timed out; "
                            "keeping best command so far")
                self._starved("multi_node_consolidation", len(probed) + 1,
                              hi - lo + 1)
                timed_out = True
                break
            mid = (lo + hi) // 2
            probed.add(mid)
            cmd = self.compute_consolidation(candidates[:mid])
            if cmd is not None:
                best = cmd
                lo = mid + 1
            else:
                hi = mid - 1
        # descending sweep over every prefix LARGER than what the
        # binary search settled on: under non-monotonicity a bigger
        # (more saving) merge can hide above a failing midpoint
        best_n = len(best.candidates) if best is not None else 1
        if not timed_out:
            sweeps = 0
            for n in range(len(candidates) - 1, best_n, -1):
                if n in probed:
                    continue
                if sweeps >= MULTI_NODE_SWEEP_PROBES:
                    break
                if self.clock() > deadline:
                    log.warning("multi-node consolidation timed out "
                                "during prefix sweep; keeping best")
                    self._starved("multi_node_consolidation",
                                  len(probed) + 1 + sweeps,
                                  MULTI_NODE_SWEEP_PROBES - sweeps)
                    break
                sweeps += 1
                cmd = self.compute_consolidation(candidates[:n])
                if cmd is not None:
                    best = cmd
                    break
        return best

    def _same_type_guard(self, best: Command) -> bool:
        """Same-instance-type anti-churn (multinodeconsolidation.go:
        171-225): N nodes of one type must never churn into one node
        of that same type without savings. Judged over the FULL
        surviving option set, not just the first type: a plan whose
        first type differs but whose only launchable offerings belong
        to the candidates' own type would otherwise slip through.
        Mirroring the reference's filterOutSameOrInvalidType, the
        candidates' type is filtered OUT of the replacement options;
        the command survives only if a genuinely different type can
        still launch. Returns False to drop the command."""
        if not best.results or not best.results.new_node_plans:
            return True
        plan = best.results.new_node_plans[0]
        names = {c.instance_type_name for c in best.candidates}
        if len(names) != 1 or not plan.instance_types:
            return True
        keep = [it for it in plan.instance_types if it.name not in names]
        offerings = [
            o for o in plan.offerings
            if any(o in it.offerings for it in keep)
        ]
        if not keep or not offerings:
            return False
        plan.instance_types = keep
        plan.offerings = offerings
        plan.price = min(o.price for o in offerings)
        return True

    def _starved(self, method: str, attempted: int, remaining: int) -> None:
        DISRUPTION_PROBE_STARVATION.inc(
            {"method": method, "count": "attempted"}, value=float(attempted)
        )
        DISRUPTION_PROBE_STARVATION.inc(
            {"method": method, "count": "remaining"}, value=float(remaining)
        )

    def single_node_consolidation(self, now: float) -> Optional[Command]:
        """Try candidates one at a time, round-robining nodepools
        (singlenodeconsolidation.go:56-160). The rotation's visitation
        order is replayed up front so a full budget-allowed round of
        probes can be primed as lanes of one batched solve; the loop
        below then consults the primed verdicts in the same order."""
        candidates = self.get_candidates(REASON_UNDERUTILIZED, now)
        by_pool: dict[str, list[Candidate]] = {}
        for c in candidates:
            by_pool.setdefault(c.node_pool.metadata.name, []).append(c)
        budgets = self.budget_mapping(REASON_UNDERUTILIZED, now)
        for pool_candidates in by_pool.values():
            self._rng.shuffle(pool_candidates)
        # zero-budget pools can never be probed this call (budgets are
        # fixed for the round), so drop them from the rotation up front
        # instead of burning rotation turns popping candidates only to
        # skip them; with no budgeted pool at all, return immediately
        pools = sorted(p for p in by_pool if budgets.get(p, 0) > 0)
        if not pools:
            return None
        idx = 0
        remaining = {p: list(by_pool[p]) for p in pools}
        # materialize the rotation's pop order (a pure replay of the
        # loop below) so the primer batches probes in visitation order
        order: list[Candidate] = []
        sim = {p: list(remaining[p]) for p in pools}
        j = 0
        while any(sim.values()):
            pool = pools[j % len(pools)]
            j += 1
            if sim[pool]:
                order.append(sim[pool].pop())
        primer = self._probe_primer([[c] for c in order])
        self._set_probe_cache({})
        attempted = 0
        deadline = self.clock() + SINGLE_NODE_TIMEOUT_SECONDS
        try:
            while any(remaining.values()):
                if self.clock() > deadline:
                    left = sum(len(v) for v in remaining.values())
                    log.warning("single-node consolidation timed out after "
                                "%d candidates (%d unprobed)", idx, left)
                    # budget-starvation visibility: how far the scan got
                    # vs how much it silently dropped
                    self._starved("single_node_consolidation", attempted,
                                  left)
                    return None
                pool = pools[idx % len(pools)]
                idx += 1
                if not remaining[pool]:
                    continue
                candidate = remaining[pool].pop()
                primer.ensure([candidate])
                attempted += 1
                cmd = self.compute_consolidation([candidate])
                if cmd is not None:
                    return cmd
            return None
        finally:
            self._set_probe_cache(None)
            self._set_probe_pruner(None)

    # -- controller loop (controller.go:121-176) -------------------------------

    def reconcile(self, now: Optional[float] = None) -> Optional[Command]:
        from karpenter_tpu import tracing

        now = time.time() if now is None else now
        if not self.cluster.synced():
            return None
        self._untaint_leftovers()
        for method in (
            self.emptiness,
            self.drift,
            self.global_repack_consolidation,
            self.multi_node_consolidation,
            self.single_node_consolidation,
        ):
            t0 = time.perf_counter()
            with tracing.span(f"disruption.{method.__name__}") as sp:
                command = method(now)
                sp.annotate(decided=command is not None)
            DISRUPTION_EVALUATION_DURATION.observe(
                time.perf_counter() - t0,
                {"method": method.__name__},
            )
            if command is not None:
                # the decided command's candidates get the terminal
                # verdict — overwriting any kept:<reason> an earlier
                # probe of the same ladder recorded for them
                for c in command.candidates:
                    explain.note_candidate(
                        c.state_node.name, explain.VERDICT_CONSOLIDATED,
                        reason=command.reason,
                        replacements=command.replacement_count,
                    )
                # crash window: the disruption decision exists only in
                # memory — a restart recomputes it from cluster state
                from karpenter_tpu.solver import faults as _faults

                _faults.fire("crash_disruption")
                self.queue.start_command(command, now)
                return command
        return None


    def _untaint_leftovers(self) -> None:
        """Un-taint nodes left disrupted by a previous action that is
        no longer in flight — a crashed operator or a rolled-back
        command must not leave capacity unschedulable forever
        (controller.go:136-157)."""
        in_flight = {
            c.state_node.name
            for cmd in self.queue.active
            for c in cmd.candidates
        }
        for node in self.cluster.nodes():
            if node.name in in_flight or node.node is None:
                continue
            # only API-level deletion exempts a node; marked_for_deletion
            # alone is exactly the stale state this pass must recover (a
            # command that died before reaching the queue leaves the mark
            # AND the taint — skipping on it would wedge the node forever)
            if any(
                obj is not None and obj.metadata.deletion_timestamp is not None
                for obj in (node.node, node.node_claim)
            ):
                continue
            if any(
                t.key == DISRUPTED_NO_SCHEDULE_TAINT.key
                for t in node.node.spec.taints
            ):
                node.node.spec.taints = [
                    t for t in node.node.spec.taints
                    if t.key != DISRUPTED_NO_SCHEDULE_TAINT.key
                ]
                self.kube.update(node.node)
                if node.node_claim is not None:
                    node.node_claim.status_conditions.clear(
                        COND_DISRUPTION_REASON
                    )
                node.marked_for_deletion = False


class _ProbePrimer:
    """Feeds a search method's candidate subsets to the batched probe
    solver, filling the engine's probe cache with lazy verdicts. The
    whole spec list primes in ONE call — priming only stages the
    shared problem (one snapshot, one encode); device dispatch and
    decode happen lane by lane as the search consults its probes, so
    offering every subset up front costs nothing extra. Lanes the
    batch cannot reproduce exactly are simply left out of the cache
    (or decode to None later), and the caller's unchanged
    `compute_consolidation` / `simulate_scheduling` probe runs
    sequentially for exactly those.

    The BatchProbeSolver (and its deep-copied snapshot) is acquired
    lazily on the first ensure/prime_all — a search that never probes
    (no candidates, early return) never pays for it.
    """

    def __init__(self, engine: DisruptionEngine, lane_specs: list):
        self.engine = engine
        self.specs = list(lane_specs)
        self.primed = False
        self.dead = not self.specs or not engine.batch_probes_enabled()

    @staticmethod
    def _key(spec) -> frozenset:
        return frozenset(c.state_node.name for c in spec)

    def prime_all(self) -> None:
        if self.dead or self.primed:
            return
        self.primed = True
        solver = self.engine._probe_solver()
        if solver is None:
            self.dead = True
            return
        verdicts = solver.prime(self.specs)
        if verdicts is None:
            # the whole batch is outside the fast path (topology /
            # host-ports / volume limits): probe sequentially
            self.dead = True
            return
        # the staged union problem doubles as the dual-certificate
        # pruner's input; the search's finally clears it with the cache
        self.engine._set_probe_pruner(solver.pruner())
        cache = self.engine._get_probe_cache()
        if cache is None:
            return
        for spec, verdict in zip(self.specs, verdicts):
            if verdict is not None:
                cache[self._key(spec)] = verdict

    def ensure(self, spec) -> None:
        """Make sure `spec`'s lane has been offered to the batch before
        the caller probes it."""
        self.prime_all()


class OrchestrationQueue:
    """Executes commands: taint + mark + replace, then delete once
    replacements initialize (disruption/queue.go:94-370)."""

    def __init__(self, kube: KubeClient, cluster: Cluster, provisioner: Provisioner,
                 recorder=None):
        self.kube = kube
        self.cluster = cluster
        self.provisioner = provisioner
        self.recorder = recorder
        self.active: list[Command] = []
        self.validator = None  # set by DisruptionEngine

    def protected_claim_names(self) -> set[str]:
        """Replacement claims of in-flight commands: OFF LIMITS to the
        candidate scan. Without this, emptiness eats a replace
        command's still-empty replacement the moment its
        consolidatable TTL elapses, the command sees its replacement
        dying and rolls back, and the taint/launch/reap cycle livelocks
        forever (the reference nominates replacement nodes for the
        candidates' pods — disruption.go launchReplacementNodeClaims —
        which keeps them out of the candidate set the same way)."""
        return {
            plan.claim_name
            for command in self.active
            if command.results is not None
            for plan in command.results.new_node_plans
            if plan.claim_name
        }

    def _nominate_replacements(self, command: Command,
                               now: Optional[float] = None) -> None:
        """Refresh the nomination window on every replacement's state
        node while the command is in flight: the candidates' pods are
        already spoken for onto this capacity."""
        if command.results is None:
            return
        for plan in command.results.new_node_plans:
            if not plan.claim_name:
                continue
            state = self.cluster.node_for_key(plan.claim_name)
            if state is None:
                claim = self.kube.get_node_claim(plan.claim_name)
                if claim is not None and claim.status.node_name:
                    state = self.cluster.node_for_name(claim.status.node_name)
            if state is not None:
                state.nominate(now=now)

    def _record(self, command: Command, now: float) -> None:
        """DisruptionTerminating on every candidate (disruption/
        events/events.go:56-63 posts to both the Node and the
        NodeClaim)."""
        if self.recorder is None:
            return
        from karpenter_tpu.events.recorder import Event

        for candidate in command.candidates:
            node = candidate.state_node
            message = f"Disrupting Node: {command.reason}"
            if node.node is not None:
                self.recorder.publish(Event(
                    kind="Node", name=node.node.metadata.name,
                    type="Normal", reason="DisruptionTerminating",
                    message=message,
                ), now=now)
            if node.node_claim is not None:
                self.recorder.publish(Event(
                    kind="NodeClaim", name=node.node_claim.metadata.name,
                    type="Normal", reason="DisruptionTerminating",
                    message=message,
                ), now=now)

    def start_command(self, command: Command, now: Optional[float] = None) -> None:
        from karpenter_tpu import tracing

        with tracing.span(
            "disruption.start", reason=command.reason,
            candidates=len(command.candidates),
        ):
            self._start_command(command, now)

    def _start_command(self, command: Command,
                       now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        command.started_at = now
        self._record(command, now)
        for candidate in command.candidates:
            node = candidate.state_node
            if node.node is not None and not any(
                t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in node.node.spec.taints
            ):
                node.node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
                self.kube.update(node.node)
            if node.node_claim is not None:
                node.node_claim.status_conditions.set_true(
                    COND_DISRUPTION_REASON, reason=command.reason, now=now
                )
            node.marked_for_deletion = True
        if command.results is not None:
            self.provisioner.create_node_claims(command.results, now=now)
            # a plan that produced no claim (e.g. nodepool limits) means
            # replacement capacity will never come: roll back now
            if any(not p.claim_name for p in command.results.new_node_plans):
                log.warning("replacement creation failed; rolling back %s command",
                            command.reason)
                self._rollback(command, now=now)
                return
            self._nominate_replacements(command, now=now)
        self.active.append(command)

    def reconcile(self, now: Optional[float] = None) -> None:
        """waitOrTerminate (queue.go:137-246): once all replacement
        claims are Initialized, re-validate (validation.go:152-280 —
        pods/budgets may have churned since the command was computed)
        and delete the candidates. Commands whose replacements die,
        that fail validation, or that exceed the retry deadline roll
        back — candidates are un-tainted and unmarked."""
        now = time.time() if now is None else now
        still_active = []
        for command in self.active:
            # keep the replacements' nomination windows fresh while
            # the command waits (registration may outlive one window)
            self._nominate_replacements(command, now=now)
            state = self._replacements_state(command)
            if state == "ready":
                from karpenter_tpu import tracing

                with tracing.span(
                    "disruption.validation", reason=command.reason,
                ) as vsp:
                    verdict = self._validate(command, now)
                    vsp.annotate(verdict=verdict)
                if verdict == "retry":
                    # transient failure (e.g. catalog fetch blip): keep
                    # the command active; the COMMAND_TIMEOUT deadline
                    # bounds how long it can retry before rolling back
                    if now - command.started_at > COMMAND_TIMEOUT_SECONDS:
                        log.warning(
                            "disruption command %s rolled back: validation "
                            "still failing transiently after retry deadline",
                            command.reason,
                        )
                        self._rollback(command, now=now)
                    else:
                        still_active.append(command)
                    continue
                if verdict == "invalid":
                    self._rollback(command, now=now)
                    continue
                with tracing.span(
                    "disruption.commit", reason=command.reason,
                    candidates=len(command.candidates),
                ):
                    for candidate in command.candidates:
                        claim = candidate.state_node.node_claim
                        if claim is not None and (
                            claim.metadata.deletion_timestamp is None
                        ):
                            self.kube.delete(claim, now=now)
                            NODECLAIMS_DISRUPTED.inc({
                                "reason": command.reason,
                                "nodepool":
                                    candidate.node_pool.metadata.name,
                            })
            elif state == "failed" or now - command.started_at > COMMAND_TIMEOUT_SECONDS:
                log.warning("disruption command %s rolled back (%s)", command.reason,
                            state)
                self._rollback(command, now=now)
            else:
                still_active.append(command)
        self.active = still_active

    def _validate(self, command: Command, now: float) -> str:
        """'ok' | 'invalid' | 'retry'."""
        if self.validator is None:
            return "ok"
        from karpenter_tpu.disruption.validation import ValidationRetry

        try:
            self.validator.validate_for_execution(command, now)
            return "ok"
        except ValidationRetry as err:
            log.warning("disruption command %s validation deferred: %s",
                        command.reason, err)
            return "retry"
        except Exception as err:
            log.warning("disruption command %s failed validation: %s",
                        command.reason, err)
            return "invalid"

    def _replacements_state(self, command: Command) -> str:
        """ready | waiting | failed."""
        if command.results is None:
            return "ready"
        for plan in command.results.new_node_plans:
            if not plan.claim_name:
                return "failed"
            claim = self.kube.get_node_claim(plan.claim_name)
            if claim is None or claim.metadata.deletion_timestamp is not None:
                # launch failed and the lifecycle controller deleted it
                return "failed"
            if not claim.status_conditions.is_true("Initialized"):
                return "waiting"
        return "ready"

    def _rollback(self, command: Command, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for candidate in command.candidates:
            node = candidate.state_node
            node.marked_for_deletion = False
            if node.node is not None:
                node.node.spec.taints = [
                    t for t in node.node.spec.taints
                    if t.key != DISRUPTED_NO_SCHEDULE_TAINT.key
                ]
                self.kube.update(node.node)
            if node.node_claim is not None:
                node.node_claim.status_conditions.clear(COND_DISRUPTION_REASON)
        # Replacements launched eagerly at start_command (the reference
        # launches only after validation, queue.go:287): on rollback,
        # retire the ones that never took load so a stale decision does
        # not leave paid-for empty capacity waiting for emptiness to
        # collect it. Replacements that host non-daemon pods OR have
        # pending pods nominated onto them are kept — deleting those
        # would disrupt (or strand) workloads.
        if command.results is None:
            return
        for plan in command.results.new_node_plans:
            if not plan.claim_name:
                continue
            claim = self.kube.get_node_claim(plan.claim_name)
            if claim is None or claim.metadata.deletion_timestamp is not None:
                continue
            state_node = self.cluster.node_for_key(plan.claim_name)
            if state_node is None and claim.status.node_name:
                state_node = self.cluster.node_for_name(claim.status.node_name)
            hosts_load = False
            if state_node is not None:
                # the QUEUE's own in-flight protection nominated this
                # replacement (see _nominate_replacements) — that must
                # not read as "pending pods want it" at rollback, so
                # withdraw it before judging real load. (A concurrent
                # provisioner nomination is withdrawn too; its pods
                # re-solve through the batcher when the claim retires.)
                state_node.nominated_until = 0.0
                for pod_key in state_node.pod_keys:
                    pod = self.kube.get_pod(*pod_key.split("/", 1))
                    if pod is None or pod.is_terminal() or pod.is_terminating():
                        continue
                    if pod.owner_kind() == "DaemonSet":
                        continue
                    hosts_load = True
                    break
            if not hosts_load:
                self.kube.delete(claim, now=now)
