"""Grouped first-fit-decreasing bin-packing as a JAX program.

The reference's Scheduler.Solve (scheduler.go:377-675) is a per-pod
loop: try existing nodes, then in-flight nodes, then a new NodeClaim,
each via CanAdd (taints -> requirements -> resources -> re-filter
instance types). Here the same decision procedure runs as a
`lax.while_loop` over *pod groups* with all per-step work vectorized
over (nodes x configs):

  state: node_mask [N, C] bool  -- configs still feasible per node
         node_used [N, R] f32   -- resources committed per node
         node_active [N] bool, node_count
  step:  ok = node_mask & compat[g] & fits  (fits: used <= alloc-req)
         j  = lowest-index feasible node    (stable tie-break)
         k  = per-config capacity floor((alloc - used_j) / req)
         m  = min(remaining, max over ok configs of k)
         place m pods on j, tighten node_mask[j] to configs with k>=m

Placing a whole group at once is equivalent to the reference's per-pod
FFD for identical pods: scanning pods one-by-one fills the first
feasible node until it no longer fits, which is exactly "place
min(remaining, capacity) then spill" under the lowest-index rule.
Existing/in-flight nodes occupy the first `n_existing` node slots with
one-hot pseudo-config masks, so "existing first, then in-flight, then
new" falls out of the index order. New nodes open on the
highest-weight pool whose configs admit the group (configs are ordered
by pool weight at encode time) and are restricted to that pool's
configs, mirroring addToNewNodeClaim (scheduler.go:587-647).

Determinism: every choice is an argmax/argmin over a static axis with
index tie-breaks — bit-reproducible across runs and shardable over the
config axis.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.solver.encode import Encoded

BIG = jnp.float32(3.4e38)
INT_BIG = jnp.int32(2**31 - 1)


@dataclass
class PackResult:
    assign: np.ndarray        # [N, G] int32 pods of group g on node n
    node_mask: np.ndarray     # [N, C] bool configs remaining per node
    node_used: np.ndarray     # [N, R] float32
    node_active: np.ndarray   # [N] bool
    node_count: int
    unschedulable: np.ndarray  # [G] int32 pods that found no placement


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def pack(
    compat: jnp.ndarray,       # [G, C] bool
    group_req: jnp.ndarray,    # [G, R] f32
    group_count: jnp.ndarray,  # [G] i32
    cfg_alloc: jnp.ndarray,    # [C, R] f32
    cfg_pool: jnp.ndarray,     # [C] i32 (-1 for pseudo-configs)
    pool_overhead: jnp.ndarray,  # [P+1, R] f32
    existing_mask: jnp.ndarray,  # [E, C] bool one-hot pseudo-config rows
    existing_used: jnp.ndarray,  # [E, R] f32
    max_nodes: int,
):
    G, C = compat.shape
    R = group_req.shape[1]
    E = existing_mask.shape[0]
    N = max_nodes

    node_mask = jnp.zeros((N, C), bool).at[:E].set(existing_mask)
    node_used = jnp.zeros((N, R), jnp.float32).at[:E].set(existing_used)
    node_active = jnp.zeros((N,), bool).at[:E].set(existing_mask.any(axis=1))
    assign = jnp.zeros((N, G), jnp.int32)
    unschedulable = jnp.zeros((G,), jnp.int32)

    def fits(used, alloc_minus_req):
        # [N, C]: node usage fits under alloc - req for every resource
        return jnp.all(used[:, None, :] <= alloc_minus_req[None, :, :] + 1e-4, axis=-1)

    def capacity(used_j, req):
        # [C]: how many pods of `req` fit on top of used_j per config
        safe_req = jnp.where(req > 0, req, 1.0)
        head = cfg_alloc - used_j[None, :]
        k = jnp.floor((head + 1e-4) / safe_req[None, :])
        k = jnp.where(req[None, :] > 0, k, BIG)
        return jnp.clip(jnp.min(k, axis=-1), 0.0, BIG).astype(jnp.int32)

    def body(state):
        g, remaining, node_mask, node_used, node_active, node_count, assign, unsched = state
        req = group_req[g]
        row = compat[g]

        alloc_minus_req = cfg_alloc - req[None, :]
        ok = node_mask & row[None, :] & fits(node_used, alloc_minus_req)
        feasible = ok.any(axis=1) & node_active
        j_existing = jnp.argmax(feasible)
        has_existing = feasible.any()

        # New-node option: highest-weight pool (lowest pool index) whose
        # configs admit a single pod of this group on a fresh node.
        fresh_ok = row & jnp.all(pool_overhead[cfg_pool] <= alloc_minus_req, axis=-1) & (
            cfg_pool >= 0
        )
        chosen_pool = jnp.min(jnp.where(fresh_ok, cfg_pool, INT_BIG))
        can_open = fresh_ok.any() & (node_count < N)

        def place_existing(args):
            node_mask, node_used, node_active, node_count, assign, remaining = args
            j = j_existing
            k = capacity(node_used[j], req) * ok[j]
            m = jnp.minimum(remaining, jnp.max(k))
            new_mask_j = ok[j] & (k >= m)
            return (
                node_mask.at[j].set(new_mask_j),
                node_used.at[j].add(m.astype(jnp.float32) * req),
                node_active,
                node_count,
                assign.at[j, g].add(m),
                remaining - m,
            )

        def place_new(args):
            node_mask, node_used, node_active, node_count, assign, remaining = args
            j = node_count
            mask = fresh_ok & (cfg_pool == chosen_pool)
            overhead = pool_overhead[chosen_pool]
            k = capacity(overhead, req) * mask
            m = jnp.minimum(remaining, jnp.max(k))
            new_mask_j = mask & (k >= m)
            return (
                node_mask.at[j].set(new_mask_j),
                node_used.at[j].set(overhead + m.astype(jnp.float32) * req),
                node_active.at[j].set(True),
                node_count + 1,
                assign.at[j, g].add(m),
                remaining - m,
            )

        def give_up(args):
            node_mask, node_used, node_active, node_count, assign, remaining = args
            return node_mask, node_used, node_active, node_count, assign, jnp.int32(0)

        branch = jnp.where(has_existing, 0, jnp.where(can_open, 1, 2))
        node_mask, node_used, node_active, node_count, assign, new_remaining = jax.lax.switch(
            branch,
            (place_existing, place_new, give_up),
            (node_mask, node_used, node_active, node_count, assign, remaining),
        )
        unsched = unsched.at[g].add(
            jnp.where(branch == 2, remaining, 0)
        )
        done = new_remaining <= 0
        g = jnp.where(done, g + 1, g)
        next_remaining = jnp.where(
            done, jnp.where(g < G, group_count[jnp.minimum(g, G - 1)], 0), new_remaining
        )
        return (g, next_remaining, node_mask, node_used, node_active, node_count, assign, unsched)

    def cond(state):
        g = state[0]
        return g < G

    init = (
        jnp.int32(0),
        jnp.where(G > 0, group_count[0], 0),
        node_mask,
        node_used,
        node_active,
        jnp.int32(E),
        assign,
        unschedulable,
    )
    state = jax.lax.while_loop(cond, body, init)
    _, _, node_mask, node_used, node_active, node_count, assign, unsched = state
    return assign, node_mask, node_used, node_active, node_count, unsched


def solve_packing(enc: Encoded, max_nodes: int = 0) -> PackResult:
    """Host entry: run the packing kernel on the encoded problem."""
    G, C = enc.compat.shape
    E = enc.n_existing
    if max_nodes <= 0:
        # worst case: every group opens its own node chain
        max_nodes = E + int(enc.group_count.sum())
        max_nodes = min(max_nodes, E + 4096)
    existing_mask = np.zeros((E, C), dtype=bool)
    for ci, cfg in enumerate(enc.configs):
        if cfg.existing_index >= 0:
            existing_mask[cfg.existing_index, ci] = True

    assign, node_mask, node_used, node_active, node_count, unsched = pack(
        jnp.asarray(enc.compat),
        jnp.asarray(enc.group_req),
        jnp.asarray(enc.group_count),
        jnp.asarray(enc.cfg_alloc),
        jnp.asarray(enc.cfg_pool),
        jnp.asarray(enc.pool_overhead),
        jnp.asarray(existing_mask),
        jnp.asarray(enc.existing_used),
        max_nodes=max_nodes,
    )
    return PackResult(
        assign=np.asarray(assign),
        node_mask=np.asarray(node_mask),
        node_used=np.asarray(node_used),
        node_active=np.asarray(node_active),
        node_count=int(node_count),
        unschedulable=np.asarray(unsched),
    )
