"""Grouped first-fit-decreasing bin-packing as a JAX program.

The reference's Scheduler.Solve (scheduler.go:377-675) is a per-pod
loop: try existing nodes, then in-flight nodes, then a new NodeClaim,
each via CanAdd (taints -> requirements -> resources -> re-filter
instance types). Here the same decision procedure runs as a
`lax.while_loop` over *pod groups* with all per-step work vectorized
over (nodes x configs):

  state: node_mask [N, C] bool  -- configs still feasible per node
         node_used [N, R] f32   -- resources committed per node
         node_active [N] bool, node_count
  step:  ok = node_mask & compat[g] & fits  (fits: used <= alloc-req)
         j  = lowest-index feasible node    (stable tie-break)
         k  = per-config capacity floor((alloc - used_j) / req)
         m  = min(remaining, max over ok configs of k)
         place m pods on j, tighten node_mask[j] to configs with k>=m

Placing a whole group at once is equivalent to the reference's per-pod
FFD for identical pods: scanning pods one-by-one fills the first
feasible node until it no longer fits, which is exactly "place
min(remaining, capacity) then spill" under the lowest-index rule.
Existing/in-flight nodes occupy the first `n_existing` node slots with
one-hot pseudo-config masks, so "existing first, then in-flight, then
new" falls out of the index order. New nodes open on the
highest-weight pool whose configs admit the group (configs are ordered
by pool weight at encode time) and are restricted to that pool's
configs, mirroring addToNewNodeClaim (scheduler.go:587-647).

Determinism: every choice is an argmax/argmin over a static axis with
index tie-breaks — bit-reproducible across runs and shardable over the
config axis.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.solver.encode import Encoded

BIG = jnp.float32(3.4e38)
INT_BIG = jnp.int32(2**31 - 1)


@dataclass
class PackResult:
    assign: np.ndarray        # [N, G] int32 pods of group g on node n
    node_mask: np.ndarray     # [N, C] bool configs remaining per node
    node_used: np.ndarray     # [N, R] float32
    node_active: np.ndarray   # [N] bool
    node_count: int
    unschedulable: np.ndarray  # [G] int32 pods that found no placement


@functools.partial(jax.jit, static_argnames=("max_nodes", "mode"))
def pack(
    compat: jnp.ndarray,       # [G, C] bool
    group_req: jnp.ndarray,    # [G, R] f32
    group_count: jnp.ndarray,  # [G] i32
    cfg_alloc: jnp.ndarray,    # [C, R] f32
    cfg_pool: jnp.ndarray,     # [C] i32 (-1 for pseudo-configs)
    pool_overhead: jnp.ndarray,  # [P+1, R] f32
    existing_mask: jnp.ndarray,  # [E, C] bool one-hot pseudo-config rows
    existing_used: jnp.ndarray,  # [E, R] f32
    cfg_price: jnp.ndarray,    # [C] f32 (0 for pseudo-configs)
    max_nodes: int,
    mode: str = "ffd",
    quota: jnp.ndarray | None = None,  # [N, G] i32 per-node group caps
):
    G, C = compat.shape
    R = group_req.shape[1]
    E = existing_mask.shape[0]
    N = max_nodes

    node_mask = jnp.zeros((N, C), bool).at[:E].set(existing_mask)
    node_used = jnp.zeros((N, R), jnp.float32).at[:E].set(existing_used)
    node_active = jnp.zeros((N,), bool).at[:E].set(existing_mask.any(axis=1))
    assign = jnp.zeros((N, G), jnp.int32)
    unschedulable = jnp.zeros((G,), jnp.int32)

    def capacity(used_j, req):
        # [C]: how many pods of `req` fit on top of used_j per config
        safe_req = jnp.where(req > 0, req, 1.0)
        head = cfg_alloc - used_j[None, :]
        k = jnp.floor((head + 1e-4) / safe_req[None, :])
        k = jnp.where(req[None, :] > 0, k, BIG)
        return jnp.clip(jnp.min(k, axis=-1), 0.0, BIG).astype(jnp.int32)

    def body(g, state):
        """One group per iteration: (1) prefix-sum fill across every
        feasible open node in index order — exactly the per-pod
        first-fit outcome — then (2) bulk-open q identical fresh nodes
        for any spill. Exact under FFD: within one group the open-node
        feasibility set never changes, so the per-pod scan would
        produce this same layout. Loop trip count is G, independent of
        pod count."""
        node_mask, node_used, node_active, node_count, assign, unsched = state
        req = group_req[g]
        row = compat[g]
        remaining = group_count[g]

        alloc_minus_req = cfg_alloc - req[None, :]

        # [N, C] capacity for this group's pods; feasibility (>=1 pod
        # fits) falls out of the same tensor, so the dominant N x C x R
        # broadcast happens exactly once per iteration.
        safe_req = jnp.where(req > 0, req, 1.0)
        kmat = jnp.floor(
            (cfg_alloc[None, :, :] - node_used[:, None, :] + 1e-4) / safe_req[None, None, :]
        )
        kmat = jnp.where(req[None, None, :] > 0, kmat, BIG).min(axis=-1)
        kmat = jnp.clip(kmat, 0.0, 2.0e9).astype(jnp.int32)
        ok = node_mask & row[None, :] & (kmat >= 1)
        kmat = kmat * ok
        k = kmat.max(axis=1)
        if quota is not None:
            # LP-planned nodes cap each group's take so complementary
            # resource shapes can share the node (see lp_plan).
            k = jnp.minimum(k, quota[:, g])
        prefix = jnp.cumsum(k) - k
        take = jnp.clip(remaining - prefix, 0, k)
        touched = take > 0
        node_mask = jnp.where(touched[:, None], ok & (kmat >= take[:, None]), node_mask)
        node_used = node_used + take[:, None].astype(jnp.float32) * req[None, :]
        assign = assign.at[:, g].add(take)
        remaining = remaining - take.sum()

        # (2) bulk open on the highest-weight admitting pool
        fresh_ok = row & jnp.all(pool_overhead[cfg_pool] <= alloc_minus_req, axis=-1) & (
            cfg_pool >= 0
        )
        chosen_pool = jnp.min(jnp.where(fresh_ok, cfg_pool, INT_BIG))
        do_open = (remaining > 0) & fresh_ok.any() & (node_count < N)

        def open_nodes(args):
            node_mask, node_used, node_active, node_count, assign, remaining = args
            mask = fresh_ok & (cfg_pool == chosen_pool)
            overhead = pool_overhead[chosen_pool]
            kf = capacity(overhead, req) * mask
            if mode == "cost":
                # Price-aware open: pick the config minimizing $/pod
                # (lowest index on ties) instead of max capacity — the
                # batched analogue of launching the cheapest adequate
                # instance rather than the biggest compatible one.
                ppp = jnp.where(kf >= 1, cfg_price / jnp.maximum(kf, 1), BIG)
                c_star = jnp.argmin(ppp)
                m_star = jnp.maximum(kf[c_star], 1)
            else:
                m_star = jnp.maximum(jnp.max(kf), 1)
            q = jnp.minimum((remaining + m_star - 1) // m_star, N - node_count)
            rem_last = jnp.minimum(m_star, remaining - (q - 1) * m_star)
            idx = jnp.arange(N, dtype=jnp.int32)
            sel_full = (idx >= node_count) & (idx < node_count + q - 1)
            sel_last = idx == node_count + q - 1
            fill = (
                sel_full.astype(jnp.int32) * m_star
                + sel_last.astype(jnp.int32) * rem_last
            )
            node_mask = jnp.where(
                sel_full[:, None], (mask & (kf >= m_star))[None, :],
                jnp.where(sel_last[:, None], (mask & (kf >= rem_last))[None, :], node_mask),
            )
            node_used = jnp.where(
                (sel_full | sel_last)[:, None],
                overhead[None, :] + fill[:, None].astype(jnp.float32) * req[None, :],
                node_used,
            )
            placed = (q - 1) * m_star + rem_last
            return (
                node_mask,
                node_used,
                node_active | sel_full | sel_last,
                node_count + q,
                assign.at[:, g].add(fill),
                remaining - placed,
            )

        node_mask, node_used, node_active, node_count, assign, remaining = jax.lax.cond(
            do_open,
            open_nodes,
            lambda args: args,
            (node_mask, node_used, node_active, node_count, assign, remaining),
        )
        unsched = unsched.at[g].add(jnp.maximum(remaining, 0))
        return (node_mask, node_used, node_active, node_count, assign, unsched)

    state = jax.lax.fori_loop(
        0,
        G,
        body,
        (node_mask, node_used, node_active, jnp.int32(E), assign, unschedulable),
    )
    node_mask, node_used, node_active, node_count, assign, unsched = state
    return assign, node_mask, node_used, node_active, node_count, unsched


@functools.partial(jax.jit, static_argnames=("max_nodes", "mode"))
def pack_flat(*args, max_nodes: int, mode: str = "ffd", quota=None):
    """`pack` with every output concatenated into ONE float32 vector.

    The remote-device transport charges a fixed latency per
    device-to-host fetch of a fresh array (~70ms through the axon
    tunnel); fusing the six outputs into one buffer makes each solve
    pay that latency exactly once.
    """
    assign, node_mask, node_used, node_active, node_count, unsched = pack(
        *args, max_nodes=max_nodes, mode=mode, quota=quota
    )
    return jnp.concatenate(
        [
            assign.astype(jnp.float32).ravel(),
            node_mask.astype(jnp.float32).ravel(),
            node_used.ravel(),
            node_active.astype(jnp.float32).ravel(),
            jnp.asarray([node_count], jnp.float32),
            unsched.astype(jnp.float32).ravel(),
        ]
    )


def _estimate_nodes(enc: Encoded) -> int:
    """Lower bound on fresh nodes: per group, count / best-config
    capacity, summed. The packer retries with a larger axis if the
    estimate proves too tight (cap detection in solve_packing)."""
    launchable = enc.cfg_pool >= 0
    total = 0
    for gi in range(enc.compat.shape[0]):
        mask = enc.compat[gi] & launchable
        count = int(enc.group_count[gi])
        if not mask.any() or count == 0:
            continue
        req = enc.group_req[gi]
        safe_req = np.where(req > 0, req, 1.0)
        per_node = np.floor((enc.cfg_alloc[mask] + 1e-4) / safe_req[None, :])
        per_node = np.where(req[None, :] > 0, per_node, np.inf).min(axis=1)
        best = max(1.0, float(per_node.max()) if per_node.size else 1.0)
        total += -(-count // int(best))
    return total


def solve_packing(
    enc: Encoded, max_nodes: int = 0, mode: str = "ffd", plan=None
) -> PackResult:
    """Host entry: run the packing kernel on the encoded problem.

    With `max_nodes` unset, the node axis is sized from a per-group
    capacity estimate, rounded to 1.5x-spaced buckets so repeated
    solves share compilations, and grown on cap-hit — keeping the
    per-iteration N x C work tight instead of worst-casing N at the
    pod count. An explicit `max_nodes` is honored as a hard cap
    (excess pods report unschedulable).

    With a `plan` (lp_plan.FleetPlan), the planned nodes are pre-opened
    as reserved slots pointing at their launch config column, each with
    the LP's per-node group quotas; the fresh-node path only handles
    rounding spill.
    """
    G, C = enc.compat.shape
    E = enc.n_existing
    n_planned = len(plan.planned_cols) if plan is not None else 0
    reserved = E + n_planned
    existing_mask = np.zeros((reserved, C), dtype=bool)
    for ci, cfg in enumerate(enc.configs):
        if cfg.existing_index >= 0:
            existing_mask[cfg.existing_index, ci] = True
    existing_used = enc.existing_used
    quota = None
    if plan is not None:
        existing_mask[E + np.arange(n_planned), plan.planned_cols] = True
        planned_used = enc.pool_overhead[enc.cfg_pool[plan.planned_cols]]
        existing_used = np.concatenate([enc.existing_used, planned_used], axis=0)
        quota = np.concatenate(
            [
                np.full((E, G), np.iinfo(np.int32).max, np.int32),
                plan.planned_quota,
            ],
            axis=0,
        )

    if max_nodes > 0:
        return _run_pack(enc, existing_mask, existing_used, max_nodes, mode, quota)

    estimate = _estimate_nodes(enc)
    if plan is not None:
        # LP covered the bulk; fresh axis only absorbs rounding spill.
        max_nodes = _bucket(reserved + max(32, estimate // 8 + 8))
    else:
        max_nodes = reserved + max(32, int(1.35 * estimate) + 16)
        max_nodes = _bucket(
            min(max_nodes, reserved + max(64, int(enc.group_count.sum())))
        )
    worst_case = reserved + int(enc.group_count.sum())
    while True:
        result = _run_pack(enc, existing_mask, existing_used, max_nodes, mode, quota)
        capped = (
            result.node_count >= max_nodes and result.unschedulable.sum() > 0
        )
        if not capped or max_nodes > worst_case:
            return result
        max_nodes = _bucket(max_nodes * 2)


def _bucket(n: int) -> int:
    """Round up to the next 1.5x-spaced bucket (>=32) to bound the
    number of distinct compiled shapes while keeping padding waste
    under 50%."""
    out = 32
    while out < n:
        out = (out * 3 + 1) // 2
    return out


def _run_pack(
    enc: Encoded,
    existing_mask: np.ndarray,
    existing_used: np.ndarray,
    max_nodes: int,
    mode: str = "ffd",
    quota: np.ndarray | None = None,
) -> PackResult:
    quota_full = None
    if quota is not None:
        quota_full = np.full(
            (max_nodes, quota.shape[1]), np.iinfo(np.int32).max, np.int32
        )
        quota_full[: quota.shape[0]] = quota
        quota_full = jnp.asarray(quota_full)
    flat = pack_flat(
        jnp.asarray(enc.compat),
        jnp.asarray(enc.group_req),
        jnp.asarray(enc.group_count),
        jnp.asarray(enc.cfg_alloc),
        jnp.asarray(enc.cfg_pool),
        jnp.asarray(enc.pool_overhead),
        jnp.asarray(existing_mask),
        jnp.asarray(existing_used),
        jnp.asarray(enc.cfg_price),
        max_nodes=max_nodes,
        mode=mode,
        quota=quota_full,
    )
    flat = np.asarray(flat)  # the one device->host fetch
    G, C = enc.compat.shape
    R = enc.group_req.shape[1]
    N = max_nodes
    o0, o1, o2, o3, o4 = (
        N * G,
        N * G + N * C,
        N * G + N * C + N * R,
        N * G + N * C + N * R + N,
        N * G + N * C + N * R + N + 1,
    )
    return PackResult(
        assign=flat[:o0].reshape(N, G).astype(np.int32),
        node_mask=flat[o0:o1].reshape(N, C) > 0.5,
        node_used=flat[o1:o2].reshape(N, R),
        node_active=flat[o2:o3] > 0.5,
        node_count=int(flat[o3]),
        unschedulable=flat[o4:].astype(np.int32),
    )
