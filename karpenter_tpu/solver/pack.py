"""Grouped first-fit-decreasing bin-packing as a JAX program.

The reference's Scheduler.Solve (scheduler.go:377-675) is a per-pod
loop: try existing nodes, then in-flight nodes, then a new NodeClaim,
each via CanAdd (taints -> requirements -> resources -> re-filter
instance types). Here the same decision procedure runs as a
`lax.while_loop` over *pod groups* with all per-step work vectorized
over (nodes x configs):

  state: node_mask [N, C] bool  -- configs still feasible per node
         node_used [N, R] f32   -- resources committed per node
         node_active [N] bool, node_count
  step:  ok = node_mask & compat[g] & fits  (fits: used <= alloc-req)
         j  = lowest-index feasible node    (stable tie-break)
         k  = per-config capacity floor((alloc - used_j) / req)
         m  = min(remaining, max over ok configs of k)
         place m pods on j, tighten node_mask[j] to configs with k>=m

Placing a whole group at once is equivalent to the reference's per-pod
FFD for identical pods: scanning pods one-by-one fills the first
feasible node until it no longer fits, which is exactly "place
min(remaining, capacity) then spill" under the lowest-index rule.
Existing/in-flight nodes occupy the first `n_existing` node slots with
one-hot pseudo-config masks, so "existing first, then in-flight, then
new" falls out of the index order. New nodes open on the
highest-weight pool whose configs admit the group (configs are ordered
by pool weight at encode time) and are restricted to that pool's
configs, mirroring addToNewNodeClaim (scheduler.go:587-647).

Determinism: every choice is an argmax/argmin over a static axis with
index tie-breaks — bit-reproducible across runs and shardable over the
config axis.
"""

from __future__ import annotations

import functools
import os
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.solver.encode import Encoded

BIG = jnp.float32(3.4e38)
INT_BIG = jnp.int32(2**31 - 1)
# per-node capacity ceiling: fits int32 exactly (2_000_000_000) and
# behaves as "unbounded" against any real demand. Every capacity the
# kernels compute is clipped here BEFORE the int cast — casting the
# f32 BIG sentinel to int32 is implementation-defined in XLA and the
# int32 range audit (tests/test_scale_dtypes.py) pins the clamp.
CAP_MAX = 2.0e9


def _prefix_take(k: jnp.ndarray, remaining: jnp.ndarray) -> jnp.ndarray:
    """The per-group prefix fill, safe against int32 overflow:
    take_i = clip(remaining - sum_{j<i} k_j, 0, k_i) without ever
    materializing the raw cumulative sum. Per-node capacities are
    clipped at CAP_MAX (~2e9), so a plain int32 cumsum wraps as soon
    as two unbounded rows stack — at million-pod node axes the wrapped
    prefix would fabricate placements. Instead: clamp each capacity at
    `remaining` (a row's surplus beyond the group's demand can never
    be consumed, so takes are unchanged) and saturate the running sum
    at `remaining` via a uint32 associative scan — min(a+b, r) over
    non-negatives is associative, and a+b <= 2r always fits uint32.
    Exact integer arithmetic: bit-identical to the naive prefix
    wherever the int32 math didn't overflow."""
    # clamp against the NON-NEGATIVE remaining: the replaced
    # clip(remaining - prefix, 0, k) returned zeros for a negative
    # demand, and min(k, raw_remaining) would wrap negative through
    # the uint32 cast into huge takes
    rem = jnp.maximum(remaining, 0)
    r = rem.astype(jnp.uint32)
    kc = jnp.minimum(k, rem).astype(jnp.uint32)

    def sat_add(a, b):
        return jnp.minimum(a + b, r)

    inclusive = jax.lax.associative_scan(sat_add, kc)
    prefix = jnp.concatenate(
        [jnp.zeros((1,), jnp.uint32), inclusive[:-1]]
    )
    return jnp.minimum((r - prefix).astype(jnp.int32), kc.astype(jnp.int32))


@functools.lru_cache(maxsize=None)
def _mesh(shards: int):
    """Device mesh over the config axis. Configs are the natural
    parallel dimension: every hot tensor in the kernel is [N, C] or
    [C, R], per-step reductions over C (feasibility max, argmax picks)
    lower to ICI collectives XLA inserts, and the pod/group loop state
    stays tiny and replicated."""
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < shards:
        raise ValueError(
            f"{shards} solver shards requested but only "
            f"{len(devices)} devices visible"
        )
    return Mesh(np.array(devices[:shards]), ("cfg",))


def default_shards() -> int:
    """Shard count the framework paths inherit (0 = unsharded)."""
    try:
        return int(os.environ.get("KARPENTER_SOLVER_SHARDS", "0") or 0)
    except ValueError:
        return 0


# last observed shard resolution (ISSUE 11 satellite): the silent
# default_shards() fallback-to-unsharded used to be log-only; now the
# resolved count lands in the karpenter_solver_shards gauge, on the
# solve.execute span, and in readyz()["solver"] via this record.
_shards_observed = {"effective": 0, "devices": 0}


def last_resolved_shards() -> dict:
    """{"effective": shards the last solve ran with (1 = unsharded),
    "devices": devices visible at that resolution} — 0s before any
    solve has dispatched."""
    return dict(_shards_observed)


def visible_devices(default: int = 1) -> int:
    """len(jax.devices()) with a guarded fallback — backend init can
    raise on hosts whose accelerator runtime is absent. The one probe
    every shard-resolution site shares (solve fallback, warm pool,
    service auto-mesh, observability)."""
    try:
        return len(jax.devices())
    except Exception:
        return default


def _observe_shards(effective: int) -> None:
    from karpenter_tpu.metrics.store import SOLVER_SHARDS

    eff = effective if effective > 1 else 1
    _shards_observed["effective"] = eff
    _shards_observed["devices"] = visible_devices(0)
    SOLVER_SHARDS.set(eff)


@dataclass
class PackResult:
    assign: np.ndarray        # [N, G] int32 pods of group g on node n
    node_mask: np.ndarray     # [N, C] bool configs remaining per node
    node_used: np.ndarray     # [N, R] float64 (exact host recompute)
    node_active: np.ndarray   # [N] bool
    node_count: int
    unschedulable: np.ndarray  # [G] int32 pods that found no placement
    # outer-loop device steps the solve executed (sequential: one per
    # padded group; wavefront: one per committed round) and, for
    # wavefront solves, the groups committed per round
    device_steps: int = 0
    wavefront_widths: np.ndarray | None = None


@functools.partial(jax.jit, static_argnames=("max_nodes", "mode"))
def pack(
    compat: jnp.ndarray,       # [G, C] bool
    group_req: jnp.ndarray,    # [G, R] f32
    group_count: jnp.ndarray,  # [G] i32
    cfg_alloc: jnp.ndarray,    # [C, R] f32
    cfg_pool: jnp.ndarray,     # [C] i32 (-1 for pseudo-configs)
    pool_overhead: jnp.ndarray,  # [P+1, R] f32
    existing_mask: jnp.ndarray,  # [E, C] bool one-hot pseudo-config rows
    existing_used: jnp.ndarray,  # [E, R] f32
    cfg_price: jnp.ndarray,    # [C] f32 (0 for pseudo-configs)
    max_nodes: int,
    mode: str = "ffd",
    quota: jnp.ndarray | None = None,  # [N, G] i32 per-node group caps
    cfg_rsv: jnp.ndarray | None = None,  # [C] i32 reservation slot, -1 none
    rsv_cap: jnp.ndarray | None = None,  # [K] f32 budget per reservation
    group_cap: jnp.ndarray | None = None,  # [G] i32 max pods of g per node
    conflict: jnp.ndarray | None = None,  # [G, G] bool groups that exclude
                                          # each other from sharing a node
                                          # (hostname anti-affinity, ports)
):
    G, C = compat.shape
    R = group_req.shape[1]
    E = existing_mask.shape[0]
    N = max_nodes
    if quota is not None:
        quota = quota.astype(jnp.int32)  # shipped int16, compared int32

    node_mask = jnp.zeros((N, C), bool).at[:E].set(existing_mask)
    node_used = jnp.zeros((N, R), jnp.float32).at[:E].set(existing_used)
    node_active = jnp.zeros((N,), bool).at[:E].set(existing_mask.any(axis=1))
    assign = jnp.zeros((N, G), jnp.int32)
    unschedulable = jnp.zeros((G,), jnp.int32)
    if cfg_rsv is None:
        cfg_rsv = jnp.full((C,), -1, jnp.int32)
    if rsv_cap is None:
        rsv_cap = jnp.zeros((0,), jnp.float32)
    K = rsv_cap.shape[0]
    capped = cfg_rsv >= 0
    # Budgets are per RESERVATION, shared by every column drawing on it
    # (zones / pools / dedupe survivors of one reservation id). Slot K
    # is the uncapped sink with infinite budget.
    rsv_cap_ext = jnp.concatenate([rsv_cap, jnp.full((1,), BIG, jnp.float32)])
    cfg_slot = jnp.where(capped, cfg_rsv, K)  # [C] -> [K+1] index
    # Nodes pre-opened against a capped config (LP-planned reserved
    # slots) consume that reservation's budget up front.
    rsv_used0 = (
        jnp.zeros((K + 1,), jnp.float32)
        .at[cfg_slot]
        .add(existing_mask.astype(jnp.float32).sum(axis=0) * capped)
    )

    def capacity(used_j, req):
        # [C]: how many pods of `req` fit on top of used_j per config
        safe_req = jnp.where(req > 0, req, 1.0)
        head = cfg_alloc - used_j[None, :]
        k = jnp.floor((head + 1e-4) / safe_req[None, :])
        k = jnp.where(req[None, :] > 0, k, BIG)
        return jnp.clip(jnp.min(k, axis=-1), 0.0, CAP_MAX).astype(jnp.int32)

    def body(g, state):
        """One group per iteration: (1) prefix-sum fill across every
        feasible open node in index order — exactly the per-pod
        first-fit outcome — then (2) bulk-open fresh nodes for any
        spill, config by config while capacity-reservation budgets
        allow (inner while). Exact under FFD: within one group the
        open-node feasibility set never changes, so the per-pod scan
        would produce this same layout. Loop trip count is G,
        independent of pod count."""
        node_mask, node_used, node_active, node_count, assign, unsched, rsv_used = state
        req = group_req[g]
        row = compat[g]
        remaining = group_count[g]

        alloc_minus_req = cfg_alloc - req[None, :]

        # [N, C] capacity for this group's pods; feasibility (>=1 pod
        # fits) falls out of the same tensor, so the dominant N x C x R
        # broadcast happens exactly once per iteration.
        safe_req = jnp.where(req > 0, req, 1.0)
        kmat = jnp.floor(
            (cfg_alloc[None, :, :] - node_used[:, None, :] + 1e-4) / safe_req[None, None, :]
        )
        kmat = jnp.where(req[None, None, :] > 0, kmat, BIG).min(axis=-1)
        kmat = jnp.clip(kmat, 0.0, CAP_MAX).astype(jnp.int32)
        ok = node_mask & row[None, :] & (kmat >= 1)
        # a reservation-pinned node (mask holds a capped column) only
        # admits groups compatible with THAT column, and its fill is
        # bounded by the reserved machine — otherwise a later group
        # could tighten the reserved column away, silently un-pinning
        # a node whose budget was already spent
        pinned = node_mask & capped[None, :]
        is_pinned = pinned.any(axis=1)
        pin_ok = (ok & pinned).any(axis=1)
        ok = ok & jnp.where(is_pinned[:, None], pin_ok[:, None], True)
        kmat = kmat * ok
        k = jnp.where(
            is_pinned, (kmat * pinned).max(axis=1), kmat.max(axis=1)
        )
        if quota is not None:
            # LP-planned nodes cap each group's take so complementary
            # resource shapes can share the node (see lp_plan).
            k = jnp.minimum(k, quota[:, g])
        if group_cap is not None:
            # per-node cap for this group net of what the node already
            # holds (hostname topology spread: at most maxSkew matching
            # pods per node, topologygroup.go:226-311)
            k = jnp.minimum(k, jnp.maximum(group_cap[g] - assign[:, g], 0))
        if conflict is not None:
            # a node holding any pod of a conflicting group is off
            # limits (hostname anti-affinity owners + their selector
            # matches, topology.go:280-327; host-port collisions,
            # hostportusage.go) — one masked reduction over the live
            # assignment state
            blocked = (assign * conflict[g][None, :]).sum(axis=1) > 0
            k = jnp.where(blocked, 0, k)
        take = _prefix_take(k, remaining)
        touched = take > 0
        node_mask = jnp.where(touched[:, None], ok & (kmat >= take[:, None]), node_mask)
        node_used = node_used + take[:, None].astype(jnp.float32) * req[None, :]
        assign = assign.at[:, g].add(take)
        remaining = remaining - take.sum()

        # (2) bulk open, config by config, while reservation budgets
        # allow. Each inner iteration opens >=1 node (or the loop
        # exits), so it terminates within the node axis. Most groups
        # take exactly one iteration; extra rounds happen only when a
        # capacity reservation runs dry mid-group and the spill falls
        # back to the next config (ReservationManager fallback,
        # scheduling/reservationmanager.go + nodeclaim.go:201-251).
        fits_fresh = row & jnp.all(
            pool_overhead[cfg_pool] <= alloc_minus_req, axis=-1
        ) & (cfg_pool >= 0)

        def open_cond(args):
            _, _, _, node_count, _, remaining, rsv_used = args
            can = fits_fresh & (rsv_used[cfg_slot] < rsv_cap_ext[cfg_slot])
            return (remaining > 0) & can.any() & (node_count < N)

        def open_round(args):
            node_mask, node_used, node_active, node_count, assign, remaining, rsv_used = args
            fresh_ok = fits_fresh & (rsv_used[cfg_slot] < rsv_cap_ext[cfg_slot])
            chosen_pool = jnp.min(jnp.where(fresh_ok, cfg_pool, INT_BIG))
            mask = fresh_ok & (cfg_pool == chosen_pool)
            overhead = pool_overhead[chosen_pool]
            kf = capacity(overhead, req) * mask
            if mode == "cost":
                # Price-aware open: pick the config minimizing $/pod
                # (lowest index on ties) instead of max capacity — the
                # batched analogue of launching the cheapest adequate
                # instance rather than the biggest compatible one.
                ppp = jnp.where(kf >= 1, cfg_price / jnp.maximum(kf, 1), BIG)
                c_star = jnp.argmin(ppp)
            else:
                # Greedy opens the biggest instance, but the launch
                # resolves to the cheapest offering — which is the
                # reservation while it lasts (the reference's
                # ReservationManager reserves per nodeclaim). Prefer a
                # capped config that undercuts every uncapped price.
                kf_ok = kf >= 1
                min_uncapped = jnp.min(
                    jnp.where(kf_ok & ~capped, cfg_price, BIG)
                )
                res_mask = kf_ok & capped & (cfg_price < min_uncapped)
                c_res = jnp.argmax(jnp.where(res_mask, kf, -1))
                c_star = jnp.where(res_mask.any(), c_res, jnp.argmax(kf))
            m_star = jnp.maximum(kf[c_star], 1)
            if group_cap is not None:
                # fresh nodes respect the per-node group cap too (a
                # self-conflicting group must set group_cap=1 so each
                # fresh node takes one pod)
                m_star = jnp.clip(group_cap[g], 1, m_star)
            slot_star = cfg_slot[c_star]
            cap_left = jnp.minimum(
                rsv_cap_ext[slot_star] - rsv_used[slot_star], CAP_MAX
            )
            q = jnp.minimum((remaining - 1) // m_star + 1, N - node_count)
            q = jnp.minimum(q, jnp.maximum(cap_left, 0).astype(jnp.int32))
            q = jnp.maximum(q, 1)  # open_cond guarantees one is legal
            rem_last = jnp.clip(remaining - (q - 1) * m_star, 1, m_star)
            idx = jnp.arange(N, dtype=jnp.int32)
            sel_full = (idx >= node_count) & (idx < node_count + q - 1)
            sel_last = idx == node_count + q - 1
            fill = (
                sel_full.astype(jnp.int32) * m_star
                + sel_last.astype(jnp.int32) * rem_last
            )
            # A capped (reserved) open keeps its reserved column PLUS
            # the same-pool uncapped columns that fit: decode resolves
            # the node onto the (near-free) reservation — so the claim
            # still pins the reservation id (FinalizeScheduling,
            # scheduling/nodeclaim.go:252) — while the instance-type
            # OPTION list keeps the flexibility the reference's
            # minValues floor is measured against (the pin narrows the
            # launch, not the option set). Uncapped opens exclude
            # capped columns so decode can never resolve a node onto a
            # reservation the budget didn't admit.
            is_capped = capped[c_star]
            one_hot = jnp.arange(C) == c_star
            base_full = mask & ~capped & (kf >= m_star)
            base_last = mask & ~capped & (kf >= rem_last)
            open_mask_full = jnp.where(is_capped, one_hot | base_full, base_full)
            open_mask_last = jnp.where(is_capped, one_hot | base_last, base_last)
            node_mask = jnp.where(
                sel_full[:, None], open_mask_full[None, :],
                jnp.where(sel_last[:, None], open_mask_last[None, :], node_mask),
            )
            node_used = jnp.where(
                (sel_full | sel_last)[:, None],
                overhead[None, :] + fill[:, None].astype(jnp.float32) * req[None, :],
                node_used,
            )
            placed = (q - 1) * m_star + rem_last
            return (
                node_mask,
                node_used,
                node_active | sel_full | sel_last,
                node_count + q,
                assign.at[:, g].add(fill),
                remaining - placed,
                rsv_used.at[slot_star].add(q.astype(jnp.float32)),
            )

        (node_mask, node_used, node_active, node_count, assign, remaining,
         rsv_used) = jax.lax.while_loop(
            open_cond,
            open_round,
            (node_mask, node_used, node_active, node_count, assign, remaining,
             rsv_used),
        )
        unsched = unsched.at[g].add(jnp.maximum(remaining, 0))
        return (node_mask, node_used, node_active, node_count, assign, unsched,
                rsv_used)

    state = jax.lax.fori_loop(
        0,
        G,
        body,
        (node_mask, node_used, node_active, jnp.int32(E), assign, unschedulable,
         rsv_used0),
    )
    node_mask, node_used, node_active, node_count, assign, unsched, _ = state
    return assign, node_mask, node_used, node_active, node_count, unsched


@functools.partial(jax.jit, static_argnames=("max_nodes", "mode"))
def pack_flat(*args, max_nodes: int, mode: str = "ffd", quota=None,
              cfg_rsv=None, rsv_cap=None, group_cap=None, conflict=None):
    """`pack` with the outputs fused into ONE compact uint32 vector.

    The remote-device transport charges a fixed latency per
    device-to-host fetch of a fresh array (~70ms through the axon
    tunnel) plus bandwidth per byte; one buffer pays the latency once,
    and the buffer carries only what the host cannot recompute:
    `assign` counts, the node config masks bit-packed 32 columns per
    word, `node_count`, and the unschedulable tally. `node_used` and
    `node_active` are derived host-side from `assign` (see the fetch
    closure in `_run_pack`) — shipping them would quadruple the payload.
    """
    assign, node_mask, node_used, node_active, node_count, unsched = pack(
        *args, max_nodes=max_nodes, mode=mode, quota=quota,
        cfg_rsv=cfg_rsv, rsv_cap=rsv_cap, group_cap=group_cap,
        conflict=conflict,
    )
    n, cp = node_mask.shape
    words = cp // 32  # _run_pack pads the config axis to a 32-multiple
    packed = (
        node_mask.reshape(n, words, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, None, :]
    ).sum(axis=-1, dtype=jnp.uint32)
    return jnp.concatenate(
        [
            assign.astype(jnp.uint32).ravel(),
            packed.ravel(),
            node_count.astype(jnp.uint32)[None],
            unsched.astype(jnp.uint32).ravel(),
        ]
    )


@functools.partial(jax.jit, static_argnames=("max_free", "mode"))
def pack_split(
    compat: jnp.ndarray,        # [G, C] bool
    group_req: jnp.ndarray,     # [G, R] f32
    group_count: jnp.ndarray,   # [G] i32
    cfg_alloc: jnp.ndarray,     # [C, R] f32
    cfg_pool: jnp.ndarray,      # [C] i32 (-1 for pseudo-configs)
    pool_overhead: jnp.ndarray,  # [P+1, R] f32
    bound_compat: jnp.ndarray,  # [G, B] bool — compat[g, bound_cfg[b]]
    bound_alloc: jnp.ndarray,   # [B, R] f32 — cfg_alloc[bound_cfg]
    bound_used0: jnp.ndarray,   # [B, R] f32 initial usage
    bound_slot: jnp.ndarray,    # [B] i32 reservation slot (K = none)
    bound_live: jnp.ndarray,    # [B] bool real row (not padding)
    cfg_price: jnp.ndarray,     # [C] f32
    max_free: int,
    mode: str = "ffd",
    bound_quota: jnp.ndarray | None = None,  # [B, G] i16 per-node caps
    cfg_rsv: jnp.ndarray | None = None,
    rsv_cap: jnp.ndarray | None = None,
    group_cap: jnp.ndarray | None = None,
    conflict: jnp.ndarray | None = None,
):
    """`pack` with the node axis SPLIT by config breadth.

    `cfg_price` is the kernel's TYPE-PREFERENCE input, not just a
    decode artifact: cost-mode opens argmin over it, so callers may
    feed a dual-adjusted ranking (solver/lp_device.rank_prices) to
    steer opens toward LP-efficient configs — the kernel body is
    identical, ordering is data, and decode always re-prices nodes
    from the encode's true prices (ISSUE 12's bit-identical decode
    contract).

    Existing and LP-planned nodes are one-hot — each holds exactly one
    (pseudo-)config column — so their per-group capacity is a dense
    [B, R] computation against a pre-gathered alloc vector, NOT a slice
    of the [N, C, R] broadcast. Only fresh rows (multi-config masks the
    bulk-open writes) pay the [F, C, R] work. A planned 50k-pod solve
    carries ~5k one-hot rows against a ~200-row fresh spill axis, so
    the per-iteration work drops ~25x vs the dense kernel while the
    semantics stay bit-identical: bound rows sit at the low indices
    (existing first, planned next, fresh last — the reference's
    existing -> in-flight -> new order, scheduler.go:515-587), the
    unified prefix fill runs over the concatenated capacity vector, and
    one-hot rows never tighten (their mask is the single column the
    capacity was computed from). `pack` remains as the dense oracle the
    equivalence tests compare against.
    """
    G, C = compat.shape
    R = group_req.shape[1]
    B = bound_alloc.shape[0]
    F = max_free
    if bound_quota is not None:
        bound_quota = bound_quota.astype(jnp.int32)

    free_mask = jnp.zeros((F, C), bool)
    free_used = jnp.zeros((F, R), jnp.float32)
    assign = jnp.zeros((B + F, G), jnp.int32)
    unschedulable = jnp.zeros((G,), jnp.int32)
    if cfg_rsv is None:
        cfg_rsv = jnp.full((C,), -1, jnp.int32)
    if rsv_cap is None:
        rsv_cap = jnp.zeros((0,), jnp.float32)
    K = rsv_cap.shape[0]
    capped = cfg_rsv >= 0
    rsv_cap_ext = jnp.concatenate([rsv_cap, jnp.full((1,), BIG, jnp.float32)])
    cfg_slot = jnp.where(capped, cfg_rsv, K)
    # bound rows on capped columns consumed their reservation budget
    # when they were opened/planned (same init as the dense kernel's
    # existing_mask column sums)
    rsv_used0 = (
        jnp.zeros((K + 1,), jnp.float32)
        .at[bound_slot]
        .add(jnp.where(bound_live & (bound_slot < K), 1.0, 0.0))
    )

    def capacity(used_j, req):
        safe_req = jnp.where(req > 0, req, 1.0)
        head = cfg_alloc - used_j[None, :]
        k = jnp.floor((head + 1e-4) / safe_req[None, :])
        k = jnp.where(req[None, :] > 0, k, BIG)
        return jnp.clip(jnp.min(k, axis=-1), 0.0, CAP_MAX).astype(jnp.int32)

    def body(g, state):
        (free_mask, free_used, node_count, assign, unsched,
         rsv_used, bound_used) = state
        req = group_req[g]
        row = compat[g]
        remaining = group_count[g]
        safe_req = jnp.where(req > 0, req, 1.0)
        alloc_minus_req = cfg_alloc - req[None, :]

        blocked = None
        if conflict is not None:
            blocked = (assign * conflict[g][None, :]).sum(axis=1) > 0

        # ---- bound rows: one config each, O(B x R)
        kb = jnp.floor(
            (bound_alloc - bound_used + 1e-4) / safe_req[None, :]
        )
        kb = jnp.where(req[None, :] > 0, kb, BIG).min(axis=-1)
        kb = jnp.clip(kb, 0.0, CAP_MAX).astype(jnp.int32)
        ok_b = bound_compat[g] & bound_live & (kb >= 1)
        kb = kb * ok_b
        if bound_quota is not None:
            kb = jnp.minimum(kb, bound_quota[:, g])
        if group_cap is not None:
            kb = jnp.minimum(
                kb, jnp.maximum(group_cap[g] - assign[:B, g], 0)
            )
        if blocked is not None:
            kb = jnp.where(blocked[:B], 0, kb)

        # ---- fresh rows: multi-config masks, O(F x C x R)
        kmat = jnp.floor(
            (cfg_alloc[None, :, :] - free_used[:, None, :] + 1e-4)
            / safe_req[None, None, :]
        )
        kmat = jnp.where(req[None, None, :] > 0, kmat, BIG).min(axis=-1)
        kmat = jnp.clip(kmat, 0.0, CAP_MAX).astype(jnp.int32)
        okf = free_mask & row[None, :] & (kmat >= 1)
        pinned = free_mask & capped[None, :]
        is_pinned = pinned.any(axis=1)
        pin_ok = (okf & pinned).any(axis=1)
        okf = okf & jnp.where(is_pinned[:, None], pin_ok[:, None], True)
        kmat = kmat * okf
        kf = jnp.where(
            is_pinned, (kmat * pinned).max(axis=1), kmat.max(axis=1)
        )
        if group_cap is not None:
            kf = jnp.minimum(
                kf, jnp.maximum(group_cap[g] - assign[B:, g], 0)
            )
        if blocked is not None:
            kf = jnp.where(blocked[B:], 0, kf)

        # ---- unified prefix fill (bound rows precede fresh in index
        # order, preserving existing -> in-flight/planned -> new)
        k = jnp.concatenate([kb, kf])
        take = _prefix_take(k, remaining)
        take_b = take[:B]
        take_f = take[B:]
        touched_f = take_f > 0
        free_mask = jnp.where(
            touched_f[:, None], okf & (kmat >= take_f[:, None]), free_mask
        )
        bound_used = bound_used + take_b[:, None].astype(jnp.float32) * req[None, :]
        free_used = free_used + take_f[:, None].astype(jnp.float32) * req[None, :]
        assign = assign.at[:, g].add(take)
        remaining = remaining - take.sum()

        # ---- bulk open on the fresh axis (identical to the dense
        # kernel; node indices offset by the bound block)
        fits_fresh = row & jnp.all(
            pool_overhead[cfg_pool] <= alloc_minus_req, axis=-1
        ) & (cfg_pool >= 0)

        def open_cond(args):
            _, _, node_count, _, remaining, rsv_used = args
            can = fits_fresh & (rsv_used[cfg_slot] < rsv_cap_ext[cfg_slot])
            return (remaining > 0) & can.any() & (node_count < B + F)

        def open_round(args):
            (free_mask, free_used, node_count, assign,
             remaining, rsv_used) = args
            fresh_ok = fits_fresh & (rsv_used[cfg_slot] < rsv_cap_ext[cfg_slot])
            chosen_pool = jnp.min(jnp.where(fresh_ok, cfg_pool, INT_BIG))
            mask = fresh_ok & (cfg_pool == chosen_pool)
            overhead = pool_overhead[chosen_pool]
            kf = capacity(overhead, req) * mask
            if mode == "cost":
                ppp = jnp.where(kf >= 1, cfg_price / jnp.maximum(kf, 1), BIG)
                c_star = jnp.argmin(ppp)
            else:
                kf_ok = kf >= 1
                min_uncapped = jnp.min(
                    jnp.where(kf_ok & ~capped, cfg_price, BIG)
                )
                res_mask = kf_ok & capped & (cfg_price < min_uncapped)
                c_res = jnp.argmax(jnp.where(res_mask, kf, -1))
                c_star = jnp.where(res_mask.any(), c_res, jnp.argmax(kf))
            m_star = jnp.maximum(kf[c_star], 1)
            if group_cap is not None:
                m_star = jnp.clip(group_cap[g], 1, m_star)
            slot_star = cfg_slot[c_star]
            cap_left = jnp.minimum(
                rsv_cap_ext[slot_star] - rsv_used[slot_star], CAP_MAX
            )
            q = jnp.minimum((remaining - 1) // m_star + 1,
                            B + F - node_count)
            q = jnp.minimum(q, jnp.maximum(cap_left, 0).astype(jnp.int32))
            q = jnp.maximum(q, 1)
            rem_last = jnp.clip(remaining - (q - 1) * m_star, 1, m_star)
            free_base = node_count - B
            idx = jnp.arange(F, dtype=jnp.int32)
            sel_full = (idx >= free_base) & (idx < free_base + q - 1)
            sel_last = idx == free_base + q - 1
            fill = (
                sel_full.astype(jnp.int32) * m_star
                + sel_last.astype(jnp.int32) * rem_last
            )
            is_capped = capped[c_star]
            one_hot = jnp.arange(C) == c_star
            base_full = mask & ~capped & (kf >= m_star)
            base_last = mask & ~capped & (kf >= rem_last)
            open_mask_full = jnp.where(is_capped, one_hot | base_full, base_full)
            open_mask_last = jnp.where(is_capped, one_hot | base_last, base_last)
            free_mask = jnp.where(
                sel_full[:, None], open_mask_full[None, :],
                jnp.where(sel_last[:, None], open_mask_last[None, :], free_mask),
            )
            free_used = jnp.where(
                (sel_full | sel_last)[:, None],
                overhead[None, :] + fill[:, None].astype(jnp.float32) * req[None, :],
                free_used,
            )
            placed = (q - 1) * m_star + rem_last
            fill_all = jnp.concatenate(
                [jnp.zeros((B,), jnp.int32), fill]
            )
            return (
                free_mask,
                free_used,
                node_count + q,
                assign.at[:, g].add(fill_all),
                remaining - placed,
                rsv_used.at[slot_star].add(q.astype(jnp.float32)),
            )

        (free_mask, free_used, node_count, assign, remaining,
         rsv_used) = jax.lax.while_loop(
            open_cond,
            open_round,
            (free_mask, free_used, node_count, assign,
             remaining, rsv_used),
        )
        unsched = unsched.at[g].add(jnp.maximum(remaining, 0))
        return (free_mask, free_used, node_count, assign,
                unsched, rsv_used, bound_used)

    state = jax.lax.fori_loop(
        0,
        G,
        body,
        (free_mask, free_used, jnp.int32(B), assign,
         unschedulable, rsv_used0, bound_used0),
    )
    (free_mask, free_used, node_count, assign, unsched,
     _, _) = state
    return assign, free_mask, node_count, unsched


# unrecognized KARPENTER_WAVEFRONT spellings already warned about
_warned_wavefront: set[str] = set()


def wavefront_width() -> int:
    """Resolve the KARPENTER_WAVEFRONT knob into a lane width (0 =
    sequential).

    Unset / "1" / "on" / "auto" is backend-aware AUTO: wavefront on
    accelerators (the round's plan fan-out rides chip lanes the serial
    loop leaves idle), sequential on CPU — XLA:CPU pays the fan-out in
    real FLOPs (measured on the bench mix: ~2.8x fewer device steps
    but ~3x more wall). "0"/"off" disables everywhere; "force" (or an
    integer >= 2, which IS the width) enables on any backend — tests
    and step-count benchmarks use this. KARPENTER_WAVEFRONT_WIDTH
    overrides the default width (16) without forcing the backend
    choice."""
    raw = os.environ.get("KARPENTER_WAVEFRONT", "auto").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return 0
    width = 0
    force = raw == "force"
    if raw not in ("", "1", "on", "true", "yes", "auto", "force"):
        try:
            width = int(raw)
            if width <= 0:
                # a non-positive width can only mean "off" — falling
                # back to auto would enable the kernel against the
                # operator's evident intent
                return 0
        except ValueError:
            # unrecognized spelling: fall back to AUTO, but say so —
            # an operator typing "seq"/"disabled" meant something, and
            # silently auto-enabling on an accelerator would hide it.
            # Warn once per spelling: this resolver runs on every
            # dispatch, and a consolidation scan must not flood the
            # log with the same line per probe.
            if raw not in _warned_wavefront:
                _warned_wavefront.add(raw)
                import logging

                logging.getLogger("karpenter.solver").warning(
                    "unrecognized KARPENTER_WAVEFRONT=%r; using auto "
                    "(accelerators on, CPU sequential — use 0/off to "
                    "disable, force or an integer width to enable)", raw,
                )
            width = 0
        force = width > 1
    if width == 0:
        wraw = os.environ.get("KARPENTER_WAVEFRONT_WIDTH", "").strip()
        if wraw:
            try:
                width = max(0, int(wraw))
            except ValueError:
                width = 0
    if width == 0:
        width = 16
    if not force:
        try:
            if jax.default_backend() == "cpu":
                return 0
        except Exception:
            return 0
    return 0 if width <= 1 else width


# Below this many real groups the sequential loop wins: the wavefront
# round plans `width` lanes to commit at most `remaining` groups, so a
# tiny solve pays the fan-out without ever amortizing it.
WAVEFRONT_MIN_GROUPS = 8


def wavefront_plan(n_groups: int, shards: int = 0) -> int:
    """Static wavefront width for a solve over `n_groups` real groups;
    0 routes the sequential kernel (knob off, or the solve is too
    small to amortize the fan-out).

    Sharded solves take the wavefront too: every per-lane decision is
    an index-tie-broken arg-reduction over the config axis, the round
    commits touch only replicated state (node axis, reservation
    budgets, the done mask), and the acceptance scan runs on
    replicated scalars — so partitioning the config axis over the mesh
    changes where reductions run, never what they produce. Bit
    identity to the unsharded sequential solve is oracle-enforced
    (tests/test_wavefront_oracle.py sharded axis,
    tests/test_sharded_solver.py)."""
    if n_groups < WAVEFRONT_MIN_GROUPS:
        return 0
    return wavefront_width()


@functools.partial(jax.jit, static_argnames=("max_free", "mode", "width"))
def pack_split_wavefront(
    compat: jnp.ndarray,        # [G, C] bool
    group_req: jnp.ndarray,     # [G, R] f32
    group_count: jnp.ndarray,   # [G] i32
    cfg_alloc: jnp.ndarray,     # [C, R] f32
    cfg_pool: jnp.ndarray,      # [C] i32 (-1 for pseudo-configs)
    pool_overhead: jnp.ndarray,  # [P+1, R] f32
    bound_compat: jnp.ndarray,  # [G, B] bool
    bound_alloc: jnp.ndarray,   # [B, R] f32
    bound_used0: jnp.ndarray,   # [B, R] f32
    bound_slot: jnp.ndarray,    # [B] i32
    bound_live: jnp.ndarray,    # [B] bool
    cfg_price: jnp.ndarray,     # [C] f32
    max_free: int,
    mode: str = "ffd",
    width: int = 8,
    bound_quota: jnp.ndarray | None = None,
    cfg_rsv: jnp.ndarray | None = None,
    rsv_cap: jnp.ndarray | None = None,
    group_cap: jnp.ndarray | None = None,
    conflict: jnp.ndarray | None = None,
):
    """`pack_split` with the serial group loop collapsed into WAVEFRONT
    rounds: each device step PLANS the next `width` uncommitted groups
    in index order — vectorized, each against the same pre-round state,
    computing exactly the placement the sequential body would produce —
    then greedily ACCEPTS the maximal PREFIX of them whose plans
    provably commute, and COMMITS all accepted plans in one scatter.
    Results are bit-identical to `pack_split` (test-enforced:
    tests/test_wavefront_oracle.py); width 1 degenerates to the
    sequential kernel one group per round.

    Acceptance walks the candidates in index order and STOPS at the
    first rejection — a rejected group's plan is stale (that is what
    rejection means), so nothing about it can clear the groups behind
    it; committing past it would also reorder fresh-node indices
    against the sequential solve. A candidate is accepted while, for
    every group already accepted this round (all of them sequential
    predecessors whose plans really commit):

      * node disjointness — the rows they write (`take` > 0) intersect
        none of its DEPENDENCE rows: rows with capacity > 0 up to its
        last fill, or all such rows when it spills (commits only ever
        shrink capacities, so a zero-capacity row can never start
        mattering);
      * no fresh-open interaction — their freshly opened nodes admit
        no config compatible with it (the sequential solve would
        prefix-fill them);
      * no shared reservation slot — once an accepted group spends
        capacity-reservation budget, a spilling candidate re-reads
        those budgets and is deferred;
      * clean index shift — a spilling candidate commits only when its
        planned opens were not clamped by the node axis and still fit
        after the accepted groups' opens shift its slots up.

    The first remaining group is always accepted (its plan IS the
    sequential step), so every round commits >= 1 group and the round
    count is bounded by the longest dependency chain, not the group
    count. Extra outputs: `steps` (rounds executed — the device-step
    metric) and `widths[G]` (groups committed per round, for the
    wavefront width histogram)."""
    G, C = compat.shape
    R = group_req.shape[1]
    B = bound_alloc.shape[0]
    F = max_free
    N = B + F
    W = width
    if bound_quota is not None:
        bound_quota = bound_quota.astype(jnp.int32)
    if cfg_rsv is None:
        cfg_rsv = jnp.full((C,), -1, jnp.int32)
    if rsv_cap is None:
        rsv_cap = jnp.zeros((0,), jnp.float32)
    K = rsv_cap.shape[0]
    capped = cfg_rsv >= 0
    rsv_cap_ext = jnp.concatenate([rsv_cap, jnp.full((1,), BIG, jnp.float32)])
    cfg_slot = jnp.where(capped, cfg_rsv, K)
    rsv_used0 = (
        jnp.zeros((K + 1,), jnp.float32)
        .at[bound_slot]
        .add(jnp.where(bound_live & (bound_slot < K), 1.0, 0.0))
    )
    node_idx = jnp.arange(N, dtype=jnp.int32)

    def plan_one(g, valid, free_mask, free_used, bound_used, assign,
                 rsv_used, node_count):
        """The sequential body of `pack_split`, re-expressed as a pure
        PLAN: identical capacity/fill/open arithmetic (expression for
        expression — the oracle suite holds the two in lockstep), but
        fresh opens land in slot-relative scratch arrays instead of the
        live state, so many plans can be evaluated against one state
        and committed by scatter."""
        req = group_req[g]
        row = compat[g] & valid
        remaining = jnp.where(valid, group_count[g], 0)
        safe_req = jnp.where(req > 0, req, 1.0)
        alloc_minus_req = cfg_alloc - req[None, :]

        blocked = None
        if conflict is not None:
            blocked = (assign * conflict[g][None, :]).sum(axis=1) > 0

        # ---- bound rows (mirrors pack_split.body exactly)
        kb = jnp.floor(
            (bound_alloc - bound_used + 1e-4) / safe_req[None, :]
        )
        kb = jnp.where(req[None, :] > 0, kb, BIG).min(axis=-1)
        kb = jnp.clip(kb, 0.0, CAP_MAX).astype(jnp.int32)
        ok_b = bound_compat[g] & bound_live & (kb >= 1)
        kb = kb * ok_b
        if bound_quota is not None:
            kb = jnp.minimum(kb, bound_quota[:, g])
        if group_cap is not None:
            kb = jnp.minimum(
                kb, jnp.maximum(group_cap[g] - assign[:B, g], 0)
            )
        if blocked is not None:
            kb = jnp.where(blocked[:B], 0, kb)

        # ---- fresh rows (mirrors pack_split.body exactly)
        kmat = jnp.floor(
            (cfg_alloc[None, :, :] - free_used[:, None, :] + 1e-4)
            / safe_req[None, None, :]
        )
        kmat = jnp.where(req[None, None, :] > 0, kmat, BIG).min(axis=-1)
        kmat = jnp.clip(kmat, 0.0, CAP_MAX).astype(jnp.int32)
        okf = free_mask & row[None, :] & (kmat >= 1)
        pinned = free_mask & capped[None, :]
        is_pinned = pinned.any(axis=1)
        pin_ok = (okf & pinned).any(axis=1)
        okf = okf & jnp.where(is_pinned[:, None], pin_ok[:, None], True)
        kmat = kmat * okf
        kf = jnp.where(
            is_pinned, (kmat * pinned).max(axis=1), kmat.max(axis=1)
        )
        if group_cap is not None:
            kf = jnp.minimum(
                kf, jnp.maximum(group_cap[g] - assign[B:, g], 0)
            )
        if blocked is not None:
            kf = jnp.where(blocked[B:], 0, kf)

        k = jnp.concatenate([kb, kf])
        take = _prefix_take(k, remaining)
        take_f = take[B:]
        touched_f = take_f > 0
        newmask_f = okf & (kmat >= take_f[:, None])
        spill = (remaining - take.sum()) > 0

        # ---- fresh-open plan, slot-RELATIVE (commit shifts it onto
        # the node axis at this lane's acceptance offset)
        fits_fresh = row & jnp.all(
            pool_overhead[cfg_pool] <= alloc_minus_req, axis=-1
        ) & (cfg_pool >= 0)

        def open_cond(args):
            _, _, _, n_open, rem, spend, _ = args
            can = fits_fresh & (
                (rsv_used + spend)[cfg_slot] < rsv_cap_ext[cfg_slot]
            )
            return (rem > 0) & can.any() & (node_count + n_open < N)

        def open_round(args):
            o_fill, o_mask, o_used, n_open, rem, spend, clamped = args
            rsv_now = rsv_used + spend
            fresh_ok = fits_fresh & (
                rsv_now[cfg_slot] < rsv_cap_ext[cfg_slot]
            )
            chosen_pool = jnp.min(jnp.where(fresh_ok, cfg_pool, INT_BIG))
            mask = fresh_ok & (cfg_pool == chosen_pool)
            overhead = pool_overhead[chosen_pool]
            head = cfg_alloc - overhead[None, :]
            kfc = jnp.floor((head + 1e-4) / safe_req[None, :])
            kfc = jnp.where(req[None, :] > 0, kfc, BIG)
            kfc = jnp.clip(jnp.min(kfc, axis=-1), 0.0, CAP_MAX).astype(jnp.int32)
            kf_open = kfc * mask
            if mode == "cost":
                ppp = jnp.where(
                    kf_open >= 1, cfg_price / jnp.maximum(kf_open, 1), BIG
                )
                c_star = jnp.argmin(ppp)
            else:
                kf_ok = kf_open >= 1
                min_uncapped = jnp.min(
                    jnp.where(kf_ok & ~capped, cfg_price, BIG)
                )
                res_mask = kf_ok & capped & (cfg_price < min_uncapped)
                c_res = jnp.argmax(jnp.where(res_mask, kf_open, -1))
                c_star = jnp.where(res_mask.any(), c_res, jnp.argmax(kf_open))
            m_star = jnp.maximum(kf_open[c_star], 1)
            if group_cap is not None:
                m_star = jnp.clip(group_cap[g], 1, m_star)
            slot_star = cfg_slot[c_star]
            cap_left = jnp.minimum(
                rsv_cap_ext[slot_star] - rsv_now[slot_star], CAP_MAX
            )
            axis_left = N - (node_count + n_open)
            # min() terms commute, so splitting the sequential
            # min(ceil, axis_left, cap) lets the plan see whether the
            # AXIS was ever the binding constraint — a clamped plan
            # cannot survive an index shift and is re-planned instead
            q_noaxis = jnp.minimum(
                (rem - 1) // m_star + 1,
                jnp.maximum(cap_left, 0).astype(jnp.int32),
            )
            q = jnp.maximum(jnp.minimum(q_noaxis, axis_left), 1)
            clamped = clamped | (jnp.maximum(q_noaxis, 1) > axis_left)
            rem_last = jnp.clip(rem - (q - 1) * m_star, 1, m_star)
            idx = jnp.arange(F, dtype=jnp.int32)
            sel_full = (idx >= n_open) & (idx < n_open + q - 1)
            sel_last = idx == n_open + q - 1
            fill = (
                sel_full.astype(jnp.int32) * m_star
                + sel_last.astype(jnp.int32) * rem_last
            )
            is_capped = capped[c_star]
            one_hot = jnp.arange(C) == c_star
            base_full = mask & ~capped & (kf_open >= m_star)
            base_last = mask & ~capped & (kf_open >= rem_last)
            open_mask_full = jnp.where(
                is_capped, one_hot | base_full, base_full
            )
            open_mask_last = jnp.where(
                is_capped, one_hot | base_last, base_last
            )
            o_mask = jnp.where(
                sel_full[:, None], open_mask_full[None, :],
                jnp.where(sel_last[:, None], open_mask_last[None, :], o_mask),
            )
            o_used = jnp.where(
                (sel_full | sel_last)[:, None],
                overhead[None, :]
                + fill[:, None].astype(jnp.float32) * req[None, :],
                o_used,
            )
            placed = (q - 1) * m_star + rem_last
            return (
                o_fill + fill,
                o_mask,
                o_used,
                n_open + q,
                rem - placed,
                spend.at[slot_star].add(q.astype(jnp.float32)),
                clamped,
            )

        (o_fill, o_mask, o_used, n_open, rem_after, spend,
         clamped) = jax.lax.while_loop(
            open_cond,
            open_round,
            (
                jnp.zeros((F,), jnp.int32),
                jnp.zeros((F, C), bool),
                jnp.zeros((F, R), jnp.float32),
                jnp.int32(0),
                remaining - take.sum(),
                jnp.zeros((K + 1,), jnp.float32),
                jnp.array(False),
            ),
        )
        # the loop exiting with demand left AND a willing config means
        # the node axis was full: that decision too reads node_count
        can_after = fits_fresh & (
            (rsv_used + spend)[cfg_slot] < rsv_cap_ext[cfg_slot]
        )
        clamped = clamped | ((rem_after > 0) & can_after.any())

        touched = take > 0
        last = jnp.max(jnp.where(touched, node_idx, -1))
        dep = (k > 0) & (spill | (node_idx <= last))
        return (
            take,
            newmask_f,
            touched_f,
            touched,
            dep,
            row,
            spill,
            o_fill,
            o_mask,
            o_used,
            n_open,
            spend,
            (spend[:K] > 0).any() if K else jnp.array(False),
            clamped,
            jnp.maximum(rem_after, 0),
            o_mask.any(axis=0),
        )

    def round_body(state):
        (free_mask, free_used, bound_used, node_count, assign, unsched,
         rsv_used, done, steps, widths) = state

        # ---- candidates: the first W uncommitted groups, index order
        remaining_g = ~done
        rank = (jnp.cumsum(remaining_g) - 1).astype(jnp.int32)
        sel = remaining_g & (rank < W)
        cand = (
            jnp.full((W,), G, jnp.int32)
            .at[jnp.where(sel, rank, W)]
            .set(jnp.arange(G, dtype=jnp.int32), mode="drop")
        )
        valid = cand < G
        gsafe = jnp.minimum(cand, G - 1)

        # ---- plan all W lanes against the shared pre-round state
        (take, newmask_f, touched_f, touched, dep, row, spill, o_fill,
         o_mask, o_used, n_open, spend, capped_spend, clamped,
         unsched_add, open_union) = jax.vmap(
            lambda g, v: plan_one(
                g, v, free_mask, free_used, bound_used, assign,
                rsv_used, node_count,
            )
        )(gsafe, valid)

        # ---- greedy PREFIX acceptance scan (lane order == group index
        # order). Acceptance stops at the first rejection: a rejected
        # group's plan is stale by definition (an earlier commit
        # invalidated it), so its planned footprint cannot clear later
        # lanes — only groups whose every sequential predecessor
        # commits THIS round are safe to commit with it. The accepted
        # set is therefore a contiguous prefix of the remaining
        # sequence, and each member needs only one-directional
        # independence from the (real, committing) plans before it.
        def accept_step(carry, xs):
            acc_touched, acc_open, acc_capped, shift, stopped = carry
            (v, touched_w, dep_w, row_w, spill_w, n_open_w, capped_w,
             clamped_w, open_u_w) = xs
            indep = (
                ~(acc_touched & dep_w).any()
                & ~(row_w & acc_open).any()
            )
            spill_ok = ~spill_w | (
                ~acc_capped
                & (
                    (shift == 0)
                    | (~clamped_w & (node_count + shift + n_open_w <= N))
                )
            )
            accept = v & ~stopped & indep & spill_ok
            offset = node_count + shift
            carry = (
                acc_touched | (accept & touched_w),
                acc_open | (accept & open_u_w),
                acc_capped | (accept & capped_w),
                shift + jnp.where(accept, n_open_w, 0),
                stopped | ~accept,
            )
            return carry, (accept, offset)

        carry0 = (
            jnp.zeros((N,), bool),
            jnp.zeros((C,), bool),
            jnp.array(False),
            jnp.int32(0),
            jnp.array(False),
        )
        _, (accept, offset) = jax.lax.scan(
            accept_step,
            carry0,
            (valid, touched, dep, row, spill, n_open, capped_spend,
             clamped, open_union),
        )

        # ---- commit every accepted plan in one scatter. Accepted
        # plans write pairwise-disjoint rows (the acceptance
        # conditions guarantee it), so summed/OR-ed commits equal the
        # sequential one-at-a-time writes bit for bit: every f32 add
        # below has at most one nonzero addend per row.
        accf = accept.astype(jnp.int32)
        off_free = offset - B
        sh_fill = jax.vmap(jnp.roll)(o_fill, off_free)
        sh_mask = jax.vmap(
            lambda m, s: jnp.roll(m, s, axis=0)
        )(o_mask, off_free)
        sh_used = jax.vmap(
            lambda u, s: jnp.roll(u, s, axis=0)
        )(o_used, off_free)

        take_acc = take * accf[:, None]
        fill_all = jnp.concatenate(
            [take_acc[:, :B], take_acc[:, B:] + sh_fill * accf[:, None]],
            axis=1,
        )
        col = jnp.where(accept, cand, G)
        assign = assign.at[:, col].add(fill_all.T, mode="drop")
        reqs = group_req[gsafe]
        bound_used = bound_used + jnp.einsum(
            "wb,wr->br", take_acc[:, :B].astype(jnp.float32), reqs
        )
        free_used = (
            free_used
            + jnp.einsum(
                "wf,wr->fr", take_acc[:, B:].astype(jnp.float32), reqs
            )
            + (sh_used * accf[:, None, None].astype(jnp.float32)).sum(axis=0)
        )
        t_acc = touched_f & accept[:, None]
        free_mask = jnp.where(
            t_acc.any(axis=0)[:, None],
            (newmask_f & t_acc[:, :, None]).any(axis=0),
            free_mask,
        )
        free_mask = free_mask | (sh_mask & accept[:, None, None]).any(axis=0)

        node_count = node_count + (n_open * accf).sum()
        rsv_used = rsv_used + (
            spend * accf[:, None].astype(jnp.float32)
        ).sum(axis=0)
        unsched = unsched.at[col].add(unsched_add * accf, mode="drop")
        done = done.at[col].set(True, mode="drop")
        widths = widths.at[steps].set(accf.sum())
        return (free_mask, free_used, bound_used, node_count, assign,
                unsched, rsv_used, done, steps + 1, widths)

    def cond(state):
        done, steps = state[7], state[8]
        return (~done.all()) & (steps < G)

    state = jax.lax.while_loop(
        cond,
        round_body,
        (
            jnp.zeros((F, C), bool),
            jnp.zeros((F, R), jnp.float32),
            bound_used0,
            jnp.int32(B),
            jnp.zeros((N, G), jnp.int32),
            jnp.zeros((G,), jnp.int32),
            rsv_used0,
            group_count <= 0,
            jnp.int32(0),
            jnp.zeros((G,), jnp.int32),
        ),
    )
    (free_mask, _, _, node_count, assign, unsched, _, _, steps,
     widths) = state
    return assign, free_mask, node_count, unsched, steps, widths


@functools.partial(jax.jit, static_argnames=("max_free", "mode", "wavefront"))
def pack_probe_lanes_flat(
    compat: jnp.ndarray,        # [G, C] bool (shared)
    group_req: jnp.ndarray,     # [G, R] f32 (shared)
    lane_counts: jnp.ndarray,   # [L, G] i32 — per-lane pod demand
    cfg_alloc: jnp.ndarray,     # [C, R] f32 (shared)
    cfg_pool: jnp.ndarray,      # [C] i32 (shared)
    pool_overhead: jnp.ndarray,  # [P+1, R] f32 (shared)
    bound_compat: jnp.ndarray,  # [G, B] bool (shared)
    bound_alloc: jnp.ndarray,   # [B, R] f32 (shared)
    bound_used0: jnp.ndarray,   # [B, R] f32 (shared)
    bound_slot: jnp.ndarray,    # [B] i32 (shared)
    lane_live: jnp.ndarray,     # [L, B] bool — per-lane retained rows
    cfg_price: jnp.ndarray,     # [C] f32 (shared)
    max_free: int,
    mode: str = "ffd",
    wavefront: int = 0,
    cfg_rsv: jnp.ndarray | None = None,
    rsv_cap: jnp.ndarray | None = None,
    conflict: jnp.ndarray | None = None,
):
    """The consolidation probe batch: `pack_split` vmapped over a LANE
    axis. Every lane shares one encoded problem (the whole fleet's
    bound rows, the full launchable catalog, the union of all probed
    pods' groups) and differs only in (a) which bound rows are live —
    a probe masks out its candidate subset's nodes — and (b) how many
    pods of each group it must repack (a lane's excluded-candidate
    pods plus the shared pending backlog; groups outside the lane
    carry count 0 and are exact no-ops in the kernel). One dispatch
    evaluates the entire prefix ladder / candidate rotation instead of
    one sequential solve per probe; the flat uint32 output stacks one
    pack_split_flat-layout row per lane so the host pays a single
    device fetch for the whole batch.

    `wavefront > 1` vmaps the wavefront kernel instead: the batched
    while_loop runs max-rounds-across-lanes iterations rather than G,
    so every lane of the probe batch inherits the step reduction. The
    per-lane stats ([G] widths + round count) land AFTER the
    sequential-layout fields of each row, keeping offset decoders
    unchanged."""
    steps = widths = None
    if wavefront > 1:
        def one(counts, live):
            return pack_split_wavefront(
                compat, group_req, counts, cfg_alloc, cfg_pool,
                pool_overhead, bound_compat, bound_alloc, bound_used0,
                bound_slot, live, cfg_price, max_free=max_free, mode=mode,
                width=wavefront, cfg_rsv=cfg_rsv, rsv_cap=rsv_cap,
                conflict=conflict,
            )

        (assign, free_mask, node_count, unsched, steps,
         widths) = jax.vmap(one)(lane_counts, lane_live)
    else:
        def one(counts, live):
            return pack_split(
                compat, group_req, counts, cfg_alloc, cfg_pool,
                pool_overhead, bound_compat, bound_alloc, bound_used0,
                bound_slot, live, cfg_price, max_free=max_free, mode=mode,
                cfg_rsv=cfg_rsv, rsv_cap=rsv_cap, conflict=conflict,
            )

        assign, free_mask, node_count, unsched = jax.vmap(one)(
            lane_counts, lane_live
        )
    L, f, cp = free_mask.shape
    words = cp // 32
    packed = (
        free_mask.reshape(L, f, words, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, None, None, :]
    ).sum(axis=-1, dtype=jnp.uint32)
    parts = [
        assign.astype(jnp.uint32).reshape(L, -1),
        packed.reshape(L, -1),
        node_count.astype(jnp.uint32)[:, None],
        unsched.astype(jnp.uint32).reshape(L, -1),
    ]
    if wavefront > 1:
        parts.append(widths.astype(jnp.uint32).reshape(L, -1))
        parts.append(steps.astype(jnp.uint32)[:, None])
    return jnp.concatenate(parts, axis=1)


def probe_batch_width() -> int:
    """Probe lanes per device dispatch (KARPENTER_PROBE_BATCH_WIDTH).

    Unset, the width is backend-aware: accelerators get 64 — the lane
    axis genuinely parallelizes across the chip, so one wide dispatch
    amortizes everything — while CPU gets 1: XLA:CPU serializes the
    vmapped packing loop (per-lane execute measured ~4x a solo solve)
    and its compile cost grows with the lane bucket, so probes there
    dispatch the plain split kernel one consulted lane at a time and
    take their win from the shared snapshot/encode/staging instead."""
    raw = os.environ.get("KARPENTER_PROBE_BATCH_WIDTH", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    try:
        if jax.default_backend() != "cpu":
            return 64
    except Exception:
        pass
    return 1


def _lane_bucket(n: int) -> int:
    """Lane-axis shape bucket (KARPENTER_PROBE_LANE_BUCKET sets the
    base): probes compile per (lane, problem) shape bucket, so lanes
    pad to a small 1.25x-spaced family exactly like the node axis —
    padded lanes carry zero demand and no live rows, making them
    near-free no-ops."""
    try:
        base = max(1, int(os.environ.get("KARPENTER_PROBE_LANE_BUCKET", "8")))
    except ValueError:
        base = 8
    return _pad_axis(n, base=base)


@functools.partial(jax.jit, static_argnames=("max_free", "mode", "wavefront"))
def pack_split_flat(*args, max_free: int, mode: str = "ffd",
                    wavefront: int = 0, bound_quota=None, cfg_rsv=None,
                    rsv_cap=None, group_cap=None, conflict=None):
    """`pack_split` with outputs fused into ONE compact uint32 vector
    (see pack_flat for the transport rationale). Bound rows ship no
    masks at all — the host rebuilds their one-hot rows from the
    bound_cfg vector it computed, so the payload shrinks by the whole
    [B, C] block.

    `wavefront > 1` routes the wavefront kernel and APPENDS its
    per-round width vector [G] and round count to the buffer — after
    every sequential-layout field, so offset-based decoders that don't
    know about the stats keep working unchanged."""
    if wavefront > 1:
        (assign, free_mask, node_count, unsched, steps,
         widths) = pack_split_wavefront(
            *args, max_free=max_free, mode=mode, width=wavefront,
            bound_quota=bound_quota, cfg_rsv=cfg_rsv, rsv_cap=rsv_cap,
            group_cap=group_cap, conflict=conflict,
        )
    else:
        assign, free_mask, node_count, unsched = pack_split(
            *args, max_free=max_free, mode=mode, bound_quota=bound_quota,
            cfg_rsv=cfg_rsv, rsv_cap=rsv_cap, group_cap=group_cap,
            conflict=conflict,
        )
    f, cp = free_mask.shape
    words = cp // 32
    packed = (
        free_mask.reshape(f, words, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, None, :]
    ).sum(axis=-1, dtype=jnp.uint32)
    parts = [
        assign.astype(jnp.uint32).ravel(),
        packed.ravel(),
        node_count.astype(jnp.uint32)[None],
        unsched.astype(jnp.uint32).ravel(),
    ]
    if wavefront > 1:
        parts.append(widths.astype(jnp.uint32).ravel())
        parts.append(steps.astype(jnp.uint32)[None])
    return jnp.concatenate(parts)


# problem-shape signature -> node-axis bucket that fit last time.
# Guarded by a lock: the cost objective runs its FFD and planned solves
# on separate threads, and an unsynchronized clear-at-cap could drop a
# sibling's just-remembered axis.
_axis_memory: dict[tuple, int] = {}
_axis_lock = threading.Lock()


def _estimate_nodes(enc: Encoded) -> int:
    """Lower bound on fresh nodes: per group, count / best-config
    capacity, summed. The packer retries with a larger axis if the
    estimate proves too tight (cap detection in solve_packing)."""
    launchable = enc.cfg_pool >= 0
    total = 0
    for gi in range(enc.compat.shape[0]):
        mask = enc.compat[gi] & launchable
        count = int(enc.group_count[gi])
        if not mask.any() or count == 0:
            continue
        req = enc.group_req[gi]
        safe_req = np.where(req > 0, req, 1.0)
        per_node = np.floor((enc.cfg_alloc[mask] + 1e-4) / safe_req[None, :])
        per_node = np.where(req[None, :] > 0, per_node, np.inf).min(axis=1)
        best = max(1.0, float(per_node.max()) if per_node.size else 1.0)
        total += -(-count // int(best))
    return total


def solve_packing(
    enc: Encoded, max_nodes: int = 0, mode: str = "ffd", plan=None,
    shards: int = 0,
) -> PackResult:
    """Host entry: run the packing kernel on the encoded problem."""
    return solve_packing_async(
        enc, max_nodes=max_nodes, mode=mode, plan=plan, shards=shards
    ).result()


class PendingPack:
    """A dispatched-but-unfetched device solve.

    `result()` blocks on the device buffer, decodes it, and — if the
    node axis proved too small — re-runs synchronously with a larger
    axis. Dispatching without fetching lets the caller overlap host
    work (column generation, decoding a sibling solve) with the kernel:
    the cost objective dispatches FFD, prices columns on the host while
    the device packs, dispatches the planned solve, then decodes the
    FFD result while the second kernel runs.
    """

    def __init__(self, fetch):
        self._fetch = fetch
        self._result: PackResult | None = None

    def result(self) -> PackResult:
        if self._result is None:
            self._result = self._fetch()
        return self._result


def solve_packing_async(
    enc: Encoded, max_nodes: int = 0, mode: str = "ffd", plan=None,
    shards: int = 0,
) -> PendingPack:
    """`solve_packing` that returns immediately after dispatching the
    first kernel attempt; see PendingPack.

    With `max_nodes` unset, the node axis is sized from a per-group
    capacity estimate (or the axis remembered from the last solve of
    the same problem), rounded to 1.25x-spaced buckets so repeated
    solves share compilations, and grown on cap-hit — keeping the
    per-iteration N x C work tight instead of worst-casing N at the
    pod count. An explicit `max_nodes` is honored as a hard cap
    (excess pods report unschedulable).

    With a `plan` (lp_plan.FleetPlan), the planned nodes are pre-opened
    as reserved slots pointing at their launch config column, each with
    the LP's per-node group quotas; the fresh-node path only handles
    rounding spill.

    With `shards > 1` (or KARPENTER_SOLVER_SHARDS set), the config
    axis is partitioned over a `shards`-device mesh — inputs land
    pre-sharded via NamedSharding and XLA turns the kernel's config
    reductions into collectives. Results are identical to the
    unsharded solve (every choice is an index-tie-broken arg-reduction,
    insensitive to partitioning).
    """
    if shards == 0:
        shards = default_shards()
        if shards > 1:
            # env-inherited counts degrade gracefully: a fleet-wide
            # KARPENTER_SOLVER_SHARDS must not crash-loop hosts with
            # fewer visible devices — fall back to the unsharded solve.
            # An explicit shards argument still raises (the caller
            # asked for that exact mesh).
            visible = visible_devices(1)
            if shards > visible:
                import logging

                logging.getLogger("karpenter.solver").warning(
                    "KARPENTER_SOLVER_SHARDS=%d exceeds %d visible "
                    "devices; running unsharded", shards, visible,
                )
                shards = 0
    _observe_shards(shards)
    G, C = enc.compat.shape
    E = enc.n_existing
    n_planned = len(plan.planned_cols) if plan is not None else 0
    reserved = E + n_planned
    existing_mask = np.zeros((reserved, C), dtype=bool)
    for ci, cfg in enumerate(enc.configs):
        if cfg.existing_index >= 0:
            existing_mask[cfg.existing_index, ci] = True
    existing_used = enc.existing_used
    existing_rows = (
        enc.existing_quota.astype(np.int32)
        if enc.existing_quota is not None
        else np.full((E, G), np.iinfo(np.int32).max, np.int32)
    )
    quota = existing_rows if enc.existing_quota is not None else None
    if plan is not None:
        existing_mask[E + np.arange(n_planned), plan.planned_cols] = True
        planned_used = enc.pool_overhead[enc.cfg_pool[plan.planned_cols]]
        existing_used = np.concatenate([enc.existing_used, planned_used], axis=0)
        quota = np.concatenate([existing_rows, plan.planned_quota], axis=0)

    # the kernel sees the existing axis padded to its shape bucket, so
    # fresh nodes open at the padded offset — size the node axis for it
    reserved_p = _pad_axis(reserved) if reserved else 0

    if max_nodes > 0:
        # the node axis must at least hold the existing/planned slots
        # (the kernel writes them unconditionally); a cap below that
        # count means "no fresh opens at all", not a smaller axis
        return PendingPack(
            _run_pack(
                enc, existing_mask, existing_used,
                max(max_nodes + (reserved_p - reserved), reserved_p),
                mode, quota, shards,
            )
        )

    total_pods = int(enc.group_count.sum())
    # repeated solves of the SAME problem (bench steady state,
    # consolidation probes, back-to-back rounds) reuse the axis that
    # worked last time — the static estimate can undershoot ~3x, and a
    # capped first attempt costs a full extra device solve every call.
    # The key fingerprints the demand content, not just its shape: two
    # different problems sharing (G, C, pods) must not thrash each
    # other's remembered axis.
    import zlib

    fingerprint = (
        zlib.crc32(enc.group_count.tobytes())
        ^ zlib.crc32(enc.group_req.tobytes())
        ^ zlib.crc32(existing_used.tobytes())
        ^ (zlib.crc32(plan.planned_cols.tobytes()) if plan is not None else 0)
    )
    axis_key = (G, C, total_pods, mode, plan is not None, reserved_p,
                fingerprint)
    with _axis_lock:
        remembered = _axis_memory.get(axis_key)
    if remembered is not None:
        max_nodes = remembered
    else:
        # the FRESH axis is bucketed separately from the (already
        # padded) bound block: bucketing the TOTAL hands a bound-heavy
        # solve up to 25% of the fleet size as fresh axis — the
        # incremental warm-start repack (thousands of bound rows, a
        # handful of spill opens) pays the whole [F, C, R] broadcast
        # for fresh rows it can never use
        estimate = _estimate_nodes(enc)
        if plan is not None:
            # LP covered the bulk; fresh axis only absorbs rounding spill.
            max_nodes = reserved_p + _bucket(max(32, estimate // 8 + 8))
        else:
            fresh = max(32, int(1.35 * estimate) + 16)
            max_nodes = reserved_p + _bucket(
                min(fresh, max(64, total_pods))
            )
    worst_case = reserved_p + total_pods
    pending = _run_pack(
        enc, existing_mask, existing_used, max_nodes, mode, quota, shards
    )

    def fetch() -> PackResult:
        nonlocal pending, max_nodes
        while True:
            result = pending()
            capped = (
                result.node_count >= max_nodes
                and result.unschedulable.sum() > 0
            )
            if not capped or max_nodes > worst_case:
                if not capped:
                    with _axis_lock:
                        if len(_axis_memory) > 256:
                            _axis_memory.clear()
                        # remember a TIGHT axis derived from the actual
                        # FRESH node count, not the (possibly
                        # overgrown) bucket we used — the [F, C] work
                        # is linear in F, so next time pays for the
                        # fresh nodes it needs plus headroom, nothing
                        # more (node_count includes the padded bound
                        # block, which is sized independently)
                        fresh_used = max(
                            0, result.node_count - reserved_p
                        )
                        _axis_memory[axis_key] = reserved_p + _bucket(
                            int(fresh_used * 1.15) + 16
                        )
                return result
            # grow proportionally to observed density, not blind
            # doubling: a capped run tells us pods-per-node, so jump
            # straight to the bucket that should hold the rest
            scheduled = total_pods - int(result.unschedulable.sum())
            if scheduled > 0:
                needed = int(
                    result.node_count * total_pods / scheduled * 1.2
                )
            else:
                needed = max_nodes * 2
            # clamped: one node holds >= one pod, so worst_case is the
            # provable maximum — an extrapolation from a tiny scheduled
            # prefix must not force an absurd static shape
            needed = min(needed, worst_case + 1)
            max_nodes = _bucket(max(needed, max_nodes + 1))
            pending = _run_pack(
                enc, existing_mask, existing_used, max_nodes, mode, quota,
                shards,
            )

    return PendingPack(fetch)


def _bucket(n: int) -> int:
    """Node-axis bucket: 1.25x spacing from 32 — the node axis is the
    dominant cost of every kernel iteration, so tighter buckets (max
    25% padding waste) beat fewer compiled shapes; the persistent
    compile cache amortizes the extra variants."""
    return _pad_axis(n, base=32)


def _pad_axis(n: int, base: int = 16) -> int:
    """1.25x-spaced shape buckets: every solve shape maps onto a small
    family of compiled programs (first axon compiles cost ~30s; an
    unbucketed consolidation search would recompile per prefix size)."""
    out = base
    while out < n:
        out = (out * 5 + 3) // 4
    return out


def _run_pack(
    enc: Encoded,
    existing_mask: np.ndarray,
    existing_used: np.ndarray,
    max_nodes: int,
    mode: str = "ffd",
    quota: np.ndarray | None = None,
    shards: int = 0,
):
    """Dispatch one kernel attempt; returns a zero-arg callable that
    blocks on the device buffer and decodes it into a PackResult.

    Existing/planned one-hot rows become the split kernel's BOUND block
    (config index + pre-gathered alloc vector, host-computed); only the
    fresh axis keeps full [F, C] masks.

    Per-phase wall clock lands in the karpenter_solver_phase_duration
    histogram: "transfer" (host staging + H2D upload), "compile" (the
    jitted dispatch — trace+XLA on a cache miss, sub-ms when the warm
    pool / persistent cache already holds the shape bucket), "execute"
    (blocking on the device buffer at fetch)."""
    import math
    import time as _time

    from karpenter_tpu.metrics.store import SOLVER_PHASE_DURATION
    from karpenter_tpu.solver import faults

    faults.fire("solve")
    _t_stage = _time.perf_counter()

    # int32 width guard (tests/test_scale_dtypes.py): the kernel state,
    # the flat uint32 transport, and the host decode all carry pod
    # counts in 32 bits. A demand whose TOTAL exceeds int32 cannot be
    # represented anywhere downstream — reject it here, before any
    # array is staged, with an error naming the limit.
    total_demand = int(np.asarray(enc.group_count, np.int64).sum())
    if total_demand >= 2**31:
        raise ValueError(
            f"total pod demand {total_demand} exceeds the solver's "
            "int32 range (2^31-1); split the solve"
        )

    G, C = enc.compat.shape
    R = enc.group_req.shape[1]
    E = existing_mask.shape[0]
    Gp, Cp, Ep = _pad_axis(G), _pad_axis(C), _pad_axis(E) if E else 0
    # the config axis must split evenly over the mesh AND pack evenly
    # into the 32-bit mask words of the flat output
    step = math.lcm(32, shards) if shards > 1 else 32
    Cp = -(-Cp // step) * step
    N = max_nodes

    # every call path guarantees the node axis holds the existing
    # slots (the explicit-max_nodes path clamps to reserved_p; the
    # auto-sized path starts there)
    assert N >= Ep, (N, Ep)
    F = N - Ep  # fresh axis

    from karpenter_tpu.solver import stream as stream_mod

    # streaming staging (ISSUE 11): sharded solves ship the padded
    # config-axis matrices as per-shard column blocks, so the full
    # [Gp, Cp] compat block (and the [Cp, ·] cost vectors) never
    # materialize host-side at once — see solver/stream.py for the
    # memory contract. Value-identical to the classic path.
    stream_on = shards > 1 and stream_mod.enabled()

    group_req = np.zeros((Gp, R), np.float32)
    group_req[:G] = enc.group_req
    group_count = np.zeros((Gp,), np.int32)
    group_count[:G] = enc.group_count
    # padded pool vector: kept host-side on EVERY path — fetch()
    # resolves fresh nodes' daemon overhead through it
    cfg_pool = np.full((Cp,), -1, np.int32)
    cfg_pool[:C] = enc.cfg_pool
    if not stream_on:
        compat = np.zeros((Gp, Cp), bool)
        compat[:G, :C] = enc.compat
        cfg_alloc = np.zeros((Cp, R), np.float32)
        cfg_alloc[:C] = enc.cfg_alloc
        cfg_price = np.zeros((Cp,), np.float32)
        cfg_price[:C] = enc.cfg_price

    # ---- bound block: one-hot rows flattened to per-row vectors.
    # Built from the UNPADDED encode arrays (bound columns always index
    # real configs), so the streaming path never needs the padded
    # matrices it refuses to materialize.
    bound_cfg = np.full((Ep,), -1, np.int32)
    bound_used_h = np.zeros((Ep, R), np.float32)
    if E:
        any_col = existing_mask.any(axis=1)
        # rows are strictly one-hot by construction (one pseudo-config
        # per existing node, one planned column per planned slot)
        assert (existing_mask.sum(axis=1) <= 1).all()
        bound_cfg[:E] = np.where(any_col, existing_mask.argmax(axis=1), -1)
        bound_used_h[:E] = existing_used
    bound_live_h = bound_cfg >= 0
    safe_cfg = np.maximum(bound_cfg, 0)
    bound_alloc_h = np.where(
        bound_live_h[:, None], enc.cfg_alloc[safe_cfg], 0.0
    ).astype(np.float32)
    bound_compat_h = np.zeros((Gp, Ep), bool)
    if Ep:
        bound_compat_h[:G, :] = enc.compat[:, safe_cfg] & bound_live_h[None, :]

    bound_quota_h = None
    if quota is not None:
        # int16 on the wire: per-node pod counts are bounded by the
        # 'pods' capacity (hundreds), so 32767 is an honest "no cap"
        # sentinel at half the transfer bytes; the kernel widens back
        # to int32 before comparing. No quota rows ship for group_cap
        # alone — the kernel's dynamic max(group_cap[g] - assign, 0)
        # clamp is always at least as tight as the static min would be.
        bound_quota_h = np.full((Ep, Gp), np.int16(32767), np.int16)
        bound_quota_h[: quota.shape[0], :G] = np.minimum(
            quota[:, :G], 32767
        ).astype(np.int16)
        if enc.group_cap is not None:
            bound_quota_h[:, :G] = np.minimum(
                bound_quota_h[:, :G],
                np.minimum(enc.group_cap, 32767)[None, :].astype(np.int16),
            )
    group_cap_full = None
    if enc.group_cap is not None:
        gc = np.full((Gp,), np.iinfo(np.int32).max, np.int32)
        gc[:G] = enc.group_cap
        group_cap_full = jnp.asarray(gc)
    conflict_full = None
    if enc.conflict is not None and enc.conflict.any():
        cf = np.zeros((Gp, Gp), bool)
        cf[:G, :G] = enc.conflict
        conflict_full = jnp.asarray(cf)
    cfg_rsv = None
    rsv_cap = None
    K = 0
    if enc.rsv_cap is not None and enc.rsv_cap.size:
        K = int(enc.rsv_cap.size)
        rsvp = np.full((Cp,), -1, np.int32)
        rsvp[:C] = enc.cfg_rsv
        cfg_rsv_h = rsvp
        if not stream_on:
            # the streaming branch stages its own per-shard blocks —
            # converting here too would upload a device array the
            # stager immediately discards
            cfg_rsv = jnp.asarray(rsvp)
            rsv_cap = jnp.asarray(enc.rsv_cap.astype(np.float32))
    else:
        cfg_rsv_h = np.full((Cp,), -1, np.int32)
    bound_slot_h = np.where(
        bound_live_h & (cfg_rsv_h[safe_cfg] >= 0), cfg_rsv_h[safe_cfg], K
    ).astype(np.int32)

    bound = {
        "bound_compat": jnp.asarray(bound_compat_h),
        "bound_alloc": jnp.asarray(bound_alloc_h),
        "bound_used0": jnp.asarray(bound_used_h),
        "bound_slot": jnp.asarray(bound_slot_h),
        "bound_live": jnp.asarray(bound_live_h),
    }
    bound_quota_j = (
        jnp.asarray(bound_quota_h) if bound_quota_h is not None else None
    )
    rest = {
        "group_req": jnp.asarray(group_req),
        "group_count": jnp.asarray(group_count),
        "pool_overhead": jnp.asarray(enc.pool_overhead),
    }
    if not stream_on:
        compat_j = jnp.asarray(compat)
        cfg_alloc_j = jnp.asarray(cfg_alloc)
        cfg_pool_j = jnp.asarray(cfg_pool)
        cfg_price_j = jnp.asarray(cfg_price)
    if shards > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh(shards)
        shard_cfg = NamedSharding(mesh, P("cfg"))
        replicated = NamedSharding(mesh, P())
        # committed input shardings drive GSPMD: the jitted kernel
        # compiles with the config axis split over ICI and everything
        # else (including the bound block, whose per-row work has no
        # config axis) replicated
        if stream_on:
            # per-shard column blocks, built + shipped one at a time
            # (solver/stream.py): the padded matrices never exist
            # host-side at once
            staging = stream_mod._Staging()
            compat_j = stream_mod.stage(
                mesh, P(None, "cfg"), (Gp, Cp), np.bool_,
                stream_mod.col_fill_2d(enc.compat, Gp, G, C, np.bool_),
                staging,
            )
            cfg_alloc_j = stream_mod.stage(
                mesh, P("cfg", None), (Cp, R), np.float32,
                stream_mod.row_fill_2d(enc.cfg_alloc, R, C, np.float32),
                staging,
            )
            cfg_pool_j = stream_mod.stage(
                mesh, P("cfg"), (Cp,), np.int32,
                stream_mod.vec_fill(enc.cfg_pool, C, np.int32, pad_value=-1),
                staging,
            )
            cfg_price_j = stream_mod.stage(
                mesh, P("cfg"), (Cp,), np.float32,
                stream_mod.vec_fill(enc.cfg_price, C, np.float32),
                staging,
            )
            rsv_src = (
                enc.cfg_rsv if K else np.full((C,), -1, np.int32)
            )
            cfg_rsv = stream_mod.stage(
                mesh, P("cfg"), (Cp,), np.int32,
                stream_mod.vec_fill(rsv_src, C, np.int32, pad_value=-1),
                staging,
            )
            rsv_cap = jax.device_put(
                jnp.asarray(enc.rsv_cap.astype(np.float32))
                if K else jnp.zeros((0,), jnp.float32),
                replicated,
            )
            staging.commit()
        else:
            shard_nc = NamedSharding(mesh, P(None, "cfg"))
            shard_cr = NamedSharding(mesh, P("cfg", None))
            compat_j = jax.device_put(compat_j, shard_nc)
            cfg_alloc_j = jax.device_put(cfg_alloc_j, shard_cr)
            cfg_pool_j = jax.device_put(cfg_pool_j, shard_cfg)
            cfg_price_j = jax.device_put(cfg_price_j, shard_cfg)
            if cfg_rsv is None:
                # reservation-free sharded solves must still pass
                # cfg_rsv as a TRACED input: left to the in-jit
                # default, `capped` is a compile-time all-false
                # constant, XLA folds the wavefront kernel's
                # reservation reductions into degenerate reduce regions
                # (ROOT constant(false)), and the SPMD partitioner
                # rejects them as unsupported reduction computations.
                # A [C] int32 upload is noise next to compat.
                cfg_rsv = jnp.asarray(cfg_rsv_h)
                rsv_cap = jnp.zeros((0,), jnp.float32)
            cfg_rsv = jax.device_put(cfg_rsv, shard_cfg)
            rsv_cap = jax.device_put(rsv_cap, replicated)
        bound = {k: jax.device_put(v, replicated) for k, v in bound.items()}
        rest = {k: jax.device_put(v, replicated) for k, v in rest.items()}
        if bound_quota_j is not None:
            bound_quota_j = jax.device_put(bound_quota_j, replicated)
        if group_cap_full is not None:
            group_cap_full = jax.device_put(group_cap_full, replicated)
        if conflict_full is not None:
            conflict_full = jax.device_put(conflict_full, replicated)
    # wavefront routing: judged on the REAL group count (padding groups
    # carry zero demand and pre-commit, so they never widen a round);
    # sharded solves route it too — GSPMD partitions the round's config
    # reductions and the commits stay replicated. The
    # kwarg is only PASSED when active: jit keys an explicitly-passed
    # static argument differently from the omitted default, so
    # `wavefront=0` would shadow-recompile every already-warm
    # sequential program (measured ~0.6s per shape bucket).
    wf = wavefront_plan(G, shards)
    wf_kw = {"wavefront": wf} if wf > 1 else {}
    _t_dispatch = _time.perf_counter()
    SOLVER_PHASE_DURATION.observe(
        _t_dispatch - _t_stage, {"phase": "transfer"}
    )
    from karpenter_tpu import tracing
    from karpenter_tpu.metrics import sentinel as _sentinel
    from karpenter_tpu.solver import telemetry as _telemetry

    _sentinel.observe_phase("transfer", _t_dispatch - _t_stage)
    tracing.record("solve.transfer", _t_stage, _t_dispatch,
                   groups=G, configs=C, shards=shards)
    faults.fire("compile")
    flat_dev = pack_split_flat(
        compat_j,
        rest["group_req"],
        rest["group_count"],
        cfg_alloc_j,
        cfg_pool_j,
        rest["pool_overhead"],
        bound["bound_compat"],
        bound["bound_alloc"],
        bound["bound_used0"],
        bound["bound_slot"],
        bound["bound_live"],
        cfg_price_j,
        max_free=F,
        mode=mode,
        **wf_kw,
        bound_quota=bound_quota_j,
        cfg_rsv=cfg_rsv,
        rsv_cap=rsv_cap,
        group_cap=group_cap_full,
        conflict=conflict_full,
    )
    _t_compiled = _time.perf_counter()
    SOLVER_PHASE_DURATION.observe(
        _t_compiled - _t_dispatch, {"phase": "compile"}
    )
    from karpenter_tpu.solver import warm_pool as _warm_pool

    _sentinel.observe_phase("compile", _t_compiled - _t_dispatch)
    _warm_hit = _warm_pool.warmed(Gp, Cp, Ep, F, mode, shards)
    _tm_attrs: dict = {}
    if _telemetry.enabled():
        # the EXACT kwarg variant this dispatch lowered — distinct
        # combinations are distinct XLA programs and must never share
        # a telemetry entry
        _rsv_k = K if K else (0 if shards > 1 else None)
        _variant = _telemetry.variant_tag(
            int(wf), _rsv_k,
            group_cap=group_cap_full is not None,
            conflict=conflict_full is not None,
            quota=bound_quota_j is not None,
        )
        if not _warm_hit:
            # cold lowering of this padded signature: queue it for a
            # drain-time analysis (one shape-only lower per bucket) —
            # never on the tick's own clock
            _telemetry.request_pack_capture(
                Gp, Cp, Ep, F, R, enc.pool_overhead.shape[0] - 1,
                mode, int(wf), shards,
                rsv_k=_rsv_k,
                group_cap=group_cap_full is not None,
                conflict=conflict_full is not None,
                quota=bound_quota_j is not None,
            )
        _entry = _telemetry.compiled_entry(
            "pack", (Gp, Cp, Ep, F, mode, _variant), shards=shards,
        )
        if _entry is not None:
            for values in (_entry.get("memory"), _entry.get("cost")):
                for k, v in (values or {}).items():
                    _tm_attrs["tm_" + k] = v
    tracing.record("solve.compile", _t_dispatch, _t_compiled,
                   wavefront=int(wf), warm_hit=_warm_hit, **_tm_attrs)
    # compile finished: release the watchdog's compile budget (the
    # execute budget keeps running until fetch)
    from karpenter_tpu.solver import resilience

    resilience.note_dispatched()
    # dispatch returned immediately (async device execution); capture
    # only host arrays in the closure so the fetch can rebuild what the
    # compact buffer leaves out
    W = Cp // 32
    group_req_h = enc.group_req.astype(np.float32)
    pool_overhead_h = enc.pool_overhead
    cfg_pool_h = cfg_pool  # host copy, padded
    eused = bound_used_h

    def fetch() -> PackResult:
        faults.fire("execute")
        _t_exec = _time.perf_counter()
        flat = np.asarray(flat_dev)  # the one device->host fetch
        _t_fetched = _time.perf_counter()
        SOLVER_PHASE_DURATION.observe(
            _t_fetched - _t_exec, {"phase": "execute"}
        )
        _sentinel.observe_phase("execute", _t_fetched - _t_exec)
        _tm_exec: dict = {}
        if _telemetry.enabled():
            # live allocator stats straight after the device round-trip
            # — the moment the solve's buffers are all resident. Only
            # backends that report stats publish anything (CPU: no-op).
            for _dev in _telemetry.publish_device_memory():
                _stats = _dev["stats"] or {}
                if "bytes_in_use" in _stats:
                    _tm_exec["tm_in_use_bytes"] = max(
                        _tm_exec.get("tm_in_use_bytes", 0),
                        _stats["bytes_in_use"],
                    )
                if "peak_bytes_in_use" in _stats:
                    _tm_exec["tm_peak_bytes"] = max(
                        _tm_exec.get("tm_peak_bytes", 0),
                        _stats["peak_bytes_in_use"],
                    )
        tracing.record("solve.execute", _t_exec, _t_fetched,
                       shards=shards if shards > 1 else 1, **_tm_exec)
        o0 = N * Gp
        o1 = o0 + F * W
        assign = flat[:o0].reshape(N, Gp)[:, :G].astype(np.int32)
        node_mask = np.zeros((N, C), bool)
        if Ep:
            # bound rows: one-hot reconstruction from the host-side
            # config vector (the kernel never tightens a one-hot row)
            rows = np.flatnonzero(bound_live_h)
            node_mask[rows, bound_cfg[rows]] = True
        if F:
            words = np.ascontiguousarray(flat[o0:o1].reshape(F, W))
            bits = np.unpackbits(
                words.view(np.uint8).reshape(F, W * 4), axis=1,
                bitorder="little",
            )
            node_mask[Ep:] = bits[:, :C].astype(bool)
        node_count = int(flat[o0 + F * W])
        unsched = flat[o0 + F * W + 1 : o0 + F * W + 1 + Gp][:G].astype(
            np.int32
        )
        # device-step accounting: the sequential fori_loop runs one
        # step per PADDED group; the wavefront buffer carries its
        # round count and per-round widths after the sequential layout
        if wf > 1:
            o2 = o0 + F * W + 1 + Gp
            steps = int(flat[o2 + Gp])
            wf_widths = flat[o2 : o2 + Gp][:steps].astype(np.int32)
        else:
            steps = Gp
            wf_widths = None
        from karpenter_tpu.metrics.store import (
            SOLVER_DEVICE_STEPS,
            SOLVER_WAVEFRONT_WIDTH,
        )

        SOLVER_DEVICE_STEPS.observe(
            steps, {"path": "wavefront" if wf > 1 else "sequential"}
        )
        if wf_widths is not None:
            for wv in wf_widths.tolist():
                SOLVER_WAVEFRONT_WIDTH.observe(wv)
        # node_active / node_used are pure functions of the shipped
        # state: active = holds pods or is a live existing slot;
        # used = base (existing usage / fresh pool overhead) + the
        # placed pods' requests. The sum runs in float64: every addend
        # is an exact float32 value and the totals stay far below
        # 2^53, so this is the EXACT usage — float32 matmul would
        # round differently from the kernel's sequential accumulation
        # (ulp ~1KB at byte-scale memory), and a low-by-rounding value
        # could let _downsize_masks resize a node below its true fill.
        node_active = assign.sum(axis=1) > 0
        if Ep:
            node_active[:Ep] |= bound_live_h
        base = np.zeros((N, R), np.float64)
        if Ep:
            base[:Ep] = eused
        fresh = node_active.copy()
        fresh[:Ep] = False
        if fresh.any():
            first_col = node_mask[fresh].argmax(axis=1)
            base[fresh] = pool_overhead_h[cfg_pool_h[first_col]]
        node_used = base + assign.astype(np.float64) @ group_req_h.astype(
            np.float64
        )
        return PackResult(
            assign=assign,
            node_mask=node_mask,
            node_used=node_used,
            node_active=node_active,
            node_count=node_count,
            unschedulable=unsched,
            device_steps=steps,
            wavefront_widths=wf_widths,
        )

    return fetch
