"""Solver resilience layer: deadlines, circuit breakers, degradation
ladder, and an FFD hedge around every device-bound solve.

The north star moves both hot paths (provisioning pack, consolidation
probes) onto a TPU-backed solver — which means a wedged device, a hung
XLA compile, or a dead gRPC solver service could stall the reconcile
tick, the one thing the control plane must never do. This module is
the answer: `ResilientSolver` wraps the `solver._solve_packing` seam
with three mechanisms, and guarantees EVERY solve returns a decision —
degraded, perhaps, but never absent and never late past the deadline.

1. **Deadline watchdog** (`KARPENTER_SOLVE_DEADLINE_MS`,
   `KARPENTER_COMPILE_DEADLINE_MS`): with a deadline set, each rung's
   attempt runs on a watchdog thread. The compile phase is budgeted
   separately — pack._run_pack signals `note_dispatched()` once the
   jitted dispatch returns (compile done), so a hung XLA compile is
   distinguished from a slow execute and classified `compile_timeout`.
   A deadline miss abandons the attempt (the stuck thread keeps the
   device; the breaker keeps callers off it) and falls down the
   ladder. Unset (the default) the attempt runs inline — a try/except
   around the exact code that ran before, so the healthy path pays
   nothing.

2. **Per-backend circuit breaker**: `closed -> open` after
   `KARPENTER_BREAKER_THRESHOLD` consecutive classified failures
   (device_lost / xla_runtime, compile_timeout, deadline,
   rpc_unavailable); while open, the rung is skipped outright (no
   deadline burned per tick). Cooldowns are jittered exponential
   (KARPENTER_BREAKER_COOLDOWN_MS base, _MAX_COOLDOWN_MS cap, full
   desynchronizing jitter). After the cooldown one half-open probe is
   admitted; its success closes the breaker — gated, for device
   backends with KARPENTER_REWARM_ON_CLOSE=1, on a warm-pool canary
   re-compile proving XLA actually serves again — and its failure
   re-opens with a doubled cooldown.

3. **Degradation ladder**: sharded-device -> single-device -> remote
   service -> host FFD oracle (`reference_ffd`). Rung order is derived
   from the environment (`auto`): a configured
   KARPENTER_SOLVER_ENDPOINT promotes the remote service to the first
   rung (the operator's statement that the device lives off-host —
   preserving the service seam's routing semantics), local device
   rungs follow, and the host oracle is always last and cannot fail.
   KARPENTER_SOLVE_LADDER="sharded,device,remote,host" overrides the
   order explicitly. The optional **hedge**
   (KARPENTER_SOLVE_HEDGE_MS) starts the host FFD solve on a timer
   thread mid-attempt, so when a slow device does miss the deadline
   the degraded answer is already computed — a hedge that supplies the
   returned result counts as a `win` in karpenter_solver_hedge_total.

Observability: karpenter_solver_breaker_state (0 closed / 1 half-open
/ 2 open), _breaker_transitions_total, _ladder_total{rung,outcome},
_deadline_exceeded_total{phase}, _hedge_total{outcome}. Degraded
solves are recorded per-thread; the scheduler pops them
(`pop_degraded`) to log which rung actually served its tick.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

log = logging.getLogger("karpenter.solver.resilience")

STATE_CLOSED = 0.0
STATE_HALF_OPEN = 1.0
STATE_OPEN = 2.0

RUNGS = ("remote", "sharded", "device", "host")


def _env_ms(name: str, default: float = 0.0) -> float:
    """Millisecond env knob -> seconds; 0/unset/malformed disables."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(0.0, float(raw) / 1000.0)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class DeadlineExceeded(TimeoutError):
    """The watchdog abandoned a rung attempt past its budget."""

    phase = "execute"


class CompileDeadlineExceeded(DeadlineExceeded):
    """The kernel dispatch (trace + XLA compile) blew its own budget."""

    phase = "compile"


def classify(err: BaseException) -> str:
    """Failure taxonomy driving the breaker: which class of fault a
    rung failure belongs to. Anything unrecognized still degrades the
    solve (the ladder catches every exception) but counts as plain
    `error`."""
    from karpenter_tpu.solver import faults

    if isinstance(err, faults.RpcDropError):
        return "rpc_unavailable"
    if isinstance(err, faults.DeviceLostError):
        return "device_lost"
    if isinstance(err, CompileDeadlineExceeded):
        return "compile_timeout"
    if isinstance(err, DeadlineExceeded):
        return "deadline"
    tname = type(err).__name__
    module = type(err).__module__ or ""
    if tname in ("XlaRuntimeError", "InternalError") or module.startswith(
        ("jaxlib", "jax")
    ):
        return "device_lost"
    if tname in ("RpcError", "_InactiveRpcError", "_MultiThreadedRendezvous",
                 "FutureTimeoutError") or module.startswith("grpc"):
        return "rpc_unavailable"
    if isinstance(err, (ConnectionError, OSError, TimeoutError)):
        return "rpc_unavailable"
    return "error"


class CircuitBreaker:
    """Closed -> open -> half-open breaker with jittered exponential
    cooldowns and an optional gate on the close transition."""

    def __init__(
        self,
        name: str,
        threshold: Optional[int] = None,
        base_cooldown: Optional[float] = None,
        max_cooldown: Optional[float] = None,
        rng: Optional[random.Random] = None,
        close_gate: Optional[Callable[[], bool]] = None,
    ):
        self.name = name
        self._threshold = threshold
        self._base = base_cooldown
        self._max = max_cooldown
        self._rng = rng or random.Random()
        self.close_gate = close_gate
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0
        self._open_cycles = 0
        self._retry_at = 0.0
        self._publish(STATE_CLOSED, transition=False)

    # knobs read per call so tests (and live re-tuning) take effect
    # without rebuilding breakers
    def _threshold_now(self) -> int:
        if self._threshold is not None:
            return self._threshold
        return max(1, _env_int("KARPENTER_BREAKER_THRESHOLD", 2))

    def _cooldown(self) -> float:
        base = (
            self._base
            if self._base is not None
            else _env_ms("KARPENTER_BREAKER_COOLDOWN_MS", 5.0)
        ) or 5.0
        cap = (
            self._max
            if self._max is not None
            else _env_ms("KARPENTER_BREAKER_MAX_COOLDOWN_MS", 120.0)
        ) or 120.0
        from karpenter_tpu.utils.backoff import capped_exponential, jitter

        # desynchronizing jitter: a fleet of control planes tripped by
        # the same outage must not re-probe in lockstep when it heals
        return capped_exponential(self._open_cycles, base, cap) * jitter(
            self._rng
        )

    def _publish(self, state: float, transition: bool = True) -> None:
        from karpenter_tpu.metrics.store import (
            SOLVER_BREAKER_STATE,
            SOLVER_BREAKER_TRANSITIONS,
        )

        self._state = state
        SOLVER_BREAKER_STATE.set(state, {"backend": self.name})
        if transition:
            label = {STATE_CLOSED: "closed", STATE_HALF_OPEN: "half_open",
                     STATE_OPEN: "open"}[state]
            SOLVER_BREAKER_TRANSITIONS.inc(
                {"backend": self.name, "to": label})

    @property
    def state(self) -> float:
        return self._state

    def is_open(self, now: Optional[float] = None) -> bool:
        """Open AND still cooling down (a breaker past its cooldown is
        about to half-open, so callers planning work may try it)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._state == STATE_OPEN and now < self._retry_at

    def _probe_ttl(self) -> float:
        """How long a half-open probe may stay verdict-less before the
        breaker admits another (a probe abandoned by the deadline
        watchdog must not wedge the breaker half-open forever)."""
        base = (
            self._base
            if self._base is not None
            else _env_ms("KARPENTER_BREAKER_COOLDOWN_MS", 5.0)
        )
        return base or 5.0

    def allow(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_OPEN and now >= self._retry_at:
                # admit exactly one half-open probe; a concurrent
                # caller arriving before its verdict stays skipped
                self._publish(STATE_HALF_OPEN)
                self._retry_at = now + self._probe_ttl()
                log.info("breaker %s half-open: probing", self.name)
                return True
            if self._state == STATE_HALF_OPEN and now >= self._retry_at:
                # the admitted probe never reported (abandoned attempt)
                self._retry_at = now + self._probe_ttl()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == STATE_CLOSED:
                self._failures = 0
                return
            gate = self.close_gate
        # the gate (warm-pool re-warm) runs OUTSIDE the lock: it can
        # compile for seconds and concurrent solves must keep flowing
        # through their own rungs meanwhile
        gate_ok = True
        if gate is not None:
            try:
                gate_ok = bool(gate())
            except Exception:
                log.exception("breaker %s close gate crashed", self.name)
                gate_ok = False
        with self._lock:
            if not gate_ok:
                self._open_cycles += 1
                self._retry_at = time.monotonic() + self._cooldown()
                self._publish(STATE_OPEN)
                log.warning(
                    "breaker %s: half-open probe succeeded but the "
                    "re-warm gate failed; staying open", self.name)
                return
            self._failures = 0
            self._open_cycles = 0
            self._publish(STATE_CLOSED)
            log.info("breaker %s closed", self.name)

    def record_failure(self, reason: str) -> None:
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                self._open_cycles += 1
            else:
                self._failures += 1
                if self._failures < self._threshold_now():
                    return
                self._open_cycles += 1
            cooldown = self._cooldown()
            self._retry_at = time.monotonic() + cooldown
            self._failures = 0
            self._publish(STATE_OPEN)
            log.warning(
                "breaker %s open (%s): cooling down %.2fs",
                self.name, reason, cooldown)

    def force_close(self) -> None:
        """Test/ops escape hatch: reset to closed immediately."""
        with self._lock:
            self._failures = 0
            self._open_cycles = 0
            self._retry_at = 0.0
            self._publish(STATE_CLOSED, transition=False)


# -- host FFD oracle as a PackResult ------------------------------------------


def host_pack_result(enc, max_nodes: int = 0, mode: str = "ffd"):
    """The decision of last resort: the pure-Python FFD oracle
    (`reference_ffd.solve_ffd_host`) decoded into the same PackResult
    shape the device kernel produces, so the ladder degrades without
    changing a single downstream decode path. `mode` is accepted for
    signature parity; the oracle always packs FFD — exactly the floor
    the cost objective races against, so a degraded cost solve returns
    the race's guaranteed-no-worse baseline."""
    from karpenter_tpu.solver.pack import PackResult
    from karpenter_tpu.solver.reference_ffd import solve_ffd_host

    nodes, unsched = solve_ffd_host(enc)
    G, C = enc.compat.shape
    R = enc.group_req.shape[1]
    n = len(nodes)
    assign = np.zeros((n, G), np.int32)
    node_mask = np.zeros((n, C), bool)
    node_used = np.zeros((n, R), np.float64)
    for ni, node in enumerate(nodes):
        node_mask[ni] = node.mask
        node_used[ni] = node.used
        for gi, count in node.assign.items():
            assign[ni, gi] = count
    unsched_arr = np.zeros(G, np.int32)
    for gi, count in unsched.items():
        unsched_arr[gi] = count
    return PackResult(
        assign=assign,
        node_mask=node_mask,
        node_used=node_used,
        node_active=np.ones(n, bool),
        node_count=n,
        unschedulable=unsched_arr,
    )


# -- watchdog plumbing --------------------------------------------------------

_tlocal = threading.local()

# abandoned watchdog attempts still run their (possibly wedged) device
# call on daemon threads; at interpreter shutdown a daemon thread
# inside native XLA code dies with a C++ `terminate` (the same failure
# warm_pool documents). The shutdown hook below — registered via
# threading's internal hooks, which run BEFORE daemon threads are
# killed — drains live attempts with a bounded join: injected-fault
# attempts (sleeps) finish quickly; a truly wedged device forfeits the
# budget and the process exits anyway (it was exiting regardless).
_watchdog_threads: set = set()
_watchdog_lock = threading.Lock()


def _drain_watchdogs(budget: float = 10.0) -> None:
    deadline = time.monotonic() + budget
    with _watchdog_lock:
        live = list(_watchdog_threads)
    for thread in live:
        thread.join(max(0.0, deadline - time.monotonic()))


_register = getattr(threading, "_register_atexit", None)
if _register is not None:  # CPython 3.9+
    _register(_drain_watchdogs)
else:  # pragma: no cover - very old interpreters: bounded daemon risk
    import atexit

    atexit.register(_drain_watchdogs)


def note_dispatched() -> None:
    """Called by pack._run_pack the moment the jitted dispatch returns
    (== compile finished). Lets the watchdog budget the compile phase
    separately from execute. No-op outside a watchdog attempt."""
    ctx = getattr(_tlocal, "attempt", None)
    if ctx is not None:
        ctx["dispatched"].set()


def _served_list() -> list:
    """The CALLER thread's degradation accumulator. Captured at the
    public solve entry points and passed through explicitly, so ladders
    running on watchdog/executor threads still report into the thread
    that will pop_degraded() (the scheduler's)."""
    stack = getattr(_tlocal, "served", None)
    if stack is None:
        stack = _tlocal.served = []
    return stack


def _note_rung(served: Optional[list], rung: str, degraded: bool) -> None:
    if degraded and served is not None:
        served.append(rung)


def pop_degraded() -> list[str]:
    """Rungs (other than the primary) that served this thread's solves
    since the last pop — the scheduler's per-tick degradation report."""
    stack = getattr(_tlocal, "served", None)
    if not stack:
        return []
    out = list(stack)
    stack.clear()
    return out


class _LazyPending:
    """PendingPack-compatible wrapper over a deferred resilient solve."""

    def __init__(self, thunk):
        self._thunk = thunk
        self._result = None

    def result(self):
        if self._result is None:
            self._result = self._thunk()
        return self._result


class _GuardedPending:
    """A first-rung async dispatch whose fetch falls down the ladder."""

    def __init__(self, solver: "ResilientSolver", rung: str, pending,
                 ladder_tail: Callable):
        self._solver = solver
        self._rung = rung
        self._pending = pending
        self._tail = ladder_tail
        self._result = None

    def result(self):
        from karpenter_tpu import tracing

        if self._result is not None:
            return self._result
        br = self._solver.breaker(self._rung)
        with tracing.span("solver.rung", rung=self._rung) as rsp:
            try:
                out = self._pending.result()
            except Exception as err:
                reason = classify(err)
                br.record_failure(reason)
                _ladder_count(self._rung, reason)
                rsp.annotate(outcome=reason)
                log.warning("solver rung %s failed at fetch (%s: %s); "
                            "degrading", self._rung, reason, err)
                out = self._tail()
            else:
                br.record_success()
                _ladder_count(self._rung, "ok")
                rsp.annotate(outcome="ok")
        self._result = out
        return out


def _ladder_count(rung: str, outcome: str) -> None:
    from karpenter_tpu.metrics.store import SOLVER_LADDER

    SOLVER_LADDER.inc({"rung": rung, "outcome": outcome})


def note_incremental_poison() -> None:
    """The degradation ladder's `incremental_poison` rung: the
    provisioner's incremental live tick caught (or was told about) a
    poisoned retained-state cache and degraded the tick to the full
    Scheduler's decision. Not a backend rung — no breaker, nothing to
    retry — but it IS a degradation the fleet operator must see in the
    same ladder telemetry as device/remote failures: a tick served
    correct-but-slower, and a growing count means the retained state
    keeps going stale."""
    from karpenter_tpu import tracing

    tracing.add_event("rung_degraded", rung="incremental_poison",
                      reason="quarantined")
    _ladder_count("incremental_poison", "quarantined")


class ResilientSolver:
    """The solve seam's resilience wrapper; one per process (shared())
    so breaker state survives across ticks and callers."""

    def __init__(self):
        self._breakers: dict[str, CircuitBreaker] = {}
        self._breaker_lock = threading.Lock()
        self._executor = None
        self._executor_lock = threading.Lock()

    # -- breakers ------------------------------------------------------------

    def breaker(self, name: str) -> CircuitBreaker:
        with self._breaker_lock:
            br = self._breakers.get(name)
            if br is None:
                gate = (
                    self._rewarm_gate
                    if name in ("device", "sharded") else None
                )
                br = CircuitBreaker(name, close_gate=gate)
                self._breakers[name] = br
            return br

    def _rewarm_gate(self) -> bool:
        """Close-transition gate for device backends: with
        KARPENTER_REWARM_ON_CLOSE=1, a half-open success only closes
        the breaker after a warm-pool canary compile proves XLA and
        the device serve again (a device that answers one cached-shape
        probe but can't compile would otherwise flap)."""
        if os.environ.get("KARPENTER_REWARM_ON_CLOSE", "").lower() not in (
            "1", "true", "on"
        ):
            return True
        from karpenter_tpu.solver.warm_pool import rewarm_canary

        return rewarm_canary()

    def reset(self) -> None:
        """Drop all breaker state (tests)."""
        with self._breaker_lock:
            self._breakers.clear()

    # -- ladder --------------------------------------------------------------

    def _rungs(self, shards: int) -> list[str]:
        spec = os.environ.get("KARPENTER_SOLVE_LADDER", "auto").strip()
        endpoint = self._endpoint()
        if spec and spec != "auto":
            names = [n.strip() for n in spec.split(",") if n.strip()]
            names = [n for n in names if n in RUNGS]
            names = [n for n in names if n != "remote" or endpoint]
        else:
            names = []
            if endpoint:
                # an explicit endpoint is the operator saying the
                # device lives off-host: the service outranks the
                # (typically device-less) local backend
                names.append("remote")
            if self._effective_shards(shards) > 1:
                names.append("sharded")
            names.append("device")
        if "host" not in names:
            names.append("host")
        # host is the unconditional floor, always last
        names = [n for n in names if n != "host"] + ["host"]
        return names

    @staticmethod
    def _endpoint() -> Optional[str]:
        from karpenter_tpu.service.client import endpoint_from_env

        return endpoint_from_env()

    @staticmethod
    def _effective_shards(shards: int) -> int:
        if shards > 1:
            return shards
        if shards == 0:
            from karpenter_tpu.solver.pack import default_shards

            return default_shards()
        return 1

    def _rung_fn(self, name: str, enc, max_nodes, mode, plan, shards):
        if name == "sharded":
            from karpenter_tpu.solver.pack import solve_packing

            eff = self._effective_shards(shards)
            return lambda: solve_packing(
                enc, max_nodes=max_nodes, mode=mode, plan=plan, shards=eff)
        if name == "device":
            from karpenter_tpu.solver.pack import solve_packing

            # shards=1 forces the unsharded program even when the env
            # asks for a mesh — this rung IS the single-device fallback
            eff = 1 if self._effective_shards(shards) > 1 else shards
            return lambda: solve_packing(
                enc, max_nodes=max_nodes, mode=mode, plan=plan, shards=eff)
        if name == "remote":
            client = self._remote_client()
            if client is None:
                raise LookupError("no remote endpoint configured")
            return lambda: client.solve_packing(
                enc, max_nodes=max_nodes, mode=mode, plan=plan,
                shards=shards, fallback=False)
        if name == "host":
            return lambda: host_pack_result(enc, max_nodes, mode)
        raise LookupError(name)

    @staticmethod
    def _remote_client():
        # the client cache lives in solver.py (tests reset it there);
        # lazy import avoids a module cycle
        from karpenter_tpu.solver import solver as solver_mod

        return solver_mod._remote_client()

    # -- attempts ------------------------------------------------------------

    def _attempt(self, name: str, fn: Callable, budget: Optional[float],
                 compile_budget: float):
        """One rung attempt. Without budgets: inline (zero overhead).
        With budgets: on a watchdog thread, compile and execute phases
        budgeted separately; a miss abandons the thread (daemon — the
        wedged device call cannot hold a pool slot hostage)."""
        if not budget and not compile_budget:
            return fn()
        ctx = {
            "dispatched": threading.Event(),
            "done": threading.Event(),
            "result": None,
            "error": None,
        }

        def run():
            _tlocal.attempt = ctx
            try:
                ctx["result"] = fn()
            except BaseException as err:  # noqa: BLE001 — re-raised below
                ctx["error"] = err
            finally:
                _tlocal.attempt = None
                ctx["done"].set()
                # a failure BEFORE the kernel dispatch (dead device
                # raising instantly) must release the compile-budget
                # wait immediately, not let it sleep out the budget
                ctx["dispatched"].set()
                with _watchdog_lock:
                    _watchdog_threads.discard(threading.current_thread())

        thread = threading.Thread(
            target=run, name=f"solve-watchdog-{name}", daemon=True)
        with _watchdog_lock:
            _watchdog_threads.add(thread)
        start = time.monotonic()
        thread.start()
        from karpenter_tpu.metrics.store import SOLVER_DEADLINE_EXCEEDED

        if compile_budget and name in ("device", "sharded"):
            if not ctx["dispatched"].wait(compile_budget) and not ctx[
                "done"
            ].is_set():
                SOLVER_DEADLINE_EXCEEDED.inc({"phase": "compile"})
                raise CompileDeadlineExceeded(
                    f"{name}: kernel dispatch exceeded "
                    f"{compile_budget * 1000:.0f}ms compile budget")
        if budget:
            remaining = budget - (time.monotonic() - start)
            if not ctx["done"].wait(max(0.0, remaining)):
                SOLVER_DEADLINE_EXCEEDED.inc({"phase": "execute"})
                raise DeadlineExceeded(
                    f"{name}: solve exceeded {budget * 1000:.0f}ms budget")
        else:
            ctx["done"].wait()
        if ctx["error"] is not None:
            raise ctx["error"]
        return ctx["result"]

    # -- solve ---------------------------------------------------------------

    def solve_packing(self, enc, max_nodes: int = 0, mode: str = "ffd",
                      plan=None, shards: int = 0):
        names = self._rungs(shards)
        return self._ladder(names, enc, max_nodes, mode, plan, shards,
                            served=_served_list())

    def _ladder(self, names: Sequence[str], enc, max_nodes, mode, plan,
                shards, served: Optional[list] = None,
                primary: Optional[str] = None):
        from karpenter_tpu.metrics.store import (
            SOLVER_DEADLINE_EXCEEDED,
            SOLVER_HEDGE,
        )

        deadline = _env_ms("KARPENTER_SOLVE_DEADLINE_MS")
        compile_budget = _env_ms("KARPENTER_COMPILE_DEADLINE_MS")
        hedge_delay = _env_ms("KARPENTER_SOLVE_HEDGE_MS")
        t_end = time.monotonic() + deadline if deadline else None
        # `primary` survives ladder truncation (a tail ladder resumed
        # after an async fetch failure must still report host as
        # degraded relative to the ORIGINAL first rung)
        primary = primary or names[0]

        hedge: Optional[dict] = None
        timer: Optional[threading.Timer] = None
        if hedge_delay and primary != "host" and len(names) > 1:
            hedge = {"fired": threading.Event(), "done": threading.Event(),
                     "result": None, "cancel": False}

            def hedge_run():
                if hedge["cancel"]:
                    return
                hedge["fired"].set()
                SOLVER_HEDGE.inc({"outcome": "fired"})
                try:
                    hedge["result"] = host_pack_result(enc, max_nodes, mode)
                except Exception:
                    log.exception("hedged host solve failed")
                finally:
                    hedge["done"].set()

            timer = threading.Timer(hedge_delay, hedge_run)
            timer.daemon = True
            timer.start()

        from karpenter_tpu import tracing

        try:
            for name in names:
                if name == "host":
                    break
                br = self.breaker(name)
                if not br.allow():
                    _ladder_count(name, "skipped_open")
                    tracing.add_event("rung_skipped", rung=name,
                                      reason="breaker_open")
                    continue
                budget = None
                if t_end is not None:
                    budget = t_end - time.monotonic()
                    if budget <= 0:
                        # out of wall budget: the half-open admission
                        # above was consumed without a verdict — leave
                        # the breaker as-is and degrade straight down
                        SOLVER_DEADLINE_EXCEEDED.inc({"phase": "total"})
                        _ladder_count(name, "skipped_deadline")
                        tracing.add_event("rung_skipped", rung=name,
                                          reason="deadline")
                        break
                with tracing.span("solver.rung", rung=name) as rsp:
                    try:
                        fn = self._rung_fn(
                            name, enc, max_nodes, mode, plan, shards)
                        result = self._attempt(
                            name, fn, budget, compile_budget)
                    except Exception as err:
                        reason = classify(err)
                        br.record_failure(reason)
                        _ladder_count(name, reason)
                        rsp.annotate(outcome=reason)
                        log.warning(
                            "solver rung %s failed (%s: %s); degrading",
                            name, reason, err)
                        continue
                    rsp.annotate(outcome="ok")
                br.record_success()
                _ladder_count(name, "ok")
                _note_rung(served, name, degraded=(name != primary))
                if hedge is not None and hedge["fired"].is_set():
                    SOLVER_HEDGE.inc({"outcome": "loss"})
                return result

            # every device/remote rung failed, was skipped, or the
            # deadline ran out: the host oracle answers, via the hedge
            # if it already fired
            if hedge is not None:
                timer.cancel()
                if hedge["fired"].is_set():
                    hedge["done"].wait()
                    if hedge["result"] is not None:
                        SOLVER_HEDGE.inc({"outcome": "win"})
                        _ladder_count("host", "ok")
                        tracing.add_event("hedge_win", rung="host")
                        _note_rung(served, "host",
                                   degraded=(primary != "host"))
                        return hedge["result"]
            with tracing.span("solver.rung", rung="host") as rsp:
                result = host_pack_result(enc, max_nodes, mode)
                rsp.annotate(outcome="ok")
            _ladder_count("host", "ok")
            _note_rung(served, "host", degraded=(primary != "host"))
            return result
        finally:
            if timer is not None:
                timer.cancel()
            if hedge is not None:
                hedge["cancel"] = True

    def solve_packing_async(self, enc, max_nodes: int = 0, mode: str = "ffd",
                            plan=None, shards: int = 0):
        """Async variant preserving the kernel's true async dispatch on
        the healthy path: when the first rung is a local device rung
        with a closed breaker and no deadline is configured, dispatch
        through pack.solve_packing_async unchanged and guard only the
        fetch. Anything else (remote-first, open breaker, deadlines,
        hedge) runs the full resilient solve on a worker thread — the
        caller still overlaps host work against it."""
        names = self._rungs(shards)
        served = _served_list()  # the caller thread's report sink
        deadline_mode = (
            _env_ms("KARPENTER_SOLVE_DEADLINE_MS")
            or _env_ms("KARPENTER_COMPILE_DEADLINE_MS")
            or _env_ms("KARPENTER_SOLVE_HEDGE_MS")
        )
        first = names[0]
        if not deadline_mode and first in ("device", "sharded"):
            br = self.breaker(first)
            if br.allow():
                from karpenter_tpu.solver.pack import solve_packing_async

                eff = (
                    self._effective_shards(shards)
                    if first == "sharded"
                    else (1 if self._effective_shards(shards) > 1 else shards)
                )
                tail = names[1:]
                try:
                    pending = solve_packing_async(
                        enc, max_nodes=max_nodes, mode=mode, plan=plan,
                        shards=eff)
                except Exception as err:
                    reason = classify(err)
                    br.record_failure(reason)
                    _ladder_count(first, reason)
                    log.warning(
                        "solver rung %s failed at dispatch (%s: %s); "
                        "degrading", first, reason, err)
                    return _LazyPending(lambda: self._ladder(
                        tail, enc, max_nodes, mode, plan, shards,
                        served=served, primary=first))
                return _GuardedPending(
                    self, first, pending,
                    lambda: self._ladder(
                        tail, enc, max_nodes, mode, plan, shards,
                        served=served, primary=first))
            _ladder_count(first, "skipped_open")
            names = names[1:]
            if names == ["host"]:
                return _LazyPending(
                    lambda: self._ladder(
                        names, enc, max_nodes, mode, plan, shards,
                        served=served, primary=first))
        ex = self._get_executor()
        return ex.submit(
            self._ladder, names, enc, max_nodes, mode, plan, shards,
            served=served, primary=first)

    def _get_executor(self):
        with self._executor_lock:
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor

                # sized like solver._rpc_executor: the cost objective's
                # two concurrent solves plus sibling simulations
                self._executor = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="solver-resilient")
            return self._executor


# -- process-wide instance ----------------------------------------------------

_shared: Optional[ResilientSolver] = None
_shared_lock = threading.Lock()


def shared() -> ResilientSolver:
    global _shared
    if _shared is None:
        with _shared_lock:
            if _shared is None:
                _shared = ResilientSolver()
    return _shared


def reset() -> None:
    """Tests: drop breaker state and thread-local degradation notes."""
    global _shared
    with _shared_lock:
        _shared = None
    if getattr(_tlocal, "served", None):
        _tlocal.served.clear()
