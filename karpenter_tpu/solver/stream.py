"""Streaming device staging for sharded solves (ISSUE 11 tentpole b).

`pack._run_pack`'s classic staging materializes every padded
config-axis matrix host-side (the [Gp, Cp] compat block plus the
[Cp, R] / [Cp] cost vectors), copies each into a jax array, and lets
`device_put` split it over the mesh — up to three full-size host
allocations per matrix before the kernel sees a byte. At million-pod
shapes the padded group x config matrix is the largest host-side
solver array, and that full-materialization peak is what caps the
problem size a control-plane host can stage.

This module ships the same arrays as PER-SHARD COLUMN BLOCKS instead:
for each mesh device, build only that shard's padded slice (padding
and slicing fused into one fill callback), place it directly on its
device, free the host block, move on. The assembled array
(`jax.make_array_from_single_device_arrays`) is indistinguishable to
the compiled kernel from the `device_put` result — same sharding, same
values — so solves are bit-identical to the classic staging
(oracle-enforced: tests/test_wavefront_oracle.py,
tests/test_stream_encode.py). Host transient peak per matrix drops
from ~2-3x the full padded size to one 1/shards-width block.

Knob: KARPENTER_STREAM_ENCODE — "auto" (default: stream whenever the
solve is sharded), "0"/"off" (always classic), "1"/"on"/"force"
(stream sharded solves; an unsharded solve has no mesh to stream onto
and always stages classically). Stats of the most recent streamed
staging are kept per-process (`last_stats`) so the million_pod bench
can report/assert the peak-block-vs-full-materialization bytes next
to its measured RSS.
"""

from __future__ import annotations

import os
import threading
from typing import Callable

import numpy as np

_lock = threading.Lock()
_last: dict = {}


def enabled() -> bool:
    """Resolve KARPENTER_STREAM_ENCODE for a sharded solve (the only
    caller context — unsharded staging never consults this)."""
    raw = os.environ.get("KARPENTER_STREAM_ENCODE", "auto").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return False
    # auto / 1 / on / force / unrecognized spellings: stream. The
    # classic path stays reachable via the explicit off switch only —
    # streaming is value-identical, so there is no backend to be
    # conservative about.
    return True


def reset_stats() -> None:
    with _lock:
        _last.clear()


def last_stats() -> dict:
    """Staging stats of the most recent streamed solve on any thread:
    {"arrays", "blocks", "peak_block_bytes", "full_bytes"} —
    full_bytes is what ONE full-materialization copy of every streamed
    matrix would have allocated host-side (the classic path makes 2-3
    such copies per matrix; peak_block_bytes is the streamed path's
    largest single host transient)."""
    with _lock:
        return dict(_last)


class _Staging:
    """Accumulates per-solve stats across the stage() calls of one
    staging pass; commit() publishes them as last_stats()."""

    def __init__(self):
        self.arrays = 0
        self.blocks = 0
        self.peak_block_bytes = 0
        self.full_bytes = 0

    def commit(self) -> None:
        from karpenter_tpu.metrics.store import SOLVER_STREAM_BLOCKS

        SOLVER_STREAM_BLOCKS.inc(value=self.blocks)
        stats = dict(
            arrays=self.arrays,
            blocks=self.blocks,
            peak_block_bytes=self.peak_block_bytes,
            full_bytes=self.full_bytes,
        )
        with _lock:
            _last.clear()
            _last.update(stats)
        # unified staging attribution (ISSUE 13): the same per-solve
        # stats land on the device-telemetry gauges and in the per-arm
        # device_telemetry block next to the compiled-program peaks
        from karpenter_tpu.solver import telemetry

        telemetry.note_staging(stats)


def stage(
    mesh,
    spec,
    shape: tuple,
    dtype,
    fill: Callable[[tuple], np.ndarray],
    staging: _Staging | None = None,
):
    """Assemble a global sharded array from per-device blocks built one
    at a time. `fill(index)` receives the device's index tuple (slices
    into the global shape) and returns that block as a host array —
    already padded, already the right dtype; it is shipped to the
    device and released before the next block is built, so the host
    transient is one block, never the full matrix."""
    import jax
    from jax.sharding import NamedSharding, SingleDeviceSharding

    sharding = NamedSharding(mesh, spec)
    imap = sharding.addressable_devices_indices_map(shape)
    arrays = []
    for dev, idx in imap.items():
        block = np.ascontiguousarray(fill(idx))
        if staging is not None:
            staging.blocks += 1
            staging.peak_block_bytes = max(
                staging.peak_block_bytes, block.nbytes
            )
        arrays.append(jax.device_put(block, SingleDeviceSharding(dev)))
        del block
    if staging is not None:
        staging.arrays += 1
        staging.full_bytes += int(
            np.prod(shape) * np.dtype(dtype).itemsize
        )
    return jax.make_array_from_single_device_arrays(shape, sharding, arrays)


def col_fill_2d(src: np.ndarray, rows: int, real_rows: int, real_cols: int,
                dtype):
    """Fill callback for a [rows, Cp] matrix sharded over its COLUMN
    axis: pads rows beyond `real_rows` and columns beyond `real_cols`
    with zeros, copying only the live window of `src` ([real_rows,
    real_cols])."""

    def fill(idx):
        _, cs = idx
        lo = cs.start or 0
        hi = cs.stop if cs.stop is not None else src.shape[1]
        blk = np.zeros((rows, hi - lo), dtype)
        if lo < real_cols:
            take = min(hi, real_cols) - lo
            blk[:real_rows, :take] = src[:, lo : lo + take]
        return blk

    return fill


def row_fill_2d(src: np.ndarray, cols: int, real_rows: int, dtype):
    """Fill callback for a [Cp, cols] matrix sharded over its ROW
    (config) axis."""

    def fill(idx):
        rs, _ = idx
        lo = rs.start or 0
        hi = rs.stop
        blk = np.zeros((hi - lo, cols), dtype)
        if lo < real_rows:
            take = min(hi, real_rows) - lo
            blk[:take] = src[lo : lo + take]
        return blk

    return fill


def vec_fill(src: np.ndarray, real_len: int, dtype, pad_value=0):
    """Fill callback for a [Cp] vector sharded over the config axis."""

    def fill(idx):
        (cs,) = idx
        lo = cs.start or 0
        hi = cs.stop
        blk = np.full((hi - lo,), pad_value, dtype)
        if lo < real_len:
            take = min(hi, real_len) - lo
            blk[:take] = src[lo : lo + take]
        return blk

    return fill
