"""Pure-Python per-pod first-fit-decreasing oracle.

Implements the reference scheduler's decision procedure
(scheduler.go:434-647) directly over the encoded problem: pods in
size-descending order, each tried against nodes in index order
(existing first), else a new node on the highest-weight admitting
pool. Used as (a) the parity oracle for the JAX packing kernel and
(b) the in-process fallback when no accelerator is available — the
role the north star assigns to the Go FFD fallback.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.solver.encode import Encoded


@dataclass
class _Node:
    mask: np.ndarray           # [C] bool
    used: np.ndarray           # [R] float32
    assign: dict[int, int] = field(default_factory=dict)  # group -> count
    quota: np.ndarray | None = None  # [G] remaining per-group cap (existing)


def solve_ffd_host(enc: Encoded) -> tuple[list[_Node], dict[int, int]]:
    """Returns (nodes, unschedulable{group: count})."""
    C = len(enc.configs)
    alloc = enc.cfg_alloc  # [C, R]
    # reservation budgets shared per reservation id, not per column
    cfg_rsv = (
        enc.cfg_rsv if enc.cfg_rsv is not None else np.full((C,), -1, np.int32)
    )
    rsv_cap = (
        enc.rsv_cap.astype(np.float64)
        if enc.rsv_cap is not None
        else np.zeros((0,), np.float64)
    )
    capped = cfg_rsv >= 0
    rsv_used = np.zeros(len(rsv_cap), np.float64)
    G = len(enc.groups)
    # lowered topology constraints (solver/topo_batch.py) — the host
    # oracle must enforce the same per-node caps / group conflicts /
    # existing-node quotas the device kernel does
    group_cap = (
        enc.group_cap.astype(np.int64)
        if enc.group_cap is not None
        else np.full((G,), np.iinfo(np.int64).max, np.int64)
    )
    conflict = enc.conflict if enc.conflict is not None else None
    nodes: list[_Node] = []
    for ei in range(enc.n_existing):
        mask = np.zeros((C,), bool)
        for ci, cfg in enumerate(enc.configs):
            if cfg.existing_index == ei:
                mask[ci] = True
        quota = (
            enc.existing_quota[ei].astype(np.int64)
            if enc.existing_quota is not None
            else None
        )
        nodes.append(
            _Node(mask=mask, used=enc.existing_used[ei].copy(), quota=quota)
        )
    unschedulable: dict[int, int] = {}

    def node_admits(node: _Node, gi: int) -> bool:
        have = node.assign.get(gi, 0)
        cap = group_cap[gi]
        if node.quota is not None:
            cap = min(cap, node.quota[gi])
        if have >= cap:
            return False
        if conflict is not None:
            for other, count in node.assign.items():
                if count > 0 and conflict[gi, other]:
                    return False
        return True

    for gi in range(G):
        req = enc.group_req[gi]
        row = enc.compat[gi]
        for _ in range(int(enc.group_count[gi])):
            placed = False
            for node in nodes:
                if not node_admits(node, gi):
                    continue
                ok = node.mask & row & np.all(node.used[None, :] + req[None, :] <= alloc + 1e-4, axis=1)
                if ok.any():
                    node.mask = ok
                    node.used = node.used + req
                    node.assign[gi] = node.assign.get(gi, 0) + 1
                    placed = True
                    break
            if placed:
                continue
            # open new node on highest-weight (lowest index) admitting pool
            if len(rsv_cap):
                slot = np.clip(cfg_rsv, 0, None)
                budget_ok = ~capped | (rsv_used[slot] < rsv_cap[slot])
            else:
                budget_ok = np.ones((C,), bool)
            fresh = row & (enc.cfg_pool >= 0) & budget_ok
            overhead = enc.pool_overhead[enc.cfg_pool]
            fresh &= np.all(overhead + req[None, :] <= alloc + 1e-4, axis=1)
            if not fresh.any():
                unschedulable[gi] = unschedulable.get(gi, 0) + 1
                continue
            pool = int(enc.cfg_pool[fresh].min())
            mask = fresh & (enc.cfg_pool == pool)
            # a reserved (capped) column pins the node and consumes one
            # reservation instance; otherwise capped columns drop from
            # the option mask (ReservationManager semantics)
            reserved_opts = np.flatnonzero(mask & capped)
            if reserved_opts.size and enc.cfg_price is not None and (
                enc.cfg_price[reserved_opts].min()
                <= enc.cfg_price[mask].min() + 1e-12
            ):
                pin = reserved_opts[np.argmin(enc.cfg_price[reserved_opts])]
                mask = np.zeros((C,), bool)
                mask[pin] = True
                rsv_used[cfg_rsv[pin]] += 1
            else:
                # an uncapped option is strictly cheaper, so at least
                # one survives the filter
                mask = mask & ~capped
            node = _Node(mask=mask, used=enc.pool_overhead[pool] + req)
            node.assign[gi] = 1
            nodes.append(node)
    return nodes, unschedulable
