"""Solver facade: encoded problem -> per-node placements.

This is the `scheduling.Solver` seam the north star describes: the
provisioning scheduler and the consolidation engine call `solve()`
with pods + catalogs + existing nodes and get back node plans
(which pool/instance-types/offering each planned node resolves to and
which pods land where). Backend is the JAX packing kernel
(`solver.pack`) with the host FFD oracle as fallback.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.solver.encode import (
    Encoded,
    ExistingNodeInput,
    PodGroup,
    encode,
    group_pods,
)


class NodePlan:
    """One planned (new) node.

    `instance_types` (price-ordered options) and `offerings` (feasible,
    cheapest first) materialize lazily from the solver's config mask:
    a 50k-pod solve plans thousands of nodes but only the ones that
    become NodeClaims ever need their full option lists expanded.
    Both attributes remain assignable (the scheduler truncates them,
    consolidation filters them)."""

    def __init__(
        self,
        pool: NodePool,
        instance_types: Optional[list[InstanceType]] = None,
        offerings: Optional[list[Offering]] = None,
        pods: Optional[list[Pod]] = None,
        price: float = 0.0,
        claim_name: str = "",
        lazy=None,
        lazy_primary=None,
    ):
        self.pool = pool
        self._instance_types = instance_types
        self._offerings = offerings
        self._lazy = lazy
        self._lazy_primary = lazy_primary
        self.pods: list[Pod] = pods if pods is not None else []
        self.price = price
        self.claim_name = claim_name
        # set when BestEffort minValues policy relaxed the floor
        # (scheduler.go:649-658 / min-values-relaxed annotation)
        self.min_values_relaxed = False
        # reservation id this node resolves onto (its cheapest feasible
        # offering is reserved) — the claim will consume one instance
        # of that reservation's budget (reservationmanager.go)
        self.reservation_id = ""

    def _materialize(self) -> None:
        its, offs = self._lazy()
        if self._instance_types is None:
            self._instance_types = its
        if self._offerings is None:
            self._offerings = offs
        self._lazy = None

    @property
    def instance_types(self) -> list[InstanceType]:
        if self._instance_types is None and self._lazy is not None:
            self._materialize()
        return self._instance_types if self._instance_types is not None else []

    @instance_types.setter
    def instance_types(self, value: list[InstanceType]) -> None:
        self._instance_types = value

    @property
    def offerings(self) -> list[Offering]:
        if self._offerings is None and self._lazy is not None:
            self._materialize()
        return self._offerings if self._offerings is not None else []

    @offerings.setter
    def offerings(self, value: list[Offering]) -> None:
        self._offerings = value

    def primary(self) -> tuple[Optional[InstanceType], Optional[Offering]]:
        """The resolved (cheapest) launch option WITHOUT materializing
        the full option lists — the incremental pipeline adopts
        thousands of plans per full solve and needs only the launch
        target per node, not the sorted member expansion."""
        if (
            self._lazy_primary is not None
            and self._instance_types is None
            and self._offerings is None
        ):
            return self._lazy_primary()
        its, offs = self.instance_types, self.offerings
        return (its[0] if its else None), (offs[0] if offs else None)


@dataclass
class ExistingAssignment:
    existing_index: int
    pods: list[Pod] = field(default_factory=list)


@dataclass
class Solution:
    new_nodes: list[NodePlan]
    existing: list[ExistingAssignment]
    unschedulable: list[Pod]
    # subset of `unschedulable` displaced by the decode-time k-way
    # requirement check (not kernel-infeasible): schedulable alone, so
    # the caller should retry them unrelaxed
    evicted: list[Pod] = field(default_factory=list)
    # cost-objective solves attach the planner's bounds here so callers
    # can report optimality gaps without re-running column generation:
    # {"lower_bound": linear resource bound, "estimate": master-LP value}
    lp: Optional[dict] = None

    @property
    def total_price(self) -> float:
        return sum(n.price for n in self.new_nodes)


def _merge_budget_pairs() -> int:
    """Work budget (pair feasibility checks) for the post-pack merge
    improvement. A WORK budget, not a wall deadline: identical inputs
    must produce identical fleets regardless of machine load — the
    steady-state skip, the sharded-equality dryrun, and concurrent
    solves all rely on solve() being a pure function of its inputs.
    Read per call like every other solver env knob; 0 disables."""
    return int(os.environ.get("KARPENTER_MERGE_BUDGET", "12000"))


def _uncapped_cols(enc: Encoded) -> np.ndarray:
    """[C] bool: columns not drawing on a capacity reservation."""
    return (
        enc.cfg_rsv < 0 if enc.cfg_rsv is not None
        else np.ones(len(enc.configs), bool)
    )


def _fresh_uncapped_cols(enc: Encoded, masks: np.ndarray, ni: int,
                         uncapped: np.ndarray):
    """The shared eligibility gate of the mask post-passes (downsize,
    merge): a node is resizable only if it is FRESH (not an existing
    node) and its mask touches no reservation-capped column. Returns
    the mask's columns, or None if the node is off-limits."""
    cols = np.flatnonzero(masks[ni])
    if cols.size == 0:
        return None
    if enc.configs[cols[0]].existing_index >= 0:
        return None
    if not uncapped[cols].all():
        return None
    return cols


def _backend() -> str:
    return os.environ.get("KARPENTER_SOLVER_BACKEND", "jax")


_remote_solver = None
_remote_lock = __import__("threading").Lock()


def _remote_client():
    global _remote_solver
    from karpenter_tpu.service.client import endpoint_from_env

    endpoint = endpoint_from_env()
    if not endpoint:
        return None
    with _remote_lock:
        if _remote_solver is None or _remote_solver.endpoint != endpoint:
            from karpenter_tpu.service.client import RemoteSolver

            if _remote_solver is not None:
                _remote_solver.close()  # don't leak the old channel
            _remote_solver = RemoteSolver(endpoint)
        return _remote_solver


def _solve_packing(enc, **kwargs):
    """The solver seam, routed through the resilience layer
    (solver/resilience.py): the degradation ladder tries the remote
    service (when KARPENTER_SOLVER_ENDPOINT points at the TPU hosts —
    SURVEY §5.8), the sharded and single-device kernels, and finally
    the host FFD oracle, under per-backend circuit breakers and the
    optional watchdog deadline. Every call returns a PackResult —
    degraded, perhaps, but never absent.

    Device rungs resolve the KARPENTER_WAVEFRONT knob at dispatch
    (pack.wavefront_plan): solves with enough pod groups run the
    wavefront kernel — many independent groups committed per device
    step, bit-identical to the sequential loop — while small solves,
    sharded solves, and the knob's off state keep the sequential
    fori_loop. Everything stacked on this seam (the cost race, the
    incremental repack, topology lowering) inherits the routing."""
    from karpenter_tpu.solver import resilience

    return resilience.shared().solve_packing(enc, **kwargs)


def _solve_packing_async(enc, **kwargs):
    """Dispatch a solve without blocking, with the same ladder
    guarding the fetch: healthy local solves keep the kernel's true
    async dispatch (the device computes while the host keeps working);
    remote or deadline-budgeted solves run on a worker pool. Returns
    an object with .result() -> PackResult."""
    from karpenter_tpu.solver import resilience

    return resilience.shared().solve_packing_async(enc, **kwargs)


def solve(
    pods: Sequence[Pod],
    pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
    existing: Sequence[ExistingNodeInput] = (),
    daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
    required_only: bool = False,
    backend: Optional[str] = None,
    objective: str = "ffd",
    shards: int = 0,
    compat_cache=None,
) -> Solution:
    """`compat_cache` (solver/incremental.EncodedCache) memoizes the
    launchable config columns + compat rows across solves — see
    encode()."""
    groups = group_pods(pods, required_only=required_only)
    enc = encode(
        groups, pools_with_types, existing, daemon_overhead,
        compat_cache=compat_cache,
    )
    return solve_encoded(enc, backend=backend, objective=objective, shards=shards)


def solve_encoded(
    enc: Encoded, backend: Optional[str] = None, objective: str = "ffd",
    shards: int = 0, price_hint: Optional[np.ndarray] = None,
) -> Solution:
    """`shards > 1` partitions the solver's config axis over a device
    mesh (see pack.solve_packing); 0 inherits KARPENTER_SOLVER_SHARDS.

    `price_hint` (ISSUE 15): an alternative [C] price vector fed to
    the PACKING KERNEL as its type-preference ordering — the same
    ordering-is-an-input contract the cost race's rank arm uses.
    Decode always prices nodes from the true `enc.cfg_price`, so a
    hinted solve's plans carry real catalog prices; the hint only
    steers which configs the kernel opens. Ignored on the host
    backend and under the cost objective (which runs its own guided
    race)."""
    G, C = enc.compat.shape
    if G == 0 or C == 0:
        return Solution(
            new_nodes=[],
            existing=[],
            unschedulable=[p for g in enc.groups for p in g.pods],
        )
    backend = backend or _backend()
    if backend == "host":
        return _decode_host(enc)
    return _decode_device(enc, objective, shards, price_hint=price_hint)


def _decode_device(
    enc: Encoded, objective: str = "ffd", shards: int = 0,
    price_hint: Optional[np.ndarray] = None,
) -> Solution:
    if objective != "cost":
        kernel_enc = enc
        if price_hint is not None:
            from dataclasses import replace as _hint_replace

            kernel_enc = _hint_replace(
                enc, cfg_price=np.asarray(price_hint, np.float32)
            )
        result = _solve_packing(kernel_enc, mode=objective, shards=shards)
        return _build_solution_arrays(
            enc,
            np.flatnonzero(result.node_active[: result.node_count]),
            result.node_mask,
            result.assign,
            result.unschedulable,
        )

    # Cost objective: LP-planned packing raced against plain FFD; the
    # cheaper fleet wins (fewer unschedulable pods first). FFD is thus
    # a floor — the planner can only ever improve on the greedy
    # heuristic, never regress it (the LP's restricted pattern set can
    # be weak on small or degenerate demands).
    #
    # The whole race is a pipeline around ONE device: dispatch the FFD
    # kernel (async), run column generation on the host while it packs,
    # dispatch the planned kernel (its input upload overlaps the FFD
    # tail), decode/downsize the FFD result while the planned kernel
    # runs, then fetch the planned result. Host and device are both
    # busy end to end; nothing waits that doesn't have to.
    #
    # Both kernels' buffers are device-resident at once; that is the
    # deliberate price of the overlap and it is small: the per-kernel
    # state is O(N x C) bools + O(N x G) ints (~100MB even at a 50k
    # node axis), against >=16GB of HBM — three orders of magnitude of
    # headroom, so no size gate is needed.
    #
    # Dual guidance (ISSUE 12, KARPENTER_LP_GUIDE): the device LP
    # relaxation (solver/lp_device.py) contributes, when healthy:
    # (a) a third COLD race arm — the planned pack re-dispatched with
    #     dual-adjusted reduced-cost ranking as the kernel's price
    #     input (ordering is an input; kernel body unchanged; decode
    #     prices from the true enc.cfg_price) — strictly additive, so
    #     the race result is never worse than unguided;
    # (b) the dual-guided trim post-pass on the winner
    #     (_trim_undervalued below) — this is where the integrality
    #     gap actually closes — applied AFTER the race keys and the
    #     recorded FFD floor, so selection semantics are unchanged;
    # (c) a certified lower bound reported on Solution.lp.
    # LP failure degrades to exactly the unguided path (maybe_solve
    # returns None); warm steady-state solves re-run only the winning
    # arm, so the p50 wall stays that of one kernel dispatch.
    from dataclasses import replace as _replace

    from karpenter_tpu.solver import lp_device, lp_plan

    def key(item):
        # Only nodes that actually hold pods count: pre-opened planned
        # slots the packer never filled are skipped by decode, so they
        # must not bias the race either.
        result, masks = item
        act = np.flatnonzero(
            result.node_active[: result.node_count]
            & (result.assign[: result.node_count].sum(axis=1) > 0)
        )
        prices = np.where(masks[act], enc.cfg_price[None, :], np.inf).min(axis=1)
        fleet = float(np.where(np.isfinite(prices), prices, 0.0).sum())
        return (int(result.unschedulable.sum()), fleet, len(act))

    # Steady-state race skip: FFD is deterministic per problem, so its
    # full race key from the last identical solve IS what re-running
    # it would produce. When the planned pack STRICTLY beats that
    # recorded floor (min() prefers the FFD candidate on full ties),
    # the answer is identical to racing — and the wall clock drops by
    # the whole FFD kernel (the two kernels serialize on one device).
    fp = _race_fingerprint(enc)
    floor = _ffd_floor.get(fp)
    plan = None
    cost_tuple = None  # (result, masks, arm)
    # NOTE: the LP deliberately does NOT inherit the pack's shard
    # count — its tensors are tiny at any fleet size, and an unsharded
    # ascent keeps the duals identical across pack shard counts (the
    # sharded-equality contracts). KARPENTER_LP_SHARDS is the opt-in.
    dlp = lp_device.maybe_solve(enc)

    def arm_enc(arm: str) -> Encoded:
        """The encode an arm's KERNEL sees. The rank arm feeds the
        dual-adjusted type-preference ranking through the kernel's
        cfg_price input — ordering is an input, the kernel body is
        unchanged — while every decode/key/merge site in this function
        keeps reading the true prices from the original `enc`."""
        if arm == "rank" and dlp is not None:
            return _replace(enc, cfg_price=lp_device.rank_prices(enc, dlp))
        return enc

    def guide_lam():
        if plan is not None and plan.duals is not None:
            return plan.duals
        return dlp.lam_guide if dlp is not None else None

    def lp_info(trim_saved: float):
        if plan is None and dlp is None:
            return None
        info: dict = {"guided": dlp is not None,
                      "trim_saved": round(float(trim_saved), 6)}
        if plan is not None:
            info["lower_bound"] = plan.lower_bound
            info["estimate"] = plan.objective_estimate
        if dlp is not None:
            info["device_bound"] = dlp.lower_bound
            info["device_wall_s"] = round(dlp.wall_s, 6)
            info["device_iterations"] = dlp.iterations
            info["device_converged"] = dlp.converged
            info.setdefault("lower_bound", dlp.lower_bound)
        return info

    if floor is not None:
        plan = _plan_for(fp, enc)
        if plan is not None:
            arm = _warm_arm.get(fp, "cost")
            cost_result = _solve_packing(
                arm_enc(arm), mode="cost", plan=plan, shards=shards
            )
            masks = _downsize_masks(enc, cost_result)
            cost_tuple = (cost_result, masks, arm)
            if key((cost_result, masks)) < floor:
                trim_saved = _finish_winner(
                    enc, cost_result, masks, guide_lam()
                )
                solution = _build_solution_arrays(
                    enc,
                    np.flatnonzero(
                        cost_result.node_active[: cost_result.node_count]
                    ),
                    masks,
                    cost_result.assign,
                    cost_result.unschedulable,
                )
                solution.lp = lp_info(trim_saved)
                return solution
        # planned pack missing or not strictly better than the
        # recorded floor: fall through to the race, reusing the plan
        # AND the already-computed cost pack

    ffd_pending = _solve_packing_async(enc, mode="ffd", shards=shards)
    if plan is None:
        plan = _plan_for(fp, enc)
    pendings: list[tuple[str, object]] = []
    if plan is not None and cost_tuple is None:
        pendings.append((
            "cost",
            _solve_packing_async(enc, mode="cost", plan=plan, shards=shards),
        ))
        if dlp is not None and lp_device.rank_beta() > 0:
            # the guided-ranking arm joins the COLD race only — warm
            # solves re-run just the recorded winner, so steady-state
            # wall stays one kernel
            pendings.append((
                "rank",
                _solve_packing_async(
                    arm_enc("rank"), mode="cost", plan=plan, shards=shards
                ),
            ))
    ffd_result = ffd_pending.result()
    candidates = [(ffd_result, _downsize_masks(enc, ffd_result), "ffd")]
    if cost_tuple is not None:
        candidates.append(cost_tuple)
    for arm, pending in pendings:
        arm_result = pending.result()
        candidates.append((arm_result, _downsize_masks(enc, arm_result), arm))

    if len(_ffd_floor) >= 32:
        _ffd_floor.pop(next(iter(_ffd_floor)))
    _ffd_floor[fp] = key(candidates[0][:2])

    result, masks, won = min(candidates, key=lambda it: key(it[:2]))
    if len(_warm_arm) >= 32:
        _warm_arm.pop(next(iter(_warm_arm)))
    _warm_arm[fp] = won if won in ("cost", "rank") else "cost"
    # improvement pass on the WINNER only — after the race keys (and
    # the recorded FFD floor) were computed, so selection semantics
    # and the steady-state skip stay bit-identical
    trim_saved = _finish_winner(enc, result, masks, guide_lam())
    solution = _build_solution_arrays(
        enc,
        np.flatnonzero(result.node_active[: result.node_count]),
        masks,
        result.assign,
        result.unschedulable,
    )
    solution.lp = lp_info(trim_saved)
    return solution


# last FFD race key per problem fingerprint: (unschedulable, fleet
# price, active node count) — the FULL race key, so the steady-state
# skip reproduces min()'s exact tiebreaks. Bounded dict (oldest
# evicted at 32 entries).
_ffd_floor: dict[bytes, tuple[int, float, int]] = {}

# which cost arm won the last cold race per fingerprint ("cost" |
# "rank") — the warm steady-state skip re-runs only that arm
_warm_arm: dict[bytes, str] = {}

# column-generation plan per problem fingerprint: the plan is a pure
# function of the encoded problem (deterministic pricing rounds), so a
# repeated solve reuses it instead of re-running ~150ms of host LP.
# The fingerprint covers every array the LP reads (demand, prices,
# allocs, compat, reservations), and consumers never mutate a
# FleetPlan, so a hit is exactly the plan a fresh run would build.
_plan_cache: dict[bytes, object] = {}


def _plan_for(fp: bytes, enc: Encoded):
    from karpenter_tpu.solver import lp_plan

    if fp in _plan_cache:
        return _plan_cache[fp]
    plan = lp_plan.plan(enc)
    # small cap: a FleetPlan carries planned_quota [Np, G] (MBs at 50k
    # pods), so unlike _ffd_floor's 3-tuples this cache trades real RAM
    # for the ~150ms LP — keep only the working set
    if len(_plan_cache) >= 4:
        _plan_cache.pop(next(iter(_plan_cache)))
    _plan_cache[fp] = plan
    return plan


def _race_fingerprint(enc: Encoded) -> bytes:
    """Digest of everything the FFD kernel's outcome depends on — plus
    the dual-guidance configuration, so guided and unguided runs of
    the same problem (the bench's comparison arms, a mid-flight knob
    flip) can never serve each other's cached floors or plans."""
    import hashlib

    from karpenter_tpu.solver import lp_device

    h = hashlib.blake2b(digest_size=16)
    h.update(
        (
            f"g{int(lp_device.enabled())}|b{lp_device.rank_beta()}"
            f"|i{lp_device.iters()}|t{_trim_budget()}"
            f"|p{os.environ.get('KARPENTER_LP_PRIORITY_WEIGHT', '')}"
        ).encode()
    )
    for buf in (
        enc.group_count, enc.group_req, enc.cfg_price, enc.cfg_alloc,
        np.ascontiguousarray(enc.compat), enc.cfg_pool,
        enc.pool_overhead, enc.existing_used,
    ):
        h.update(np.ascontiguousarray(buf).tobytes())
    for opt in (
        enc.cfg_rsv, enc.rsv_cap, enc.group_cap, enc.conflict,
        enc.existing_quota, enc.loose_groups, enc.group_priority,
    ):
        h.update(
            b"\x00" if opt is None
            else np.ascontiguousarray(opt).tobytes()
        )
    h.update(enc.n_existing.to_bytes(4, "little"))
    return h.digest()


def _merge_underfilled(enc: Encoded, result, masks: np.ndarray) -> None:
    """Host-side improvement pass on a finished cost pack: greedily
    merge pairs of FRESH nodes when one machine that holds both loads
    is cheaper than the two they would launch as. FFD fragmentation
    under selector/taint-split demand leaves tails of underfilled
    nodes; the LP cannot see them (its patterns are per-class optimal
    but integrality strands remainders). Mutates `result` and `masks`
    in place.

    Feasibility comes straight from the DOWNSIZED masks: downsize
    widens each fresh node's mask to every same-pool config that is
    compatible with all residents AND fits its current load — any
    config fitting the merged load fits both current loads, so
    mask_i & mask_j & fits(combined) is EXACTLY the merged node's
    valid launch set (compat, pool and reservation rules included).
    Additional guards: no loose-group residents (k-way legality is
    re-judged at decode), per-node group caps, pairwise group
    conflicts, pool daemon overhead counted once."""
    n = result.node_count
    if n == 0:
        return
    active = result.node_active[:n] & (result.assign[:n].sum(axis=1) > 0)
    uncapped = _uncapped_cols(enc)
    cand: list[int] = []
    cand_pool: list[int] = []
    for ni in np.flatnonzero(active):
        cols = _fresh_uncapped_cols(enc, masks, ni, uncapped)
        if cols is None:
            continue
        if enc.loose_groups is not None and (
            enc.loose_groups & (result.assign[ni] > 0)
        ).any():
            continue
        pool = int(enc.cfg_pool[cols[0]])
        if enc.pool_min_values is not None and enc.pool_min_values[pool]:
            # a minValues pool: narrowing the mask could drop the
            # plan's type coverage below the floor and turn an
            # optional optimization into unschedulable pods
            continue
        # mergeable in principle: some masked config could hold about
        # twice this load (cheap prefilter; exact check is per-pair)
        pool = int(enc.cfg_pool[cols[0]])
        oh = enc.pool_overhead[pool]
        doubled = 2.0 * result.node_used[ni] - oh
        if not (enc.cfg_alloc[cols] + 1e-4 >= doubled[None, :]).all(
            axis=1
        ).any():
            continue
        cand.append(int(ni))
        cand_pool.append(pool)
    if len(cand) < 2:
        return
    pool_of = dict(zip(cand, cand_pool))
    order = sorted(cand, key=lambda x: float(result.node_used[x].sum()))
    caps = enc.group_cap
    conflict = enc.conflict
    # fast pair pruning: bit-packed masks for O(C/64) intersection
    # tests, plus a per-pool "largest machine" envelope so partners
    # whose combined load can't fit ANY config are skipped in one
    # vectorized sweep per anchor
    m = len(order)
    packed = np.packbits(masks[order], axis=1)
    used = result.node_used[np.array(order)]
    pools = np.array([pool_of[ni] for ni in order], np.int32)
    launch_cols = enc.cfg_pool >= 0
    pool_max: dict[int, np.ndarray] = {}
    for pool in np.unique(pools):
        pcols = launch_cols & (enc.cfg_pool == pool)
        pool_max[int(pool)] = enc.cfg_alloc[pcols].max(axis=0)
    alive = np.ones(m, bool)
    # current cheapest launch price per candidate (decode's choice),
    # maintained incrementally — recomputing it per pair would put two
    # full-C reductions on every probe
    p_cur = np.array([
        float(enc.cfg_price[masks[ni]].min()) for ni in order
    ])
    budget = _merge_budget_pairs()
    for a in range(m):
        if not alive[a] or budget <= 0:
            continue
        merged_any = True
        while merged_any:
            merged_any = False
            pool = int(pools[a])
            oh = enc.pool_overhead[pool]
            envelope = pool_max[pool] + oh
            quick = (
                alive
                & (pools == pools[a])
                & (
                    (used + used[a][None, :])
                    <= envelope[None, :] + 1e-4
                ).all(axis=1)
            )
            quick[a] = False
            # largest partner first: densest merged node
            for b in np.flatnonzero(quick)[::-1]:
                if budget <= 0:
                    break
                budget -= 1
                if not (packed[a] & packed[b]).any():
                    continue
                na, nb = order[a], order[b]
                shared = masks[na] & masks[nb]
                cols = np.flatnonzero(shared)
                combined = used[a] + used[b] - oh
                fits = (
                    enc.cfg_alloc[cols] + 1e-4 >= combined[None, :]
                ).all(axis=1)
                if not fits.any():
                    continue
                new_price = float(enc.cfg_price[cols[fits]].min())
                if new_price + 1e-9 >= p_cur[a] + p_cur[b]:
                    continue
                comb_assign = result.assign[na] + result.assign[nb]
                if caps is not None and (comb_assign > caps).any():
                    continue
                if conflict is not None:
                    gi = np.flatnonzero(result.assign[na] > 0)
                    gj = np.flatnonzero(result.assign[nb] > 0)
                    if conflict[np.ix_(gi, gj)].any():
                        continue
                # merge nb into na
                result.assign[na] = comb_assign
                result.node_used[na] = combined
                result.assign[nb] = 0
                result.node_active[nb] = False
                result.node_used[nb] = 0.0
                masks[nb] = False
                row = np.zeros_like(masks[na])
                row[cols[fits]] = True
                masks[na] = row
                used[a] = combined
                used[b] = 0.0
                p_cur[a] = new_price
                packed[a] = np.packbits(row)
                packed[b] = 0
                alive[b] = False
                merged_any = True
                break


def _trim_budget() -> int:
    """Receiver-feasibility checks the dual-guided trim may spend per
    solve — a WORK budget (like the merge pass's) so identical inputs
    trim identically regardless of machine load."""
    return int(os.environ.get("KARPENTER_LP_TRIM_BUDGET", "200000"))


def _trim_undervalued(enc: Encoded, result, masks: np.ndarray,
                      lam: np.ndarray, budget: int | None = None) -> float:
    """Dual-guided trim (ISSUE 12): empty the nodes the LP duals
    certify as BAD DEALS — price above the dual value of what they
    hold — by moving their pods into the rest of the fleet's headroom,
    then re-fit each donor onto the cheapest machine that still holds
    its remainder (or delete it outright). This is the integrality-gap
    closer: FFD remainders strand many slightly-underfilled machines
    whose pods fit in aggregate slack the prefix fill has already
    passed; the duals say exactly which nodes to attack
    (slack = price - lam.assign, largest first).

    Legality is re-proved per move from first principles — compat with
    the receiver's resolved config, capacity against its allocatable,
    pairwise group conflicts, per-node group caps — and receiver masks
    are narrowed to configs compatible with the incoming group that
    still fit, so decode semantics hold exactly. Nodes off-limits to
    the merge pass (existing, reservation-pinned, loose-group
    residents, minValues pools) are off-limits here for the same
    reasons. Every commit strictly lowers fleet price (receivers keep
    their resolved config by construction), so the pass can only
    improve the solution. Mutates `result`/`masks` in place; returns
    the price saved."""
    n = result.node_count
    if n == 0 or lam is None:
        return 0.0
    budget = _trim_budget() if budget is None else budget
    if budget <= 0:
        return 0.0
    active = result.node_active[:n] & (result.assign[:n].sum(axis=1) > 0)
    uncapped = _uncapped_cols(enc)
    launch = enc.cfg_pool >= 0
    loose = enc.loose_groups
    # vectorized candidate collection (the same eligibility the merge
    # pass applies per node, but in one sweep — a 50k-pod fleet has
    # thousands of active rows and this runs on every warm solve):
    # fresh (no pseudo-config column), reservation-uncapped mask, no
    # loose residents, not a minValues pool
    act_idx = np.flatnonzero(active)
    if act_idx.size < 2:
        return 0.0
    sub = masks[act_idx]
    pseudo = np.array(
        [cfg.existing_index >= 0 for cfg in enc.configs], dtype=bool
    )
    ok = (
        sub.any(axis=1)
        & ~(sub & pseudo[None, :]).any(axis=1)
        & ~(sub & ~uncapped[None, :]).any(axis=1)
    )
    if loose is not None:
        ok &= ~((result.assign[act_idx] > 0) & loose[None, :]).any(axis=1)
    price_mat = np.where(sub, enc.cfg_price[None, :], np.inf)
    pcol_all = price_mat.argmin(axis=1)
    if enc.pool_min_values is not None:
        ok &= ~enc.pool_min_values[enc.cfg_pool[pcol_all]]
    rows_a = act_idx[ok]
    if rows_a.size < 2:
        return 0.0
    rows = rows_a.tolist()
    m = len(rows)
    lam = np.asarray(lam, np.float64)
    req_all = enc.group_req.astype(np.float64)
    caps = enc.group_cap
    conflict = enc.conflict
    price = price_mat[ok].min(axis=1)
    pcol = pcol_all[ok]
    used = result.node_used[rows_a].astype(np.float64).copy()
    assign_rows = result.assign[rows_a].astype(np.int64).copy()
    alive = np.ones(m, bool)
    alloc_p = enc.cfg_alloc[pcol].astype(np.float64)  # [m, R]
    vals = assign_rows @ lam
    slack = price - vals
    donor_order = np.argsort(-slack, kind="stable")
    idx = np.arange(m)
    saved = 0.0
    for di in donor_order:
        if budget <= 0:
            break
        if not alive[di] or price[di] <= 0 or slack[di] <= 1e-9:
            continue
        pool = int(enc.cfg_pool[pcol[di]])
        gs = np.flatnonzero(assign_rows[di])
        if gs.size == 0:
            continue
        # plan the moves against a scratch copy; commit only if the
        # donor provably refits cheaper afterwards
        order_g = gs[np.argsort(-req_all[gs].sum(axis=1), kind="stable")]
        assign_d = assign_rows[di].copy()
        sim_used = used.copy()
        sim_assign = assign_rows  # reads only; adds tracked in moves
        moves: list[tuple[int, int, int]] = []
        for g in order_g:
            needed = int(assign_d[g])
            if needed == 0:
                continue
            req = req_all[g]
            reqpos = req > 0
            budget -= m
            elig = alive & (idx != di) & enc.compat[g, pcol]
            if conflict is not None and conflict[g].any():
                elig &= (sim_assign @ conflict[g].astype(np.int64)) == 0
            head = alloc_p - sim_used
            with np.errstate(divide="ignore", invalid="ignore"):
                kr = np.floor(
                    np.where(reqpos[None, :], (head + 1e-4) / np.where(
                        reqpos, req, 1.0
                    )[None, :], np.inf).min(axis=1)
                )
            kr = np.where(np.isfinite(kr), kr, 0.0)
            k = np.where(elig, np.clip(kr, 0, None), 0.0).astype(np.int64)
            if caps is not None:
                k = np.minimum(
                    k, np.clip(caps[g] - sim_assign[:, g], 0, None)
                )
            cum = np.cumsum(k)
            take = np.clip(needed - (cum - k), 0, k)
            hit = np.flatnonzero(take)
            for ri in hit:
                moves.append((int(ri), int(g), int(take[ri])))
                sim_used[ri] = sim_used[ri] + int(take[ri]) * req
            assign_d[g] = needed - int(take.sum())
        oh = enc.pool_overhead[pool].astype(np.float64)
        new_used = oh + assign_d @ req_all
        if assign_d.sum() == 0:
            new_price, new_mask = 0.0, None
        else:
            groups_on = np.flatnonzero(assign_d)
            fits = np.all(enc.cfg_alloc + 1e-4 >= new_used[None, :], axis=1)
            compat_all = enc.compat[groups_on].all(axis=0)
            ok = launch & (enc.cfg_pool == pool) & fits & compat_all & uncapped
            if not ok.any():
                continue
            new_price = float(enc.cfg_price[ok].min())
            new_mask = ok
        if new_price >= price[di] - 1e-9:
            continue
        # ---- commit
        d = rows[di]
        for ri, g, kk in moves:
            r0 = rows[ri]
            result.assign[r0, g] += kk
            assign_rows[ri, g] += kk
            add = kk * req_all[g]
            result.node_used[r0] = result.node_used[r0] + add
            used[ri] = used[ri] + add
            masks[r0] = masks[r0] & enc.compat[g] & np.all(
                enc.cfg_alloc + 1e-4 >= np.asarray(
                    result.node_used[r0], np.float64
                )[None, :],
                axis=1,
            )
        saved += price[di] - new_price
        if assign_d.sum() == 0:
            result.assign[d] = 0
            result.node_active[d] = False
            result.node_used[d] = 0.0
            masks[d] = False
            alive[di] = False
            assign_rows[di] = 0
            used[di] = 0.0
            price[di] = 0.0
        else:
            result.assign[d] = assign_d.astype(result.assign.dtype)
            result.node_used[d] = new_used
            masks[d] = new_mask
            assign_rows[di] = assign_d
            used[di] = new_used
            price[di] = new_price
            # the donor resolved onto a (smaller) config: later donors
            # may use it as a RECEIVER, so its capacity row must be
            # the new machine's, not the one it just shed
            new_pcol = int(
                np.flatnonzero(new_mask)[np.argmin(enc.cfg_price[new_mask])]
            )
            pcol[di] = new_pcol
            alloc_p[di] = enc.cfg_alloc[new_pcol].astype(np.float64)
    return saved


def _finish_winner(enc: Encoded, result, masks: np.ndarray,
                   lam: np.ndarray | None) -> float:
    """The improvement pipeline applied to the race winner AFTER the
    selection keys (and the recorded FFD floor) are computed: the
    pairwise merge, then — with dual guidance on — trim rounds
    interleaved with re-merges while they keep paying. Each stage only
    ever lowers fleet price, so the served solution is never worse
    than the raw race winner. Deterministic: round count depends only
    on the inputs (fleet size + what the rounds saved), never on the
    clock. Returns the trim savings."""
    _merge_underfilled(enc, result, masks)
    from karpenter_tpu.solver import lp_device

    if lam is None or not lp_device.enabled():
        return 0.0
    saved = _trim_undervalued(enc, result, masks, lam)
    if saved <= 1e-12:
        return 0.0
    # follow-up rounds pay a full merge pass each; past a few hundred
    # candidates that merge dominates the steady-state wall (its pair
    # budget saturates ~130ms), so deep refinement is reserved for the
    # small-fleet shapes where it is nearly free — the first trim
    # round captures the bulk of the gap everywhere (measured: it
    # alone takes reserved_50k 6.5% -> 0.8%)
    n_active = int(
        (result.node_active[: result.node_count]
         & (result.assign[: result.node_count].sum(axis=1) > 0)).sum()
    )
    rounds = 2 if n_active <= 256 else 0
    for _ in range(rounds):
        _merge_underfilled(enc, result, masks)
        s = _trim_undervalued(enc, result, masks, lam)
        if s <= 1e-12:
            break
        saved += s
    return saved


def _downsize_masks(enc: Encoded, result) -> np.ndarray:
    """Re-widen each planned/fresh node's config mask to every same-pool
    config that fits its *final* fill, so decode can pick a smaller,
    cheaper machine for underfilled nodes. The kernel's mask only ever
    tightens during placement (reference semantics: the in-flight
    NodeClaim filters its instance-type options, nodeclaim.go:373-447);
    once placement is final, any config compatible with all resident
    pods and large enough is a valid — possibly cheaper — launch choice.
    """
    masks = result.node_mask.copy()
    launch = enc.cfg_pool >= 0
    uncapped = _uncapped_cols(enc)
    for ni in range(result.node_count):
        if not result.node_active[ni]:
            continue
        row = masks[ni]
        # fresh + reservation-uncapped only (a pinned node's pin is the
        # point: FinalizeScheduling, scheduling/nodeclaim.go:252)
        cols = _fresh_uncapped_cols(enc, masks, ni, uncapped)
        if cols is None:
            continue
        pool = enc.cfg_pool[cols[0]]
        groups_on = np.flatnonzero(result.assign[ni] > 0)
        if groups_on.size == 0:
            continue
        fits = np.all(
            enc.cfg_alloc + 1e-4 >= result.node_used[ni][None, :], axis=1
        )
        compat_all = enc.compat[groups_on].all(axis=0)
        # capacity-reservation columns only stay valid if the packer
        # already pinned this node to them — widening onto them would
        # overspend the reservation budget
        wide = (
            launch & (enc.cfg_pool == pool) & fits & compat_all
            & (uncapped | row)
        )
        if wide.any():
            # the kernel-validated columns stay in as a floor: they
            # provably hold the final fill, so numeric edge cases in
            # the re-widened fits check can never leave the node with
            # only configs smaller than its actual usage
            masks[ni] = wide | row
    return masks


def _decode_host(enc: Encoded) -> Solution:
    from karpenter_tpu.solver.reference_ffd import solve_ffd_host

    nodes, unsched = solve_ffd_host(enc)
    G = enc.compat.shape[0]
    n = len(nodes)
    masks = np.zeros((n, enc.compat.shape[1]), bool)
    assign = np.zeros((n, G), np.int32)
    for ni, node in enumerate(nodes):
        masks[ni] = node.mask
        for gi, count in node.assign.items():
            assign[ni, gi] = count
    unsched_arr = np.zeros(G, np.int32)
    for gi, count in unsched.items():
        unsched_arr[gi] = count
    return _build_solution_arrays(enc, np.arange(n), masks, assign, unsched_arr)


def _node_options(enc: Encoded, mask: np.ndarray):
    """Closure for NodePlan's lazy (instance_types, offerings): expand
    the config mask's members cheapest-first. Captures only the masked
    ConfigInfo slice (not the Encoded) so a surviving NodePlan doesn't
    pin the solver's dense arrays and all pod groups in memory. Dedupe
    members come from THIS encode's cfg_alts lists (per-encode state:
    a shared compat cache reuses ConfigInfo objects across encodes, so
    membership must never live on them)."""
    cols = np.flatnonzero(mask)
    configs = enc.configs          # list ref only: no dense arrays, no pods
    alts = enc.cfg_alts
    prices = enc.cfg_price[cols].tolist()

    def thunk():
        members: list[tuple[float, int, object]] = []
        for ci, price in zip(cols.tolist(), prices):
            cfg = configs[ci]
            if alts is not None and alts[ci]:
                members.extend((p, ci, m) for p, m in alts[ci])
            else:
                members.append((price, ci, cfg))
        members.sort(key=lambda t: (t[0], t[1]))
        seen_types: dict[str, InstanceType] = {}
        offerings: list[Offering] = []
        for _, _, cfg in members:
            seen_types.setdefault(cfg.instance_type.name, cfg.instance_type)
            offerings.append(cfg.offering)
        return list(seen_types.values()), offerings

    return thunk


def _node_primary(enc: Encoded, price_col: int):
    """Closure for NodePlan.primary(): the cheapest (type, offering)
    the decode resolved the node onto, from the one argmin column —
    O(alts) instead of the full member sort. Captures this encode's
    own member list, so later encodes (shared compat cache) can never
    change the answer."""
    cfg = enc.configs[price_col]
    members = enc.cfg_alts[price_col] if enc.cfg_alts is not None else None

    def thunk():
        if members:
            _, best = min(members, key=lambda t: t[0])
            return best.instance_type, best.offering
        return cfg.instance_type, cfg.offering

    return thunk


def _build_solution_arrays(
    enc: Encoded,
    active_idx: np.ndarray,    # node rows with pods
    node_masks: np.ndarray,    # [N, C] bool
    assign: np.ndarray,        # [N, G] int
    unsched: np.ndarray,       # [G] int
) -> Solution:
    """Vectorized decode: per-node price/first-config via one masked
    reduction each; option lists stay lazy (see NodePlan)."""
    import time as _time

    _t_decode = _time.perf_counter()
    new_nodes: list[NodePlan] = []
    existing: dict[int, ExistingAssignment] = {}
    group_cursor = np.zeros(len(enc.groups), np.int64)

    sub_mask = node_masks[active_idx]
    price_mat = np.where(sub_mask, enc.cfg_price[None, :], np.inf)
    node_price = price_mat.min(axis=1)
    price_col = price_mat.argmin(axis=1)
    first_col = sub_mask.argmax(axis=1)
    any_col = sub_mask.any(axis=1)

    extra_unsched = np.zeros(len(enc.groups), np.int64)
    loose = enc.loose_groups
    for row, ni in enumerate(active_idx):
        gs = np.nonzero(assign[ni])[0]
        if gs.size == 0 or not any_col[row]:
            continue
        if gs.size > 1 and loose is not None and loose[gs].any():
            # k-way re-validation: pairwise conflict rows cannot see a
            # three-way empty intersection on an open key (In[g,s] /
            # In[s,b] / In[g,b]); walk the node's groups in index
            # order tightening like the reference's incremental Add
            # (nodeclaim.go:114-167) and evict what no longer fits —
            # evicted pods report unschedulable and re-enter the
            # caller's retry path
            running = enc.configs[int(first_col[row])].requirements.copy()
            admitted = []
            for gi in gs:
                reqs = enc.groups[gi].requirements
                if running.intersects(reqs) is not None:
                    extra_unsched[gi] += int(assign[ni, gi])
                    continue
                running.add(*reqs.values())
                admitted.append(gi)
            gs = np.asarray(admitted, dtype=gs.dtype)
            if gs.size == 0:
                continue
        pods: list[Pod] = []
        for gi in gs:
            count = int(assign[ni, gi])
            start = int(group_cursor[gi])
            group_cursor[gi] += count
            pods.extend(enc.groups[gi].pods[start : start + count])
        first_cfg = enc.configs[int(first_col[row])]
        if first_cfg.existing_index >= 0:
            slot = existing.setdefault(
                first_cfg.existing_index, ExistingAssignment(first_cfg.existing_index)
            )
            slot.pods.extend(pods)
            continue
        plan = NodePlan(
            pool=first_cfg.pool,
            price=float(node_price[row]),
            pods=pods,
            lazy=_node_options(enc, sub_mask[row]),
            lazy_primary=_node_primary(enc, int(price_col[row])),
        )
        # the decode resolves the claim onto the cheapest offering; if
        # that is a reserved one, the node consumes reservation budget
        cheapest_cfg = enc.configs[int(price_col[row])]
        if cheapest_cfg.offering is not None and cheapest_cfg.offering.reservation_id:
            plan.reservation_id = cheapest_cfg.offering.reservation_id
        new_nodes.append(plan)

    unschedulable: list[Pod] = []
    evicted: list[Pod] = []
    total_unsched = unsched.astype(np.int64) + extra_unsched
    for gi in np.nonzero(total_unsched)[0]:
        # unplaced pods are the tail of the group after placements;
        # the deepest tail is the k-way-evicted share (interchangeable
        # within the group, so any split is valid)
        group = enc.groups[gi]
        tail = group.pods[len(group.pods) - int(total_unsched[gi]) :]
        unschedulable.extend(tail)
        if extra_unsched[gi]:
            evicted.extend(tail[len(tail) - int(extra_unsched[gi]) :])
    from karpenter_tpu import tracing
    from karpenter_tpu.metrics import sentinel
    from karpenter_tpu.metrics.store import SOLVER_PHASE_DURATION

    _t_done = _time.perf_counter()
    SOLVER_PHASE_DURATION.observe(_t_done - _t_decode, {"phase": "decode"})
    sentinel.observe_phase("decode", _t_done - _t_decode)
    tracing.record("solve.decode", _t_decode, _t_done,
                   nodes=len(new_nodes), unschedulable=len(unschedulable))
    return Solution(
        new_nodes=new_nodes,
        existing=sorted(existing.values(), key=lambda e: e.existing_index),
        unschedulable=unschedulable,
        evicted=evicted,
    )
