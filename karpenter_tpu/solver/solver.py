"""Solver facade: encoded problem -> per-node placements.

This is the `scheduling.Solver` seam the north star describes: the
provisioning scheduler and the consolidation engine call `solve()`
with pods + catalogs + existing nodes and get back node plans
(which pool/instance-types/offering each planned node resolves to and
which pods land where). Backend is the JAX packing kernel
(`solver.pack`) with the host FFD oracle as fallback.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.solver.encode import (
    Encoded,
    ExistingNodeInput,
    PodGroup,
    encode,
    group_pods,
)


@dataclass
class NodePlan:
    """One planned (new) node."""

    pool: NodePool
    instance_types: list[InstanceType]      # price-ordered options
    offerings: list[Offering]               # feasible offerings (cheapest first)
    pods: list[Pod] = field(default_factory=list)
    price: float = 0.0                      # cheapest feasible offering
    claim_name: str = ""                    # set once a NodeClaim is created


@dataclass
class ExistingAssignment:
    existing_index: int
    pods: list[Pod] = field(default_factory=list)


@dataclass
class Solution:
    new_nodes: list[NodePlan]
    existing: list[ExistingAssignment]
    unschedulable: list[Pod]

    @property
    def total_price(self) -> float:
        return sum(n.price for n in self.new_nodes)


def _backend() -> str:
    return os.environ.get("KARPENTER_SOLVER_BACKEND", "jax")


def solve(
    pods: Sequence[Pod],
    pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
    existing: Sequence[ExistingNodeInput] = (),
    daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
    required_only: bool = False,
    backend: Optional[str] = None,
    objective: str = "ffd",
) -> Solution:
    groups = group_pods(pods, required_only=required_only)
    enc = encode(groups, pools_with_types, existing, daemon_overhead)
    return solve_encoded(enc, backend=backend, objective=objective)


def solve_encoded(
    enc: Encoded, backend: Optional[str] = None, objective: str = "ffd"
) -> Solution:
    G, C = enc.compat.shape
    if G == 0 or C == 0:
        return Solution(
            new_nodes=[],
            existing=[],
            unschedulable=[p for g in enc.groups for p in g.pods],
        )
    backend = backend or _backend()
    if backend == "host":
        return _decode_host(enc)
    return _decode_device(enc, objective)


def _decode_device(enc: Encoded, objective: str = "ffd") -> Solution:
    from karpenter_tpu.solver.pack import solve_packing

    plan = None
    if objective == "cost":
        from karpenter_tpu.solver import lp_plan

        plan = lp_plan.plan(enc)
    result = solve_packing(enc, mode=objective, plan=plan)
    node_masks = result.node_mask
    if objective == "cost":
        node_masks = _downsize_masks(enc, result)
    node_assign = result.assign
    return _build_solution(
        enc,
        [
            (ni, node_masks[ni], {g: int(c) for g, c in enumerate(node_assign[ni]) if c > 0})
            for ni in range(result.node_count)
            if result.node_active[ni]
        ],
        {g: int(c) for g, c in enumerate(result.unschedulable) if c > 0},
    )


def _downsize_masks(enc: Encoded, result) -> np.ndarray:
    """Re-widen each planned/fresh node's config mask to every same-pool
    config that fits its *final* fill, so decode can pick a smaller,
    cheaper machine for underfilled nodes. The kernel's mask only ever
    tightens during placement (reference semantics: the in-flight
    NodeClaim filters its instance-type options, nodeclaim.go:373-447);
    once placement is final, any config compatible with all resident
    pods and large enough is a valid — possibly cheaper — launch choice.
    """
    masks = result.node_mask.copy()
    launch = enc.cfg_pool >= 0
    for ni in range(result.node_count):
        if not result.node_active[ni]:
            continue
        row = masks[ni]
        cols = np.flatnonzero(row)
        if cols.size == 0:
            continue
        first = enc.configs[cols[0]]
        if first.existing_index >= 0:
            continue  # real existing node, nothing to resize
        pool = enc.cfg_pool[cols[0]]
        groups_on = np.flatnonzero(result.assign[ni] > 0)
        if groups_on.size == 0:
            continue
        fits = np.all(
            enc.cfg_alloc + 1e-4 >= result.node_used[ni][None, :], axis=1
        )
        compat_all = enc.compat[groups_on].all(axis=0)
        wide = launch & (enc.cfg_pool == pool) & fits & compat_all
        if wide.any():
            masks[ni] = wide
    return masks


def _decode_host(enc: Encoded) -> Solution:
    from karpenter_tpu.solver.reference_ffd import solve_ffd_host

    nodes, unsched = solve_ffd_host(enc)
    return _build_solution(
        enc,
        [(ni, node.mask, node.assign) for ni, node in enumerate(nodes)],
        unsched,
    )


def _build_solution(
    enc: Encoded,
    node_rows: list[tuple[int, np.ndarray, dict[int, int]]],
    unsched: dict[int, int],
) -> Solution:
    new_nodes: list[NodePlan] = []
    existing: dict[int, ExistingAssignment] = {}
    group_cursor = [0] * len(enc.groups)

    def take_pods(gi: int, count: int) -> list[Pod]:
        start = group_cursor[gi]
        group_cursor[gi] += count
        return enc.groups[gi].pods[start : start + count]

    for ni, mask, assignment in node_rows:
        if not assignment:
            continue
        config_ids = np.flatnonzero(mask)
        if config_ids.size == 0:
            continue
        first_cfg = enc.configs[config_ids[0]]
        if first_cfg.existing_index >= 0:
            slot = existing.setdefault(
                first_cfg.existing_index, ExistingAssignment(first_cfg.existing_index)
            )
            for gi, count in assignment.items():
                slot.pods.extend(take_pods(gi, count))
            continue
        members: list[tuple[float, int, "object"]] = []
        for ci in config_ids:
            cfg = enc.configs[ci]
            if cfg.alts:
                members.extend((price, ci, m) for price, m in cfg.alts)
            else:
                members.append((float(enc.cfg_price[ci]), ci, cfg))
        members.sort(key=lambda t: (t[0], t[1]))
        seen_types: dict[str, InstanceType] = {}
        offerings: list[Offering] = []
        for _, _, cfg in members:
            seen_types.setdefault(cfg.instance_type.name, cfg.instance_type)
            offerings.append(cfg.offering)
        plan = NodePlan(
            pool=first_cfg.pool,
            instance_types=list(seen_types.values()),
            offerings=offerings,
            price=members[0][0],
        )
        for gi, count in assignment.items():
            plan.pods.extend(take_pods(gi, count))
        new_nodes.append(plan)

    unschedulable: list[Pod] = []
    for gi, count in unsched.items():
        # unplaced pods are the tail of the group after placements
        group = enc.groups[gi]
        unschedulable.extend(group.pods[len(group.pods) - count :])
    return Solution(
        new_nodes=new_nodes,
        existing=sorted(existing.values(), key=lambda e: e.existing_index),
        unschedulable=unschedulable,
    )
