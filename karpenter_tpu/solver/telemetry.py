"""Device cost/memory accounting for the solver (ISSUE 13 tentpole).

The 1M-pod sharded solves run blind to XLA's own cost model: nothing
in the tree ever reads `compiled.memory_analysis()` /
`cost_analysis()`, so the only memory evidence bench rounds carry is
host RSS. This module closes that gap with three accounting surfaces,
all null-safe on CPU-only hosts (no `memory_stats()`), scipy-absent
hosts, and sharded subprocess arms:

1. **Compiled-program accounting** — at every warm-pool AOT compile
   the `Compiled` object is already in hand, so its
   `memory_analysis()` (argument/output/temp/generated-code bytes) and
   `cost_analysis()` (flops, bytes accessed) are recorded per
   (kernel, shape-bucket, shards, variant) for free. Cold `_run_pack`/
   LP-ascent lowerings go through the jit dispatch (no `Compiled`
   handle exists), so a cold dispatch only ENQUEUES its padded
   signature; `drain()` — called per bench arm, by tests, and by any
   tool that wants the numbers — materializes the queue with one
   shape-only `lower()` per never-seen bucket, reading the cost
   analysis off the StableHLO without paying a second XLA compile
   (`KARPENTER_DEVICE_TELEMETRY=force` additionally compiles the
   analysis copy to get memory_analysis for cold buckets too).
   Deliberately NOT a background thread: XLA lowering is Python-heavy
   and holds the GIL, so a worker racing the reconcile loop would
   steal exactly the tick wall the SLO engine is measuring (observed
   as a live-tick perf-guard regression). Warm-pool-covered fleets
   get full coverage at startup for free; drain() is the explicit,
   caller-paid path for the rest.
2. **Live device memory** — per-device `memory_stats()` gauges
   (bytes_in_use / peak / limit where the backend reports them; a CPU
   backend returns None and the gauges simply stay unset).
3. **Host↔device staging attribution** — `stream.py`'s per-solve
   staging stats land in `karpenter_device_staging_bytes` and in
   `snapshot()` next to the compiled peaks, so one block answers "how
   close is this solve to the device" end to end.

Everything lands three ways: gauges (`karpenter_device_*`), `tm_*`
attrs on the existing `solve.compile`/`solve.execute` spans (stripped
from `tracing.structure()` — they track background compile progress,
so byte-identical replays may legitimately disagree), and the
`snapshot()` block bench stamps per arm as `device_telemetry`.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Optional

log = logging.getLogger("karpenter.solver.telemetry")

ENV = "KARPENTER_DEVICE_TELEMETRY"

# memory_analysis() components exported per compiled bucket
_MEM_COMPONENTS = (
    ("argument", "argument_size_in_bytes"),
    ("output", "output_size_in_bytes"),
    ("temp", "temp_size_in_bytes"),
    ("generated_code", "generated_code_size_in_bytes"),
)
# memory_stats() keys exported per live device (when the backend
# reports them at all — XLA:CPU returns None)
_DEVICE_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                 "largest_alloc_size")


def mode() -> str:
    """off | auto | force. auto (default) records compiled analyses
    wherever a Compiled object already exists (warm pool) and lowers —
    but never compiles — an analysis copy for cold buckets; force also
    compiles the cold copy so memory_analysis exists for every bucket."""
    raw = os.environ.get(ENV, "auto").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return "off"
    if raw in ("force", "2"):
        return "force"
    return "auto"


def enabled() -> bool:
    return mode() != "off"


# -- compiled-program registry ------------------------------------------------

_lock = threading.Lock()
# (kernel, bucket, shards) -> {"memory": {...}|None, "cost": {...}|None,
#                              "source": "warm_pool"|"cold_lowering"}
_compiled: dict[tuple, dict] = {}
_staging: dict = {}


def variant_tag(wavefront: int, rsv_k: Optional[int] = None,
                group_cap: bool = False, conflict: bool = False,
                quota: bool = False) -> str:
    """The kernel-variant component of a pack bucket key. Distinct
    kwarg combinations lower to DIFFERENT XLA programs (reservation
    inputs, topology caps/conflicts, per-node quotas), so each needs
    its own registry entry — a shared key would annotate a solve's
    spans with a program it never dispatched."""
    parts = ["wf%d" % wavefront,
             "rsv%s" % ("n" if rsv_k is None else int(rsv_k))]
    if group_cap:
        parts.append("gc")
    if conflict:
        parts.append("cf")
    if quota:
        parts.append("qt")
    return "-".join(parts)


def _bucket_key(kernel: str, bucket: tuple, shards: int) -> tuple:
    return (kernel, tuple(int(x) if isinstance(x, (int, bool)) else str(x)
                          for x in bucket), int(shards))


def _memory_dict(compiled) -> Optional[dict]:
    """CompiledMemoryStats -> plain dict; None when the runtime can't
    produce one (old jaxlib, unsupported backend)."""
    try:
        stats = compiled.memory_analysis()
    except Exception:
        return None
    if stats is None:
        return None
    out = {}
    for name, attr in _MEM_COMPONENTS:
        value = getattr(stats, attr, None)
        if value is not None:
            out[name] = int(value)
    return out or None


def _cost_dict(analysed) -> Optional[dict]:
    """cost_analysis() of a Lowered or Compiled -> {"flops",
    "bytes_accessed"}; the API returns a dict (Lowered) or a list of
    per-computation dicts (Compiled), and either may be missing on
    exotic backends."""
    try:
        cost = analysed.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    out = {}
    if "flops" in cost:
        out["flops"] = float(cost["flops"])
    if "bytes accessed" in cost:
        out["bytes_accessed"] = float(cost["bytes accessed"])
    return out or None


def record_compiled(kernel: str, bucket: tuple, compiled,
                    shards: int = 0, source: str = "warm_pool") -> None:
    """Account one compiled program. `bucket` is the padded shape
    signature ((Gp, Cp, Ep, F, mode, variant...) for pack kernels);
    safe to call with anything — failures are swallowed (telemetry
    must never take a compile path down)."""
    if not enabled():
        return
    try:
        entry = {
            "memory": _memory_dict(compiled),
            "cost": _cost_dict(compiled),
            "source": source,
        }
        _publish_compiled(kernel, bucket, shards, entry)
    except Exception:  # pragma: no cover - defensive
        log.debug("compiled telemetry failed for %s %s", kernel, bucket,
                  exc_info=True)


def record_lowered(kernel: str, bucket: tuple, lowered,
                   shards: int = 0, source: str = "cold_lowering") -> None:
    """Cost-only accounting off a Lowered (no XLA compile paid)."""
    if not enabled():
        return
    try:
        entry = {"memory": None, "cost": _cost_dict(lowered),
                 "source": source}
        _publish_compiled(kernel, bucket, shards, entry)
    except Exception:  # pragma: no cover - defensive
        log.debug("lowered telemetry failed for %s %s", kernel, bucket,
                  exc_info=True)


def _publish_compiled(kernel: str, bucket: tuple, shards: int,
                      entry: dict) -> None:
    from karpenter_tpu.metrics.store import (
        DEVICE_COMPILED_COST,
        DEVICE_COMPILED_MEMORY,
    )

    key = _bucket_key(kernel, bucket, shards)
    with _lock:
        prior = _compiled.get(key)
        if prior is not None:
            # a warm-pool record (has memory_analysis) must not be
            # downgraded by a later cost-only capture of the same bucket
            if entry["memory"] is None and prior.get("memory") is not None:
                entry = {**entry, "memory": prior["memory"],
                         "source": prior["source"]}
        _compiled[key] = entry
    labels = {"kernel": kernel, "bucket": "x".join(str(x) for x in key[1]),
              "shards": str(shards)}
    if entry["memory"]:
        for component, value in entry["memory"].items():
            DEVICE_COMPILED_MEMORY.set(
                float(value), {**labels, "component": component}
            )
    if entry["cost"]:
        for stat, value in entry["cost"].items():
            DEVICE_COMPILED_COST.set(float(value), {**labels, "stat": stat})


def compiled_entry(kernel: str, bucket: tuple, shards: int = 0
                   ) -> Optional[dict]:
    """The recorded analysis for one bucket (None until captured) —
    the solve path annotates its compile span from this."""
    with _lock:
        entry = _compiled.get(_bucket_key(kernel, bucket, shards))
        return dict(entry) if entry is not None else None


# -- cold-bucket capture queue ------------------------------------------------
#
# The jit dispatch path holds no Compiled handle, so cold buckets are
# analysed out of band: the solve site enqueues its padded signature
# (dedup'd, bounded), and drain() lowers the same shapes once (force:
# also compiles) in the CALLER's thread — see the module docstring for
# why this is not a background worker. Eviction under pressure removes
# the dropped request's dedup key too, so a bucket squeezed out
# between drains re-enqueues on its next dispatch instead of being
# silently blacklisted forever.

_QUEUE_MAX = 64
_queue: deque = deque()
_requested: set = set()


def request_pack_capture(Gp: int, Cp: int, Ep: int, F: int, R: int,
                         P: int, mode_: str, wavefront: int,
                         shards: int, rsv_k: Optional[int],
                         group_cap: bool = False, conflict: bool = False,
                         quota: bool = False) -> None:
    """Enqueue a cold pack bucket for drain-time analysis (dedup'd).
    Called from `_run_pack` after a dispatch whose padded signature no
    warm-pool compile covered — the flags name the EXACT kwarg variant
    the real solve dispatched."""
    if not enabled():
        return
    key = ("pack", Gp, Cp, Ep, F, mode_, wavefront, shards,
           rsv_k, group_cap, conflict, quota)
    _enqueue(key, ("pack", dict(Gp=Gp, Cp=Cp, Ep=Ep, F=F, R=R, P=P,
                                mode=mode_, wavefront=wavefront,
                                shards=shards, rsv_k=rsv_k,
                                group_cap=group_cap, conflict=conflict,
                                quota=quota)))


def request_lp_capture(Gp: int, Cp: int, R: int, Kp: int,
                       n_iters: int) -> None:
    """Enqueue a cold LP-ascent bucket for background analysis."""
    if not enabled():
        return
    key = ("lp", Gp, Cp, R, Kp, n_iters)
    _enqueue(key, ("lp", dict(Gp=Gp, Cp=Cp, R=R, Kp=Kp,
                              n_iters=n_iters)))


def _enqueue(key: tuple, item: tuple) -> None:
    with _lock:
        if key in _requested:
            return
        _requested.add(key)
        while len(_queue) >= _QUEUE_MAX:
            # drop the oldest request AND its dedup key: the bucket
            # re-enqueues on its next dispatch rather than vanishing
            old_key, _ = _queue.popleft()
            _requested.discard(old_key)
        _queue.append((key, item))


def drain(timeout: float = 10.0) -> bool:
    """Materialize the queued cold-bucket captures in THIS thread,
    bounded by `timeout` seconds (bench calls this before stamping
    `device_telemetry` blocks; the steady tick path never does). True
    when the queue emptied within the budget."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        with _lock:
            try:
                key, item = _queue.popleft()
            except IndexError:
                return True
        try:
            _capture(item)
        except Exception:  # pragma: no cover - defensive
            # un-blacklist the bucket: a transient failure (device
            # busy, fault injector live) must leave it re-requestable
            # on its next dispatch, same contract as queue eviction
            with _lock:
                _requested.discard(key)
            log.debug("telemetry capture failed for %s", item[0],
                      exc_info=True)
    with _lock:
        return not _queue


def _capture(item: tuple) -> None:
    kind, spec = item
    if kind == "pack":
        _capture_pack(spec)
    elif kind == "lp":
        _capture_lp(spec)


def _capture_pack(spec: dict) -> None:
    from karpenter_tpu.solver.pack import pack_split_flat
    from karpenter_tpu.solver.warm_pool import bucket_args

    args, kw = bucket_args(
        spec["Gp"], spec["Cp"], spec["Ep"], spec["R"], spec["P"],
        shards=spec["shards"], rsv_k=spec["rsv_k"],
        group_cap=spec["group_cap"], conflict=spec["conflict"],
        quota=spec["quota"],
    )
    statics = {"max_free": spec["F"], "mode": spec["mode"]}
    if spec["wavefront"] > 1:
        statics["wavefront"] = spec["wavefront"]
    lowered = pack_split_flat.lower(*args, **statics, **kw)
    bucket = (spec["Gp"], spec["Cp"], spec["Ep"], spec["F"],
              spec["mode"],
              variant_tag(spec["wavefront"], spec["rsv_k"],
                          spec["group_cap"], spec["conflict"],
                          spec["quota"]))
    if mode() == "force":
        record_compiled("pack", bucket, lowered.compile(),
                        shards=spec["shards"], source="cold_lowering")
    else:
        record_lowered("pack", bucket, lowered, shards=spec["shards"])


def _capture_lp(spec: dict) -> None:
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from karpenter_tpu.solver.lp_device import _ascend

    Gp, Cp, R, Kp = spec["Gp"], spec["Cp"], spec["R"], spec["Kp"]
    lowered = _ascend.lower(
        S((Gp,), jnp.float32), S((Gp,), jnp.float32),
        S((Gp,), jnp.float32), S((Gp, Cp), jnp.bool_),
        S((Gp, R), jnp.float32), S((Cp, R), jnp.float32),
        S((Cp,), jnp.float32), S((Cp, R), jnp.bool_),
        S((Kp, Cp), jnp.bool_), S((Kp,), jnp.float32),
        S((Cp,), jnp.bool_),
        n_iters=spec["n_iters"],
    )
    bucket = (Gp, Cp, R, Kp, "iters%d" % spec["n_iters"])
    if mode() == "force":
        record_compiled("lp", bucket, lowered.compile(),
                        source="cold_lowering")
    else:
        record_lowered("lp", bucket, lowered)


# -- live device memory -------------------------------------------------------

def device_memory_snapshot() -> list[dict]:
    """Per-device live memory: [{"device", "platform", "stats":
    {...}|None}]. Null-safe by construction — XLA:CPU (and any backend
    without an allocator report) returns stats=None, and a jax import
    failure returns []."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for dev in devices:
        stats = None
        try:
            raw = dev.memory_stats()
        except Exception:
            raw = None
        if raw:
            stats = {k: int(raw[k]) for k in _DEVICE_STATS if k in raw}
            stats = stats or None
        out.append({
            "device": f"{dev.platform}:{dev.id}",
            "platform": str(dev.platform),
            "stats": stats,
        })
    return out


def publish_device_memory() -> list[dict]:
    """Refresh the `karpenter_device_memory_bytes` gauges from live
    `memory_stats()` and return the snapshot. Devices without stats
    leave no series behind."""
    snap = device_memory_snapshot()
    if not enabled():
        return snap
    from karpenter_tpu.metrics.store import DEVICE_MEMORY

    for dev in snap:
        if not dev["stats"]:
            continue
        for stat, value in dev["stats"].items():
            DEVICE_MEMORY.set(float(value),
                              {"device": dev["device"], "stat": stat})
    return snap


# -- staging attribution ------------------------------------------------------

def note_staging(stats: dict) -> None:
    """Record the most recent streamed staging pass (called by
    stream._Staging.commit) into the staging gauges + snapshot()."""
    if not stats:
        return
    with _lock:
        _staging.clear()
        _staging.update(stats)
    if not enabled():
        return
    from karpenter_tpu.metrics.store import DEVICE_STAGING

    for stat, key in (("peak_block", "peak_block_bytes"),
                      ("full", "full_bytes")):
        if key in stats:
            DEVICE_STAGING.set(float(stats[key]), {"stat": stat})


# -- the bench block ----------------------------------------------------------

def compiled_keys() -> set:
    """The registry's current bucket keys (bench captures this before
    an arm so snapshot() can scope its compiled roll-up to the arm)."""
    with _lock:
        return set(_compiled)


def snapshot(compiled_before: Optional[set] = None) -> dict:
    """The per-arm `device_telemetry` block: always well-formed, with
    nulls where the host genuinely has no signal (CPU memory_stats,
    never-compiled buckets). Scalar roll-ups (`compiled_peak_temp_mb`,
    `device_peak_in_use_mb`) ride at the top level so
    tools/bench_compare.py can gate them without walking the detail —
    each carries a scope: with `compiled_before` (the keys recorded
    BEFORE the arm, see compiled_keys()) the compiled peak covers only
    buckets this arm added ("arm"); without it, it covers the process
    lifetime. The live-device peak is ALWAYS process-scoped — XLA's
    peak_bytes_in_use watermark has no reset — and bench_compare
    refuses to gate process-scoped peaks (they accumulate every
    earlier arm, so a delta would fire on arm ordering, not memory)."""
    with _lock:
        items = list(_compiled.items())
        staging = dict(_staging) if _staging else None
    compiled = {}
    temp_peaks = []
    for k, v in items:
        name = "%s[%s]sh%d" % (k[0], "x".join(str(x) for x in k[1]), k[2])
        compiled[name] = {
            "memory": dict(v["memory"]) if v["memory"] else None,
            "cost": dict(v["cost"]) if v["cost"] else None,
            "source": v["source"],
        }
        if (
            v["memory"] and "temp" in v["memory"]
            and (compiled_before is None or k not in compiled_before)
        ):
            temp_peaks.append(v["memory"]["temp"])
    devices = device_memory_snapshot()
    in_use_peaks = [
        d["stats"]["peak_bytes_in_use"] for d in devices
        if d["stats"] and "peak_bytes_in_use" in d["stats"]
    ]
    return {
        "mode": mode(),
        "compiled": compiled or None,
        "devices": devices or None,
        "staging": staging,
        "compiled_peak_temp_mb": (
            round(max(temp_peaks) / 2**20, 2) if temp_peaks else None
        ),
        "compiled_scope": (
            "arm" if compiled_before is not None else "process"
        ),
        "device_peak_in_use_mb": (
            round(max(in_use_peaks) / 2**20, 2) if in_use_peaks else None
        ),
        "device_scope": "process",
    }


def headroom() -> Optional[dict]:
    """Device-memory headroom where REAL stats exist: min over devices
    of 1 - bytes_IN_USE/limit — the LIVE footprint at the call site,
    deliberately not peak_bytes_in_use: the peak is a process-lifetime
    watermark with no reset, so on a host that ran other work first
    (bench arms before million_pod on a TPU mesh) it measures history,
    not this solve — an assertion on it would abort on arm ordering.
    Callers sample right after the work whose footprint they mean to
    bound, while its buffers are still resident. The peak still rides
    along as provenance. None on hosts whose backend reports no
    allocator stats (CPU) — the caller's assertion is then vacuous by
    design (the million_pod arm records the null and moves on)."""
    fractions = []
    peaks = []
    for dev in device_memory_snapshot():
        stats = dev["stats"] or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use")
        if limit and in_use is not None:
            fractions.append(1.0 - in_use / limit)
            if "peak_bytes_in_use" in stats:
                peaks.append(1.0 - stats["peak_bytes_in_use"] / limit)
    if not fractions:
        return None
    return {
        "min_headroom_fraction": round(min(fractions), 4),
        "min_peak_headroom_fraction": (
            round(min(peaks), 4) if peaks else None
        ),
        "devices_reporting": len(fractions),
    }


def reset() -> None:
    """Test hook: drop the registries (gauges keep their last values —
    the registry has no per-series delete sweep and tests read deltas)."""
    with _lock:
        _compiled.clear()
        _staging.clear()
        _requested.clear()
    _queue.clear()
