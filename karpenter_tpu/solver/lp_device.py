"""Device-resident LP relaxation of the packing problem, and the dual
machinery spent on it (ISSUE 12).

`lp_plan` solves the Gilmore-Gomory master on the HOST with scipy —
fine for planning, but its duals arrive late and its wall is budgeted
in seconds. This module solves a *config-level* LP relaxation of the
same packing problem ON DEVICE as dense linear algebra (CvxCluster,
"Cloud Resource Allocation with Convex Optimization" — PAPERS.md):

    min  sum_c price[c] * y[c]
    s.t. sum_c x[g,c]           >= count[g]          (demand)
         sum_g req[g,r] x[g,c]  <= alloc[c,r] y[c]   (capacity)
         sum_{c in slot k} y[c] <= rsv_cap[k]        (reservations)
         x, y >= 0, x[g,c] = 0 where incompatible

via projected supergradient ascent on its DUAL: maximize

    bound(lam) = lam'.count - sum_k rsv_cap[k] * mu_k(lam')

where lam' = lam / theta(lam) is the Farley-scaled demand dual,
theta(lam) = max over uncapped configs of Vhat_c(lam)/price_c, and
Vhat_c is a closed-form per-config UPPER bound on the fractional
knapsack value max{lam.q : q.req <= alloc_c, q compatible}:

    Vhat_c = min over valid r of (max_g lam_g/req[g,r]) * alloc[c,r]

(r is valid for c when every live compatible group consumes it — the
'pods' axis always qualifies, so the min is never empty). Scaling by
theta makes lam' dual-feasible for every UNCAPPED config; capped
(reserved) configs may exceed their near-zero price, and the per-slot
cap dual mu_k = max_{c in k} relu(Vhat_c(lam') - price_c) buys that
excess back against the reservation budget. The ascent runs as ONE
jitted fori_loop (shape-bucketed so steady-state shapes share a
compiled program); the OPTIMIZER is float32 on device, but the
certificate — bound, scaled duals, cap duals — is recomputed on the
host in float64 from the best iterate, so validity never rests on
accelerator arithmetic.

The duals are spent three ways (see solver.solve_encoded and
disruption/engine.py):

- **price-guided ordering** (`rank_prices`): a dual-adjusted
  reduced-cost penalty on configs the LP says are over-priced, fed to
  `pack_split`/`pack_split_wavefront` as the type-preference ranking.
  Ordering is an INPUT (the kernel's cfg_price operand); the kernel
  body is untouched and decode always prices nodes from the true
  `enc.cfg_price`, so the bit-identical decode contract holds. The
  ranked pack races the unguided arms and the cheapest fleet wins —
  never-worse by construction.
- **dual-guided trimming** (solver._trim_undervalued): duals certify
  which packed nodes hold less value than they cost
  (lam'.assign < price); those donors are emptied into the rest of the
  fleet's headroom and re-fitted onto cheaper machines. This is where
  the integrality gap actually closes (measured: gap_vs_lp 6.5% ->
  0.3% on reserved_50k, 1.4% -> 0.2% on hetero_10k).
- **probe pruning** (`DualCertificate.cannot_pay`): weak duality
  bounds any repack's launch cost from below; a consolidation probe
  whose candidates' dual value exceeds their price even after every
  other node's free capacity and the reservation budget absorb their
  share CANNOT produce a cheaper replacement, so the engine skips the
  probe. The bound is conservative (valid lam', float64, margin knob),
  so pruning is decision-identical to the unpruned ladder —
  oracle-enforced by tests/test_lp_prune.py.

Priority (ISSUE 8 follow-up): `Encoded.group_priority` is folded into
the ASCENT objective — demand is weighted by resolved PriorityClass
value, so the guidance duals price priority, not just dollars — while
the reported bound is always recomputed unweighted (a weighted
"bound" would certify nothing).

Resilience: the LP is advisory. Any failure or unconverged solve
degrades to the unguided path (`maybe_solve` returns None, counted in
karpenter_solver_lp_total{outcome="degraded"}) and can never block a
tick; the packing solve underneath keeps riding the resilience
ladder unchanged.

Knobs: KARPENTER_LP_GUIDE (default on; 0 disables guidance + trim +
rank), KARPENTER_LP_ITERS (ascent iterations, default 192),
KARPENTER_LP_RANK_BETA (reduced-cost penalty weight, default 1.0),
KARPENTER_LP_PRUNE_MARGIN (pruning safety margin, default 0.05),
KARPENTER_LP_PRIORITY_WEIGHT (priority fold strength, default 0.25),
KARPENTER_LP_SHARDS (mesh the ascent over the config axis; default 0
= single device — the tensors are [G, C, R] and tiny even at
million-pod demand, so sharding is an opt-in for mesh-resident
deployments, not a memory need).
"""

from __future__ import annotations

import functools
import hashlib
import logging
import os
import threading
import time
from dataclasses import dataclass

import numpy as np

from karpenter_tpu.solver.encode import Encoded
# one canonical copy each (PR-7 deduped these once already): env
# parsing from the resilience/incremental modules, shape buckets from
# lp_plan — the padding growth curve decides warm-bucket matching and
# must never fork per module
from karpenter_tpu.solver.lp_plan import _pad_to
from karpenter_tpu.solver.resilience import _env_int

log = logging.getLogger("karpenter.solver.lp")

_EPS = 1e-12


def enabled() -> bool:
    return os.environ.get("KARPENTER_LP_GUIDE", "1").strip().lower() not in (
        "0", "off", "false", "no",
    )


def _env_float(name: str, default: float) -> float:
    from karpenter_tpu.solver.incremental import _env_float as _impl

    return _impl(name, default)


def iters() -> int:
    return max(8, _env_int("KARPENTER_LP_ITERS", 192))


def rank_beta() -> float:
    return max(0.0, _env_float("KARPENTER_LP_RANK_BETA", 1.0))


def prune_margin() -> float:
    return max(0.0, _env_float("KARPENTER_LP_PRUNE_MARGIN", 0.05))


def lp_shards() -> int:
    return max(0, _env_int("KARPENTER_LP_SHARDS", 0))


def _cap_rows(k: int) -> int:
    """Reservation-slot row bucket for the ascent's onehot/budget
    inputs: 1 for cap-free problems, else 64/512/... — a tiny family
    so the warm pool can precompile the shapes real solves hit."""
    if k <= 0:
        return 1
    out = 64
    while out < k:
        out *= 8
    return out


@dataclass
class DeviceLP:
    """One certified dual solve of the packing relaxation."""

    lam: np.ndarray          # [G] float64 Farley-scaled demand duals —
                             # dual-feasible: lam.q <= price_c for every
                             # feasible fill of every uncapped config
    mu: np.ndarray           # [K] float64 reservation-cap duals (>= 0)
    lower_bound: float       # float64-certified: lam.count - cap.mu
    theta: float             # the Farley scaling actually applied
    vhat: np.ndarray         # [C] float64 per-config value upper bound
                             # at lam (launchable cols; 0 elsewhere)
    lam_guide: np.ndarray    # [G] float64 priority-weighted guidance
                             # duals (== lam when priorities uniform)
    iterations: int
    converged: bool
    wall_s: float
    cache_hit: bool = False


# fingerprint -> DeviceLP (LRU, oldest evicted). The LP is a pure
# function of the encoded arrays + knobs, so steady-state solves and
# repeated probe ladders pay the ascent once per problem shape.
_cache: dict[bytes, DeviceLP] = {}
_cache_lock = threading.Lock()
_CACHE_ENTRIES = 16


def _fingerprint(enc: Encoded) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for buf in (
        enc.group_count, enc.group_req, enc.cfg_price, enc.cfg_alloc,
        np.ascontiguousarray(enc.compat), enc.cfg_pool, enc.pool_overhead,
    ):
        h.update(np.ascontiguousarray(buf).tobytes())
    for opt in (enc.cfg_rsv, enc.rsv_cap, enc.group_priority):
        h.update(
            b"\x00" if opt is None else np.ascontiguousarray(opt).tobytes()
        )
    h.update(
        f"{iters()}|{_env_float('KARPENTER_LP_PRIORITY_WEIGHT', 0.25)}"
        .encode()
    )
    return h.digest()


@functools.partial(
    __import__("jax").jit, static_argnames=("n_iters",)
)
def _ascend(lam0, count, count_w, compat, req, alloc, price, valid_r,
            cap_onehot, cap_budget, uncapped, n_iters):
    """Projected supergradient ascent, all iterations in one device
    program. Maximizes the PRIORITY-WEIGHTED dual bound; tracks the
    best iterate by the weighted objective (the host re-certifies the
    returned iterate unweighted in float64)."""
    import jax
    import jax.numpy as jnp

    safe_req = jnp.where(req > 0, req, 1.0)
    live = count > 0

    def vhat_of(lam):
        ratio = jnp.where(
            (req > 0) & live[:, None], lam[:, None] / safe_req, 0.0
        )                                                     # [G, R]
        mm = jnp.max(
            jnp.where(compat[:, :, None], ratio[:, None, :], 0.0), axis=0
        )                                                     # [C, R]
        v = jnp.where(valid_r, mm * alloc, jnp.inf)
        vh = jnp.min(v, axis=1)
        return jnp.where(jnp.isfinite(vh), vh, 0.0)           # [C]

    def bound_w(lam):
        vh = vhat_of(lam)
        theta = jnp.max(
            jnp.where(uncapped & (price > 0), vh / jnp.maximum(price, _EPS),
                      0.0)
        )
        theta = jnp.maximum(theta, _EPS)
        lam_s = lam / theta
        excess = jnp.clip(vh / theta - price, 0.0, None)      # [C]
        mu = jnp.max(
            jnp.where(cap_onehot, excess[None, :], 0.0), axis=1
        )                                                     # [K]
        return lam_s @ count_w - mu @ cap_budget

    grad = jax.grad(bound_w)

    def step(t, state):
        lam, best, best_lam, last_up = state
        g = grad(lam)
        gn = g / jnp.maximum(jnp.linalg.norm(g), _EPS)
        eta = 0.5 / jnp.sqrt(1.0 + t)
        lam2 = jnp.clip(
            lam + eta * gn * jnp.maximum(jnp.max(lam), 1e-9), 0.0, None
        )
        b = bound_w(lam2)
        better = b > best
        return (
            lam2,
            jnp.where(better, b, best),
            jnp.where(better, lam2, best_lam),
            jnp.where(better, t, last_up),
        )

    b0 = bound_w(lam0)
    _, best, best_lam, last_up = __import__("jax").lax.fori_loop(
        0, n_iters, step, (lam0, b0, lam0, jnp.int32(-1))
    )
    return best, best_lam, last_up


def _certify(lam, count, compat, req, alloc, price, valid_r, cap_slot,
             cap_budget):
    """Host float64 re-derivation of (theta, lam', mu, bound) from a
    candidate lam — the returned numbers are valid by construction,
    independent of how well (or on what hardware) the ascent did."""
    lam = np.clip(np.asarray(lam, np.float64), 0.0, None)
    live = count > 0
    safe_req = np.where(req > 0, req, 1.0)
    ratio = np.where((req > 0) & live[:, None], lam[:, None] / safe_req, 0.0)
    mm = np.max(
        np.where(compat[:, :, None], ratio[:, None, :], 0.0), axis=0
    )
    with np.errstate(invalid="ignore"):
        v = np.where(valid_r, mm * alloc, np.inf)
    vh = np.min(v, axis=1)
    vh = np.where(np.isfinite(vh), vh, 0.0)
    uncapped = cap_slot < 0
    theta = float(
        np.max(
            np.where(uncapped & (price > 0), vh / np.maximum(price, _EPS),
                     0.0),
            initial=0.0,
        )
    )
    theta = max(theta, _EPS)
    lam_s = lam / theta
    vh_s = vh / theta
    K = len(cap_budget)
    mu = np.zeros(K, np.float64)
    if K:
        excess = np.clip(vh_s - price, 0.0, None)
        for k in range(K):
            sel = cap_slot == k
            if sel.any():
                mu[k] = float(excess[sel].max())
    bound = float(lam_s @ count - mu @ cap_budget)
    return lam_s, mu, vh_s, theta, max(bound, 0.0)


def _stage(enc: Encoded):
    """Launch-masked, padded float32 staging for the ascent kernel plus
    the float64 host copies the certificate is computed from."""
    G, C = enc.compat.shape
    R = enc.group_req.shape[1]
    launch = enc.cfg_pool >= 0
    eff = enc.cfg_alloc - enc.pool_overhead[np.maximum(enc.cfg_pool, 0)]
    eff = np.where(launch[:, None], np.clip(eff, 0.0, None), 0.0)
    price = np.where(launch, enc.cfg_price, 0.0).astype(np.float64)
    compat = enc.compat & launch[None, :]
    cap_slot = (
        enc.cfg_rsv.astype(np.int64)
        if enc.cfg_rsv is not None
        else np.full(C, -1, np.int64)
    )
    cap_slot = np.where(launch, cap_slot, -1)
    cap_budget = (
        enc.rsv_cap.astype(np.float64)
        if enc.rsv_cap is not None
        else np.zeros(0, np.float64)
    )
    # per-(group, config) single-node capacity: seeds the ascent AND
    # derives the plannable mask — groups no launchable machine can
    # hold even one pod of are excluded from the priced demand, like
    # lp_plan's master (covering them is infeasible, so their duals
    # would grow without bound and certify nothing)
    safe = np.where(enc.group_req > 0, enc.group_req, 1.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        k = np.floor((eff[None, :, :] + 1e-4) / safe[:, None, :])
    k = np.where(enc.group_req[:, None, :] > 0, k, np.inf).min(axis=2)
    k = np.where(compat, k, 0.0)
    plannable = np.asarray(k >= 1).any(axis=1)
    count = np.where(plannable, enc.group_count, 0).astype(np.float64)
    live = count > 0
    with np.errstate(divide="ignore", invalid="ignore"):
        ppp = np.where(k >= 1, price[None, :] / np.maximum(k, 1.0), np.inf)
    ppp = ppp.min(axis=1)
    lam0 = np.where(np.isfinite(ppp) & live, ppp, 0.0)
    # r valid for c <=> every live compatible group consumes r (the
    # pods axis always does); invalid axes cannot upper-bound the
    # fill. Zero-capacity axes stay VALID — ratio x 0 = 0 is exactly
    # the right bound for a machine with none of a resource every
    # candidate pod needs (excluding them would let the min escape to
    # a slack axis and wildly overestimate the fill)
    reqpos = enc.group_req > 0
    bad = (compat & live[:, None])[:, :, None] & ~reqpos[:, None, :]
    valid_r = ~bad.any(axis=0)
    # priority weights: resolved PriorityClass folded into the ascent
    # objective so the guidance duals price priority, not just dollars
    # — ONE formula shared with the host column generation's pricing
    # (lp_plan.priority_weights; the ISSUE-15 satellite closing the
    # "host prices dollars only" gap)
    from karpenter_tpu.solver.lp_plan import priority_weights

    w = priority_weights(enc.group_priority, G)
    return dict(
        G=G, C=C, R=R, count=count, count_w=count * w, compat=compat,
        req=enc.group_req.astype(np.float64), alloc=eff.astype(np.float64),
        price=price, valid_r=valid_r, cap_slot=cap_slot,
        cap_budget=cap_budget, lam0=lam0, weights=w,
    )


def solve(enc: Encoded, shards: int = 0) -> DeviceLP:
    """Run (or reuse) the device dual ascent for this encode. Raises on
    failure — use `maybe_solve` for the degrading entry point."""
    import jax.numpy as jnp

    from karpenter_tpu import tracing
    from karpenter_tpu.metrics.store import (
        SOLVER_LP_DURATION,
        SOLVER_LP_ITERATIONS,
        SOLVER_LP_SOLVES,
    )

    fp = _fingerprint(enc)
    with _cache_lock:
        hit = _cache.get(fp)
    if hit is not None:
        SOLVER_LP_SOLVES.inc({"outcome": "cache_hit"})
        return hit

    t0 = time.perf_counter()
    with tracing.span("solve.lp") as sp:
        st = _stage(enc)
        G, C, R = st["G"], st["C"], st["R"]
        shards = shards or lp_shards()
        Gp, Cp = _pad_to(G), _pad_to(C)
        if shards > 1:
            # the config axis must split evenly over the mesh — a
            # non-divisible device_put is a hard ValueError, not a
            # performance detail (same rule as pack._run_pack)
            Cp = -(-Cp // shards) * shards
        K = len(st["cap_budget"])
        Kp = _cap_rows(K)

        compat_p = np.zeros((Gp, Cp), bool)
        compat_p[:G, :C] = st["compat"]
        req_p = np.zeros((Gp, R), np.float32)
        req_p[:G] = st["req"]
        alloc_p = np.zeros((Cp, R), np.float32)
        alloc_p[:C] = st["alloc"]
        price_p = np.zeros(Cp, np.float32)
        price_p[:C] = st["price"]
        valid_p = np.zeros((Cp, R), bool)
        valid_p[:C] = st["valid_r"]
        count_p = np.zeros(Gp, np.float32)
        count_p[:G] = st["count"]
        countw_p = np.zeros(Gp, np.float32)
        countw_p[:G] = st["count_w"]
        lam0_p = np.zeros(Gp, np.float32)
        lam0_p[:G] = st["lam0"]
        slot_p = np.full(Cp, -1, np.int64)
        slot_p[:C] = st["cap_slot"]
        # reservation-slot rows padded to a tiny shape family (1 when
        # cap-free, else 64/512/...) so the jit signature — which keys
        # on the onehot/budget SHAPES — matches what the warm pool
        # compiled; padding rows are all-false/zero and contribute 0
        onehot = np.zeros((Kp, Cp), bool)
        for ki in range(K):
            onehot[ki] = slot_p == ki
        budget_p = np.zeros(Kp, np.float32)
        budget_p[:K] = st["cap_budget"]
        uncapped_p = (slot_p < 0) & (price_p > 0)

        n_iters = iters()
        args = [
            jnp.asarray(lam0_p), jnp.asarray(count_p), jnp.asarray(countw_p),
            jnp.asarray(compat_p), jnp.asarray(req_p), jnp.asarray(alloc_p),
            jnp.asarray(price_p), jnp.asarray(valid_p), jnp.asarray(onehot),
            jnp.asarray(budget_p), jnp.asarray(uncapped_p),
        ]
        if shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from karpenter_tpu.solver.pack import _mesh, visible_devices

            if shards <= visible_devices(1):
                import jax as _jax

                mesh = _mesh(shards)
                spec = {
                    3: P(None, "cfg"), 5: P("cfg", None), 6: P("cfg"),
                    7: P("cfg", None), 8: P(None, "cfg"), 10: P("cfg"),
                }
                args = [
                    _jax.device_put(a, NamedSharding(mesh, spec.get(i, P())))
                    for i, a in enumerate(args)
                ]
        best_w, best_lam, last_up = _ascend(*args, n_iters=n_iters)
        # device telemetry (ISSUE 13): the jit dispatch holds no
        # Compiled handle, so a cold ascent bucket is analysed out of
        # band — one background lowering per (Gp, Cp, Kp) signature
        from karpenter_tpu.solver import telemetry

        telemetry.request_lp_capture(Gp, Cp, R, Kp, n_iters)
        entry = telemetry.compiled_entry(
            "lp", (Gp, Cp, R, Kp, "iters%d" % n_iters)
        )
        if entry is not None and entry.get("cost"):
            sp.annotate(**{
                "tm_" + k: v for k, v in entry["cost"].items()
            })
        lam_raw = np.asarray(best_lam, np.float64)[:G]
        converged = int(last_up) < (n_iters * 3) // 4

        # float64 certificate from the best iterate (validity never
        # rests on the float32 device arithmetic)
        lam_s, mu, vh_s, theta, bound = _certify(
            lam_raw, st["count"], st["compat"], st["req"], st["alloc"],
            st["price"], st["valid_r"], st["cap_slot"], st["cap_budget"],
        )
        wall = time.perf_counter() - t0
        out = DeviceLP(
            lam=lam_s,
            mu=mu,
            lower_bound=bound,
            theta=theta,
            vhat=vh_s,
            lam_guide=lam_s * st["weights"],
            iterations=n_iters,
            converged=converged,
            wall_s=wall,
            cache_hit=False,
        )
        sp.annotate(groups=G, configs=C, iterations=n_iters,
                    converged=converged)
        SOLVER_LP_DURATION.observe(wall)
        SOLVER_LP_ITERATIONS.observe(n_iters)
        SOLVER_LP_SOLVES.inc(
            {"outcome": "converged" if converged else "maxiter"}
        )
    with _cache_lock:
        _cache.pop(fp, None)
        while len(_cache) >= _CACHE_ENTRIES:
            _cache.pop(next(iter(_cache)))
        _cache[fp] = out
    return out


def maybe_solve(enc: Encoded, shards: int = 0):
    """The degrading entry: None when guidance is disabled, the
    problem is degenerate, or the solve failed — callers then run the
    exact unguided path they ran before this module existed. An LP
    hiccup is advisory-only and must never block a tick (the packing
    solve underneath still rides the resilience ladder)."""
    if not enabled():
        return None
    if enc.compat.shape[0] == 0 or not (enc.cfg_pool >= 0).any():
        return None
    try:
        dlp = solve(enc, shards=shards)
    except Exception as err:
        from karpenter_tpu.metrics.store import SOLVER_LP_SOLVES

        SOLVER_LP_SOLVES.inc({"outcome": "degraded"})
        log.warning("device LP degraded to unguided path: %s", err)
        return None
    # decision explainability (karpenter_tpu/explain): the duals ARE
    # the economic reading of the tick — attach a per-solve summary to
    # the open record (no record open / kill switch -> one global read)
    from karpenter_tpu import explain

    if explain.active() is not None:
        explain.note_lp(dual_summary(enc, dlp))
    return dlp


def dual_summary(enc: Encoded, dlp: DeviceLP, k: int = 3) -> dict:
    """The per-solve dual digest the explain plane records: the top-k
    binding demand groups (their scaled dual prices — what one more
    pod of that shape would cost the fleet), the reservation-cap duals
    (what one more reserved instance would be worth), and the
    certified bound. Values are the float64 host-certified duals,
    rounded for stable replay comparison."""
    lam = dlp.lam
    order = [
        int(gi) for gi in np.argsort(-lam, kind="stable")[:k]
        if lam[gi] > 0
    ]
    return {
        "bound": round(float(dlp.lower_bound), 6),
        "binding_groups": [
            {
                "group": gi,
                "dual": round(float(lam[gi]), 6),
                "pods": int(enc.group_count[gi]),
                "priority": (
                    int(enc.group_priority[gi])
                    if enc.group_priority is not None else 0
                ),
            }
            for gi in order
        ],
        "reservation_cap_duals": [
            round(float(m), 6) for m in dlp.mu.tolist()
        ],
        "iterations": int(dlp.iterations),
        "converged": bool(dlp.converged),
        # NOTE: cache_hit/wall_s deliberately absent — both track
        # process history (the LRU, machine speed), not the decision,
        # and would break the replay byte-identity contract
    }


def rank_prices(enc: Encoded, dlp: DeviceLP,
                beta: float | None = None) -> np.ndarray:
    """Dual-adjusted reduced-cost ranking of the launchable configs,
    expressed in the packer's native ordering input — a price vector.
    Configs the LP deems over-priced (price above their dual value)
    are penalized by their reduced cost, steering the kernel's
    cost-mode opens toward LP-efficient machines; under-priced configs
    keep their true price. Decode never sees this vector (node prices
    always come from enc.cfg_price), and the ranked pack only ever
    RACES the unguided arms, so the result is never worse."""
    beta = rank_beta() if beta is None else beta
    launch = enc.cfg_pool >= 0
    price = enc.cfg_price.astype(np.float64)
    vh = dlp.vhat
    # priority fold: value configs by the guidance duals' scale
    # (value comparison, not object identity — lam_guide is always a
    # fresh array; with uniform priorities the scale is exactly 1.0)
    if len(dlp.lam) and np.max(dlp.lam) > 0:
        scale = np.max(dlp.lam_guide) / np.max(dlp.lam)
        if scale != 1.0:
            vh = vh * max(scale, _EPS)
    rc = np.clip(price - vh, 0.0, None)
    out = np.where(launch, price + beta * rc, price)
    return out.astype(np.float32)


class DualCertificate:
    """Weak-duality machinery for consolidation probe pruning.

    Built from one encode of the probe problem (the LaneSolver's union
    encode): `lam` is dual-feasible for every uncapped launchable
    config, `mu`/cap budgets buy back reserved configs' excess, and
    `absorb[e]` upper-bounds the dual value existing node e's free
    capacity could host. For a candidate set S with pod demand d:

        launch_cost(any repack of d without S)
            >= lam.d - sum_{e not in S} absorb[e] - cap.mu

    so when that bound meets the candidates' current price (plus the
    safety margin), no strictly-cheaper replacement exists and the
    probe can only return None — skipping it is decision-identical.
    """

    def __init__(self, enc: Encoded, dlp: DeviceLP):
        self.lam = dlp.lam
        self.cap_term = float(
            dlp.mu @ (enc.rsv_cap.astype(np.float64)
                      if enc.rsv_cap is not None and enc.rsv_cap.size
                      else np.zeros(0))
        ) if len(dlp.mu) else 0.0
        G, C = enc.compat.shape
        live = enc.group_count > 0
        req = enc.group_req.astype(np.float64)
        safe_req = np.where(req > 0, req, 1.0)
        ratio = np.where(
            (req > 0) & live[:, None], self.lam[:, None] / safe_req, 0.0
        )
        # per existing column: the same closed-form value bound as the
        # LP, over the node's remaining allocatable — ONE batched
        # [G, E, R] computation, not a Python loop (a probe batch
        # stages the whole fleet as existing rows; thousands of
        # per-node numpy passes would cost the very seconds the pruner
        # exists to save)
        self.absorb: dict[int, float] = {}
        ex_cols = np.array(
            [ci for ci in np.flatnonzero(enc.cfg_pool < 0)
             if enc.configs[ci].existing_index >= 0],
            dtype=np.int64,
        )
        total = 0.0
        if ex_cols.size:
            ex_idx = np.array(
                [enc.configs[ci].existing_index for ci in ex_cols]
            )
            compat_e = enc.compat[:, ex_cols] & live[:, None]   # [G, E]
            alloc_e = np.clip(
                enc.cfg_alloc[ex_cols].astype(np.float64), 0.0, None
            )                                                   # [E, R]
            reqpos = req > 0                                    # [G, R]
            # zero-capacity axes stay valid: an exhausted axis every
            # candidate pod needs bounds the node's absorbable value
            # at exactly 0 (see _stage's valid_r note)
            bad = np.einsum(
                "ge,gr->er", compat_e, (~reqpos).astype(np.float64)
            ) > 0                                               # [E, R]
            mm = np.max(
                np.where(compat_e[:, :, None], ratio[:, None, :], 0.0),
                axis=0,
            )                                                   # [E, R]
            with np.errstate(invalid="ignore"):
                v = np.where(~bad, mm * alloc_e, np.inf)
            vals = np.min(v, axis=1)
            vals = np.where(np.isfinite(vals), np.clip(vals, 0.0, None), 0.0)
            any_compat = compat_e.any(axis=0)
            vals = np.where(any_compat, vals, 0.0)
            for ei, val in zip(ex_idx.tolist(), vals.tolist()):
                self.absorb[ei] = val
            total = float(vals.sum())
        self.absorb_total = total

    def floor(
        self,
        demand: np.ndarray,          # [G] pod counts of the candidates
        candidate_rows: list[int],   # existing_index of each candidate
    ) -> float:
        """The weak-duality lower bound on ANY repack of `demand`
        without the candidate rows: λ'·d minus the rest of the
        fleet's absorbable value minus the reservation-cap term. The
        number IS the economic explanation the explain plane records
        ('kept because no replacement can beat $X/hr')."""
        absorb_rest = self.absorb_total - sum(
            self.absorb.get(r, 0.0) for r in set(candidate_rows)
        )
        return (
            float(self.lam @ demand.astype(np.float64))
            - max(absorb_rest, 0.0)
            - self.cap_term
        )

    def cannot_pay(
        self,
        demand: np.ndarray,          # [G] pod counts of the candidates
        candidate_rows: list[int],   # existing_index of each candidate
        current_price: float,
        margin: float | None = None,
        floor: float | None = None,
    ) -> bool:
        """THE prune predicate — callers that also report the floor
        (the explain plane's kept:lp-prune evidence) pass it back in
        so the decision and the evidence can never desync."""
        margin = prune_margin() if margin is None else margin
        if floor is None:
            floor = self.floor(demand, candidate_rows)
        return floor >= current_price * (1.0 + margin) + 1e-9


def warm(shapes) -> int:
    """AOT-compile the ascent for (G, C, R) shape buckets — called by
    the warm pool so the first guided solve of a warmed bucket skips
    the XLA trace. Returns the number of programs compiled."""
    import jax.numpy as jnp

    n = 0
    n_iters = iters()
    for G, C, R in shapes:
        Gp, Cp = _pad_to(G), _pad_to(C)
        # both cap-row variants real solves can hit: 1 (no
        # reservations) and the first bucket (up to 64 reservation
        # slots) — the jit signature keys on these SHAPES
        for Kp in (1, _cap_rows(1)):
            try:
                from karpenter_tpu.solver import telemetry

                telemetry.request_lp_capture(Gp, Cp, R, Kp, n_iters)
                _ascend(
                    jnp.zeros(Gp, jnp.float32),
                    jnp.zeros(Gp, jnp.float32),
                    jnp.zeros(Gp, jnp.float32),
                    jnp.zeros((Gp, Cp), bool),
                    jnp.zeros((Gp, R), jnp.float32),
                    jnp.zeros((Cp, R), jnp.float32),
                    jnp.zeros(Cp, jnp.float32),
                    jnp.zeros((Cp, R), bool),
                    jnp.zeros((Kp, Cp), bool),
                    jnp.zeros(Kp, jnp.float32),
                    jnp.zeros(Cp, bool),
                    n_iters=n_iters,
                )
                n += 1
            except Exception as err:  # pragma: no cover - defensive
                log.debug("lp warm compile failed for %s: %s", (G, C, R),
                          err)
    return n


def reset() -> None:
    """Test hook: drop the dual-solve cache."""
    with _cache_lock:
        _cache.clear()
