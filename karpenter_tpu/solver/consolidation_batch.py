"""Batched consolidation probe solver: candidate subsets as lanes of
one device solve.

BENCH_r05 showed `consolidation_500` burning ~33s because every probe
of the disruption engine's searches — each binary-search prefix of
`multi_node_consolidation`, each pool-rotation candidate of
`single_node_consolidation`, each ranked candidate of `drift` — paid a
full `deep_copy_nodes()` snapshot, a fresh Scheduler, a fresh encode
(including the per-node pseudo-config compat columns, the dominant
host cost), and an independent kernel dispatch. CvxCluster (PAPERS.md)
gets its orders-of-magnitude by batching many small allocation
problems into one solver call; the probes have exactly that shape:

- every probe shares ONE cluster snapshot and ONE catalog — only the
  *masked-out node subset* and the *pods to repack* differ;
- a probe's pods are always a subset of the union of all probes' pods,
  so one `group_pods` + `encode` over the union covers every lane
  (groups a lane doesn't use carry count 0 and are exact no-ops in the
  packing kernel — `remaining=0` never places or opens);
- a probe's retained fleet is the full bound-row block with the
  candidate rows' `bound_live` bits cleared — dead rows contribute
  capacity 0 to the prefix fill, so the live rows keep both their
  relative order and their exact per-row arithmetic.

Two layers:

1. **LaneSolver** — the encode-once core. Takes (pools, existing
   inputs) once, then `solve(lanes)` stages the shared arrays exactly
   like `pack._run_pack` (same padding buckets, so the warm pool can
   AOT-compile probe shapes) and dispatches `pack_probe_lanes_flat`
   (pack_split vmapped over the lane axis) in chunks of
   `KARPENTER_PROBE_BATCH_WIDTH`. Each lane decodes through the same
   `_build_solution_arrays` path a sequential solve uses, against a
   per-lane view of the Encoded whose groups hold that lane's own
   pods — so per-lane Solutions are bit-identical to solving the
   subset problem alone (the oracle test asserts this for both pack
   objectives).

2. **BatchProbeSolver** — the DisruptionEngine wrapper that makes a
   lane equal to one `simulate_scheduling(candidates)` call: it builds
   ONE Scheduler over the full snapshot (existing inputs, daemon
   overhead, reservation usage, minValues pool filtering — all paid
   once per reconcile round instead of once per probe), injects volume
   topology the way `Scheduler._solve` does, and converts each lane
   Solution into a SchedulerResults with the same minValues
   enforcement and instance-type finalization. Anything the batched
   fast path cannot reproduce exactly falls back to the sequential
   probe: topology-constrained / host-port / volume-limited pods and
   reservation-holding candidates gate the whole batch; lanes whose
   solve k-way-evicted pods or left relaxable pods unscheduled gate
   just that lane (the engine's probe cache simply has no entry, and
   `simulate_scheduling` runs as before).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.metrics.store import (
    SOLVER_DEVICE_STEPS,
    SOLVER_PROBE_BATCH,
)
from karpenter_tpu.solver.encode import (
    Encoded,
    ExistingNodeInput,
    PodGroup,
    encode,
    group_pods,
)
from karpenter_tpu.solver.solver import Solution, _build_solution_arrays

log = logging.getLogger("karpenter.solver.probes")


def _pow2(n: int, base: int) -> int:
    out = base
    while out < n:
        out *= 2
    return out


@dataclass
class ProbeLane:
    """One candidate subset to evaluate: mask these nodes out, repack
    these pods against what remains."""

    exclude_names: tuple[str, ...]
    pods: list[Pod] = field(default_factory=list)


class LaneSolver:
    """Encode-once, mask-per-lane probe driver over one fleet state.

    `existing_inputs` is the FULL fleet (candidates included); each
    lane names the nodes it removes. `pending` pods (shared backlog)
    join every lane's demand, exactly as `simulate_scheduling` adds
    them to every sequential probe.
    """

    def __init__(
        self,
        pools_with_types,
        existing_inputs: Sequence[ExistingNodeInput],
        daemon_overhead: Optional[dict] = None,
        reserved_in_use: Optional[dict[str, int]] = None,
        pending: Sequence[Pod] = (),
        compat_cache=None,
        shape_floors: Optional[dict[str, int]] = None,
    ):
        self.pools = list(pools_with_types)
        self.inputs = list(existing_inputs)
        self.daemon_overhead = daemon_overhead
        self.reserved_in_use = dict(reserved_in_use or {})
        self.pending = list(pending)
        self.compat_cache = compat_cache
        # padded-axis floors ({"G","C","E","F"}): a caller probing a
        # SHRINKING fleet round after round (the consolidation
        # convergence loop) pins later rounds onto the first round's
        # compiled shapes — padding is semantically inert (zero-count
        # groups, all-zero config columns, dead bound rows), so this
        # trades a little wasted arithmetic for zero recompiles
        self.shape_floors = dict(shape_floors or {})
        # the padded shapes of the last staging, for chaining floors
        self.last_shapes: dict[str, int] = {}
        self._idx = {inp.name: i for i, inp in enumerate(self.inputs)}
        # the last staged union encode + pod->group map (solve_lazy
        # fills them) — the dual-certificate pruner reads both
        self.last_enc = None
        self.last_gi_by_key: dict[str, int] = {}
        self._certificate = None

    def dual_certificate(self):
        """Lazy DualCertificate over the last staged union encode —
        the weak-duality pruner the engine consults before simulating
        a candidate subset (solver/lp_device.py). None when guidance
        is off, nothing is staged yet, or the LP degraded. The
        degraded outcome is memoized as False until the next staging:
        a search ladder probes its certificate once per candidate
        subset, and re-attempting a persistently-failing LP per probe
        would turn the pruning fast-path into repeated wasted work
        (and per-probe metric/log spam)."""
        if self._certificate is not None:
            return self._certificate or None
        from karpenter_tpu.solver import lp_device

        if self.last_enc is None or not lp_device.enabled():
            return None
        dlp = lp_device.maybe_solve(self.last_enc)
        if dlp is None:
            self._certificate = False  # degraded: don't retry this staging
            return None
        try:
            self._certificate = lp_device.DualCertificate(self.last_enc, dlp)
        except Exception:
            log.exception("dual certificate build failed; not pruning")
            self._certificate = False
            return None
        return self._certificate

    def knows(self, name: str) -> bool:
        return name in self._idx

    # -- solve ----------------------------------------------------------------

    def solve(self, lanes: Sequence[ProbeLane], mode: str = "ffd") -> list[Solution]:
        """Per-lane Solutions, index-aligned with `lanes` (eagerly
        decoded — see solve_lazy for the probe-search entry)."""
        return [thunk() for thunk in self.solve_lazy(lanes, mode=mode)]

    def solve_lazy(self, lanes: Sequence[ProbeLane], mode: str = "ffd"):
        """Stage the whole lane batch eagerly (one encode, one set of
        padded device arrays shared by every lane) and return per-lane
        zero-arg thunks; DEVICE dispatch happens lazily per chunk of
        `probe_batch_width()` lanes when a lane in that chunk is first
        consulted, and decode lazily per lane. A prefix-ladder search
        consults only O(log n) of its n primed lanes — lazy dispatch
        keeps kernel AND decode cost proportional to probes actually
        consulted, not lanes shipped, while the staging amortization
        covers them all. Width 1 (the CPU default) dispatches the
        plain `pack_split_flat` kernel per consulted lane — identical
        layout, no lane axis, one compiled shape reused across every
        probe of the search. Existing assignments index into THIS
        solver's `existing_inputs` (the full fleet), never a
        lane-local subset."""
        import jax.numpy as jnp

        from karpenter_tpu.solver.pack import (
            _bucket,
            _lane_bucket,
            _pad_axis,
            pack_probe_lanes_flat,
            probe_batch_width,
            wavefront_plan,
        )

        lane_pod_lists = [list(lane.pods) + self.pending for lane in lanes]
        union: dict[str, Pod] = {}
        for pods in lane_pod_lists:
            for p in pods:
                union.setdefault(p.key, p)
        if not union:
            # nothing to place anywhere: every lane trivially succeeds
            return [
                lambda: Solution(new_nodes=[], existing=[], unschedulable=[])
                for _ in lanes
            ]
        groups = group_pods(list(union.values()))
        gi_by_key = {
            p.key: gi for gi, g in enumerate(groups) for p in g.pods
        }
        enc = encode(
            groups,
            self.pools,
            self.inputs,
            self.daemon_overhead,
            reserved_in_use=self.reserved_in_use,
            compat_cache=self.compat_cache,
        )
        # expose the staged problem to the dual-certificate pruner
        # (certificate invalidated: it is a function of this encode)
        self.last_enc = enc
        self.last_gi_by_key = gi_by_key
        self._certificate = None

        # the staging below intentionally omits the bound_quota /
        # group_cap forwarding pack._run_pack does — probe-path encodes
        # never produce them (they exist only on the topology-lowered
        # path, which the probe gates route sequentially). If that
        # assumption ever breaks, fail loudly rather than silently
        # diverging from the sequential oracle.
        assert enc.existing_quota is None and enc.group_cap is None, (
            "probe staging does not forward existing_quota/group_cap; "
            "route this solve through the sequential path"
        )
        G, C = enc.compat.shape
        R = enc.group_req.shape[1]
        E = enc.n_existing
        L = len(lanes)

        # per-lane demand over the UNPADDED axes (lane group pod lists
        # materialize lazily at decode)
        counts = np.zeros((L, G), np.int32)
        for li, pods in enumerate(lane_pod_lists):
            for p in pods:
                counts[li, gi_by_key[p.key]] += 1
        base_live = np.zeros((E,), bool)
        bound_cfg_raw = np.full((E,), -1, np.int32)
        for ci, cfg in enumerate(enc.configs):
            if cfg.existing_index >= 0:
                bound_cfg_raw[cfg.existing_index] = ci
        base_live[:] = bound_cfg_raw >= 0
        live = np.repeat(base_live[None, :], max(L, 1), axis=0)
        for li, lane in enumerate(lanes):
            for name in lane.exclude_names:
                live[li, self._idx[name]] = False

        # -- shared staging, mirroring pack._run_pack's padding exactly
        # (then raised to any caller-pinned floors; see shape_floors)
        Gp, Cp = _pad_axis(G), _pad_axis(C)
        Cp = -(-Cp // 32) * 32
        Ep = _pad_axis(E) if E else 0
        Gp = max(Gp, self.shape_floors.get("G", 0))
        Cp = -(-max(Cp, self.shape_floors.get("C", 0)) // 32) * 32
        Ep = max(Ep, self.shape_floors.get("E", 0))

        compat = np.zeros((Gp, Cp), bool)
        compat[:G, :C] = enc.compat
        group_req = np.zeros((Gp, R), np.float32)
        group_req[:G] = enc.group_req
        cfg_alloc = np.zeros((Cp, R), np.float32)
        cfg_alloc[:C] = enc.cfg_alloc
        cfg_pool = np.full((Cp,), -1, np.int32)
        cfg_pool[:C] = enc.cfg_pool
        cfg_price = np.zeros((Cp,), np.float32)
        cfg_price[:C] = enc.cfg_price

        bound_cfg = np.full((Ep,), -1, np.int32)
        bound_cfg[:E] = bound_cfg_raw
        bound_live_any = bound_cfg >= 0
        safe_cfg = np.maximum(bound_cfg, 0)
        bound_alloc = np.where(
            bound_live_any[:, None], cfg_alloc[safe_cfg], 0.0
        ).astype(np.float32)
        bound_used0 = np.zeros((Ep, R), np.float32)
        bound_compat = np.zeros((Gp, Ep), bool)
        if Ep:
            bound_compat[:, :] = compat[:, safe_cfg] & bound_live_any[None, :]

        cfg_rsv_j = None
        rsv_cap_j = None
        K = 0
        cfg_rsv_h = np.full((Cp,), -1, np.int32)
        if enc.rsv_cap is not None and enc.rsv_cap.size:
            K = int(enc.rsv_cap.size)
            cfg_rsv_h[:C] = enc.cfg_rsv
            cfg_rsv_j = jnp.asarray(cfg_rsv_h)
            rsv_cap_j = jnp.asarray(enc.rsv_cap.astype(np.float32))
        bound_slot = np.where(
            bound_live_any & (cfg_rsv_h[safe_cfg] >= 0),
            cfg_rsv_h[safe_cfg], K,
        ).astype(np.int32)
        conflict_j = None
        if enc.conflict is not None and enc.conflict.any():
            cf = np.zeros((Gp, Gp), bool)
            cf[:G, :G] = enc.conflict
            conflict_j = jnp.asarray(cf)

        shared = (
            jnp.asarray(compat),
            jnp.asarray(group_req),
            jnp.asarray(cfg_alloc),
            jnp.asarray(cfg_pool),
            jnp.asarray(enc.pool_overhead),
            jnp.asarray(bound_compat),
            jnp.asarray(bound_alloc),
            jnp.asarray(bound_used0),
            jnp.asarray(bound_slot),
            jnp.asarray(cfg_price),
        )

        # fresh-axis estimate: per-group best single-node capacity once,
        # then the max per-lane ceil-sum (same bound pack._estimate_nodes
        # uses, per lane); capped first attempts regrow like
        # solve_packing_async
        launch = enc.cfg_pool >= 0
        per_best = np.ones((G,))
        for gi in range(G):
            mask = enc.compat[gi] & launch
            if not mask.any():
                continue
            req = enc.group_req[gi]
            safe = np.where(req > 0, req, 1.0)
            pn = np.floor((enc.cfg_alloc[mask] + 1e-4) / safe[None, :])
            pn = np.where(req[None, :] > 0, pn, np.inf).min(axis=1)
            per_best[gi] = max(1.0, float(pn.max()) if pn.size else 1.0)
        lane_est = np.ceil(counts / per_best[None, :]).sum(axis=1)
        lane_total = counts.sum(axis=1)
        worst_case = int(lane_total.max()) if L else 0
        F = _bucket(max(32, int(1.35 * float(lane_est.max() if L else 0)) + 16))
        F = max(F, self.shape_floors.get("F", 0))
        self.last_shapes = {"G": Gp, "C": Cp, "E": Ep, "F": F}

        from karpenter_tpu.solver.pack import pack_split_flat

        width = probe_batch_width()
        # chunk index -> (flat [len(chunk), ...], F_used, Gp_used,
        # rows-or-None): dispatched (and cap-regrown) on first
        # consultation of any member lane
        chunk_cache: dict[int, tuple] = {}

        def dispatch(ci: int) -> tuple:
            hit = chunk_cache.get(ci)
            if hit is not None:
                return hit
            from karpenter_tpu import tracing

            with tracing.span("disruption.probe_batch", chunk=ci):
                return _dispatch_traced(ci)

        def _dispatch_traced(ci: int) -> tuple:
            from karpenter_tpu import tracing
            from karpenter_tpu.solver import faults, resilience

            chunk = list(range(ci * width, min((ci + 1) * width, L)))
            tracing.annotate(lanes=len(chunk))
            # counted once per chunk — cap-regrow retries re-dispatch
            # (counted as batch + capped_retry) but don't re-ship lanes
            SOLVER_PROBE_BATCH.inc(
                {"outcome": "lane"}, value=float(len(chunk))
            )
            solo = len(chunk) == 1
            if solo:
                # solo fast path (the CPU default): the plain split
                # kernel, no lane axis, with the group axis COMPACTED
                # to this lane's nonzero groups and the fresh axis
                # sized from this lane's own estimate — the dispatched
                # program does exactly the work a sequential subset
                # solve would (zero-count union groups cost full
                # [F, C, R] sweeps otherwise), while the staging
                # stays shared
                li = chunk[0]
                rows = np.flatnonzero(counts[li])
                gsel = rows if rows.size else np.zeros((0,), np.int64)
                # LEVEL-coupled power-of-two padding: solo probes
                # compile one program per (G, F) shape combo, and the
                # padded sweep is tens of ms where an XLA compile is
                # ~1s — so both axes snap to ONE shared level k
                # (G=16<<k, F=64<<k), collapsing the combo grid to its
                # diagonal. A search's probes then touch at most a
                # handful of compiled programs, all reusable across
                # rounds while the fleet axes (pinned by shape_floors)
                # hold still.
                g_level = 0
                while (16 << g_level) < max(int(gsel.size), 1):
                    g_level += 1
                f_req = max(32, int(1.35 * float(lane_est[li])) + 16)
                f_level = 0
                while (64 << f_level) < f_req:
                    f_level += 1
                k = max(g_level, f_level)
                Gp_c = 16 << k
                compat_c = np.zeros((Gp_c, Cp), bool)
                compat_c[: gsel.size] = compat[gsel]
                req_c = np.zeros((Gp_c, R), np.float32)
                req_c[: gsel.size] = group_req[gsel]
                counts_c = np.zeros((Gp_c,), np.int32)
                counts_c[: gsel.size] = counts[li][gsel]
                bcompat_c = np.zeros((Gp_c, Ep), bool)
                bcompat_c[: gsel.size] = bound_compat[gsel]
                conflict_c = None
                if conflict_j is not None and gsel.size:
                    cfc = np.zeros((Gp_c, Gp_c), bool)
                    cfc[: gsel.size, : gsel.size] = (
                        enc.conflict[np.ix_(gsel, gsel)]
                        if enc.conflict is not None else False
                    )
                    conflict_c = jnp.asarray(cfc)
                live_row = np.zeros((Ep,), bool)
                live_row[:E] = live[li]
                F_try = 64 << k
                worst = int(lane_total[li])
                Gp_used = Gp_c
            else:
                F_try = F
                worst = int(lane_total[chunk].max())
                Gp_used = Gp
                gsel = None
            while True:
                N = Ep + F_try
                W = Cp // 32
                SOLVER_PROBE_BATCH.inc({"outcome": "batch"})
                # device-bound probe dispatch: the fault site chaos
                # drives (`...@probe`), with breaker bookkeeping so a
                # faulting device stops attracting probe batches — the
                # raised error falls through the verdict wrapper to
                # the sequential path, whose own solve rides the
                # resilience ladder down to the host oracle
                try:
                    faults.fire("probe")
                    # probes inherit the wavefront step reduction: the
                    # width is judged per dispatch on the REAL group
                    # count the kernel will walk (the lane's compacted
                    # groups for a solo probe, the shared union for a
                    # batch), exactly like pack._run_pack. The kwarg is
                    # only PASSED when active (an explicit wavefront=0
                    # would key a separate jit entry and recompile the
                    # warm sequential programs); stats append after the
                    # sequential layout, so the offset-based lane
                    # decode below needs no awareness of them
                    if solo:
                        wf = wavefront_plan(int(gsel.size))
                        flat = np.asarray(pack_split_flat(
                            jnp.asarray(compat_c), jnp.asarray(req_c),
                            jnp.asarray(counts_c),
                            shared[2], shared[3], shared[4],
                            jnp.asarray(bcompat_c),
                            shared[6], shared[7], shared[8],
                            jnp.asarray(live_row), shared[9],
                            max_free=F_try, mode=mode,
                            **({"wavefront": wf} if wf > 1 else {}),
                            cfg_rsv=cfg_rsv_j,
                            rsv_cap=rsv_cap_j, conflict=conflict_c,
                        ))[None, :]
                    else:
                        Lp = _lane_bucket(len(chunk))
                        counts_pad = np.zeros((Lp, Gp), np.int32)
                        counts_pad[: len(chunk), :G] = counts[chunk]
                        live_pad = np.zeros((Lp, Ep), bool)
                        live_pad[: len(chunk), :E] = live[chunk]
                        wf = wavefront_plan(G)
                        flat = np.asarray(pack_probe_lanes_flat(
                            shared[0], shared[1], jnp.asarray(counts_pad),
                            shared[2], shared[3], shared[4], shared[5],
                            shared[6], shared[7], shared[8],
                            jnp.asarray(live_pad), shared[9],
                            max_free=F_try, mode=mode,
                            **({"wavefront": wf} if wf > 1 else {}),
                            cfg_rsv=cfg_rsv_j,
                            rsv_cap=rsv_cap_j, conflict=conflict_j,
                        ))
                except Exception as err:
                    # only device-class failures charge the breaker: a
                    # host-side staging bug (deterministic) must not
                    # open it and exile ALL solves to the host oracle
                    reason = resilience.classify(err)
                    if reason in ("device_lost", "deadline",
                                  "compile_timeout"):
                        resilience.shared().breaker(
                            "device").record_failure(reason)
                    raise
                o1 = N * Gp_used + F_try * W
                # cheap cap check (a few ints per lane): a capped
                # lane's truncated answer must never be served, so the
                # chunk regrows the fresh axis and redispatches
                capped = any(
                    int(flat[row, o1]) >= N
                    and int(flat[row, o1 + 1 : o1 + 1 + Gp_used].sum()) > 0
                    for row in range(len(chunk))
                )
                if capped and F_try <= worst:
                    # one node holds >= one pod, so the largest lane's
                    # pod count bounds any legal fresh axis
                    grown = min(max(F_try * 2, F_try + 16), worst + 1)
                    F_try = _pow2(grown, 32) if solo else _bucket(grown)
                    SOLVER_PROBE_BATCH.inc({"outcome": "capped_retry"})
                    continue
                # device-step accounting, once per DISPATCH for both
                # kernels (per-lane observation would multiply the one
                # vmapped while_loop's rounds by the lane count): the
                # wavefront batch executes max-rounds-across-lanes, the
                # sequential kernel one step per padded group
                if wf > 1:
                    chunk_steps = int(max(
                        int(flat[r, o1 + 1 + 2 * Gp_used])
                        for r in range(len(chunk))
                    ))
                else:
                    chunk_steps = Gp_used
                SOLVER_DEVICE_STEPS.observe(
                    chunk_steps,
                    {"path": "wavefront" if wf > 1 else "sequential"},
                )
                chunk_cache[ci] = (flat, F_try, Gp_used, gsel)
                return chunk_cache[ci]

        def make_thunk(li: int):
            """Dispatch-if-needed + decode one lane on demand; memoized."""
            cell: list = []

            def thunk() -> Solution:
                if cell:
                    return cell[0]
                flat, F_used, Gp_used, gsel = dispatch(li // width)
                row = li % width
                N = Ep + F_used
                W = Cp // 32
                o0 = N * Gp_used
                o1 = o0 + F_used * W
                packed_a = flat[row, :o0].reshape(N, Gp_used)
                assign = np.zeros((N, G), np.int32)
                packed_u = flat[row, o1 + 1 : o1 + 1 + Gp_used]
                unsched = np.zeros((G,), np.int32)
                if gsel is None:
                    assign[:, :] = packed_a[:, :G]
                    unsched[:] = packed_u[:G]
                elif gsel.size:
                    assign[:, gsel] = packed_a[:, : gsel.size]
                    unsched[gsel] = packed_u[: gsel.size]
                node_count = int(flat[row, o1])
                node_mask = np.zeros((N, C), bool)
                live_rows = np.flatnonzero(live[li])
                if live_rows.size:
                    node_mask[live_rows, bound_cfg[live_rows]] = True
                if F_used:
                    words = np.ascontiguousarray(
                        flat[row, o0:o1].reshape(F_used, W)
                    )
                    bits = np.unpackbits(
                        words.view(np.uint8).reshape(F_used, W * 4),
                        axis=1, bitorder="little",
                    )
                    node_mask[Ep:] = bits[:, :C].astype(bool)
                node_active = assign.sum(axis=1) > 0
                node_active[:Ep] |= np.pad(live[li], (0, Ep - E))
                per: dict[int, list[Pod]] = {}
                for p in lane_pod_lists[li]:
                    per.setdefault(gi_by_key[p.key], []).append(p)
                lane_enc = replace(enc, groups=[
                    replace(g, pods=per.get(gi, []))
                    for gi, g in enumerate(groups)
                ])
                cell.append(_build_solution_arrays(
                    lane_enc,
                    np.flatnonzero(node_active[:node_count]),
                    node_mask,
                    assign,
                    unsched,
                ))
                return cell[0]

            return thunk

        return [make_thunk(li) for li in range(L)]


class ProbePruner:
    """Dual-based pruning of the consolidation probe ladder (ISSUE
    12): before the engine simulates a candidate subset, ask the
    lane solver's DualCertificate whether the subset can possibly be
    replaced strictly cheaper. Weak duality makes the answer
    conservative-exact — a pruned probe could only have returned "no
    command" — so pruning is decision-identical to the unpruned
    ladder (oracle-enforced, tests/test_lp_prune.py). Any gap in the
    certificate (unknown node, pod outside the staged union, LP
    degraded) returns False and the probe runs as before."""

    def __init__(self, lane_solver: LaneSolver):
        self.lane_solver = lane_solver
        # the last prune's certificate numbers (λ'·d floor vs price),
        # valid when cannot_pay just returned True — the engine hands
        # them to the explain plane as the kept:lp-prune evidence
        self.last: Optional[dict] = None

    def cannot_pay(self, candidates) -> bool:
        from karpenter_tpu.solver import lp_device

        self.last = None
        ls = self.lane_solver
        cert = ls.dual_certificate()
        if cert is None or ls.last_enc is None:
            return False
        gi = ls.last_gi_by_key
        demand = np.zeros(ls.last_enc.compat.shape[0], np.int64)
        rows: list[int] = []
        current_price = 0.0
        for c in candidates:
            name = c.state_node.name
            if not ls.knows(name):
                return False
            rows.append(ls._idx[name])
            current_price += float(c.price)
            for p in c.reschedulable_pods:
                g = gi.get(p.key)
                if g is None:
                    return False
                demand[g] += 1
        if current_price <= 0:
            return False
        margin = lp_device.prune_margin()
        floor = cert.floor(demand, rows)
        pruned = cert.cannot_pay(demand, rows, current_price,
                                 margin=margin, floor=floor)
        if pruned:
            self.last = {
                "lp_floor": round(floor, 6),
                "current_price": round(current_price, 6),
                "margin": margin,
            }
        return pruned


def _relaxable(pod: Pod) -> bool:
    """True when preferences.relax() would strip something — the
    sequential path retries such pods, so a batched lane that left one
    unscheduled must be re-probed sequentially, not cached. One
    canonical predicate (provisioning/preferences.relaxable) shared
    with the incremental live tick's fallback gate."""
    from karpenter_tpu.provisioning.preferences import relaxable

    return relaxable(pod)


class BatchProbeSolver:
    """simulate_scheduling-faithful probe batching for the engine.

    Construction pays the per-round costs once: one deep-copied
    snapshot becomes one Scheduler (existing inputs, daemon overhead,
    reservation ledger, catalog filtering). `prime(lane_specs)` then
    evaluates many candidate subsets in one kernel batch and returns,
    per lane, either the exact `(SchedulerResults, all_ok)` tuple the
    sequential probe would compute, or None when that lane (or the
    whole batch) must fall back to the sequential path.
    """

    def __init__(
        self,
        pools_with_types,
        snapshot,
        daemonsets,
        cluster_pods,
        pending_pods,
        options,
        kube,
        clock,
        compat_cache=None,
        existing_input_cache=None,
    ):
        from karpenter_tpu.provisioning.scheduler import Scheduler

        self.kube = kube
        self.scheduler = Scheduler(
            pools_with_types=pools_with_types,
            state_nodes=snapshot,
            # retained ExistingNodeInput rows from the fleet seam
            # (state/retained.py): unchanged nodes skip the per-node
            # input derivation
            existing_input_cache=existing_input_cache,
            daemonsets=daemonsets,
            cluster_pods=cluster_pods,
            allow_reserved=options.feature_gates.reserved_capacity,
            min_values_policy=options.min_values_policy,
            ignore_dra_requests=options.ignore_dra_requests,
            metrics_controller="disruption",
            kube=kube,
            clock=clock,
            objective="ffd",
            compat_cache=compat_cache,
        )
        self.pending = list(pending_pods)
        self.lane_solver = LaneSolver(
            self.scheduler.pools_with_types,
            self.scheduler.existing_inputs,
            daemon_overhead=self.scheduler.daemon_overhead,
            reserved_in_use=dict(self.scheduler.reserved_in_use),
            pending=self.pending,
            compat_cache=compat_cache,
        )
        # which snapshot nodes hold a reservation: masking one out
        # frees budget the shared encode cannot express per lane
        from karpenter_tpu.apis.v1.labels import RESERVATION_ID_LABEL
        from karpenter_tpu.provisioning.scheduler import _state_node_key

        self._reserved_nodes: set[str] = set()
        for node in snapshot:
            rid = node.labels().get(RESERVATION_ID_LABEL, "")
            if not rid and node.node_claim is not None:
                for spec in node.node_claim.spec.requirements:
                    if spec.key == RESERVATION_ID_LABEL and spec.values:
                        rid = spec.values[0]
                        break
            if rid:
                self._reserved_nodes.add(_state_node_key(node))

    def pruner(self) -> ProbePruner:
        """The dual-certificate pruner over this batch's staged union
        problem (valid once prime() has staged it)."""
        return ProbePruner(self.lane_solver)

    def usable(self) -> bool:
        """False when the sequential path would not run the in-process
        device kernel — matching its backend is part of the oracle
        contract — or when the device breaker is open (a faulting
        device must not attract whole probe batches that each burn a
        failure before degrading; the sequential path's ladder goes
        straight to the working rung)."""
        import os

        if os.environ.get("KARPENTER_SOLVER_BACKEND", "jax") == "host":
            return False
        try:
            from karpenter_tpu.service.client import endpoint_from_env

            if endpoint_from_env():
                return False
        except Exception:
            pass
        from karpenter_tpu.solver import resilience

        if resilience.shared().breaker("device").is_open():
            log.warning(
                "device breaker open; consolidation probing sequentially")
            return False
        return True

    def _batch_eligible(self, pods: Sequence[Pod]) -> tuple[bool, set[str]]:
        """(eligible, dra_keys): the batched path only reproduces the
        Scheduler's FAST path. Pods that would route to the topology /
        host-port / volume-limited machinery gate the whole batch; DRA
        pods are permanently errored exactly as Scheduler._solve does,
        so they just report as unscheduled per lane."""
        from karpenter_tpu.provisioning import volume_topology
        from karpenter_tpu.scheduling.hostports import pod_host_ports
        from karpenter_tpu.scheduling.volumeusage import pod_volume_drivers
        from karpenter_tpu.utils.pod import has_dra_requirements

        sched = self.scheduler
        dra: set[str] = set()
        limited = {
            d for usage in sched._volume_usage.values() for d in usage.limits
        }
        for pod in pods:
            if sched.ignore_dra_requests and has_dra_requirements(pod):
                dra.add(pod.key)
                continue
            if self.kube is not None and (
                pod.spec.volumes or pod.spec.injected_requirements
            ):
                # same per-solve re-derivation the sequential probe runs
                volume_topology.inject(pod, self.kube)
            if (
                limited
                and pod.spec.volumes
                and limited & pod_volume_drivers(pod, self.kube).keys()
            ):
                return False, dra
            if sched.topology.has_constraints(pod) or pod_host_ports(pod):
                return False, dra
        return True, dra

    def prime(self, lane_specs) -> Optional[list]:
        """Evaluate `lane_specs` (lists of Candidates) as one batch.
        Returns None when the WHOLE batch is unsupported, else a list
        aligned with lane_specs holding, per lane, a zero-arg thunk
        that decodes to `(SchedulerResults, all_ok)` — or to None when
        that lane turns out to need the sequential path — or None for
        lanes known-unsupported up front. The device work happens here;
        per-lane decode cost is deferred to the probes the search
        actually consults."""
        lanes: list[ProbeLane] = []
        lane_pods: list[list[Pod]] = []
        supported = [True] * len(lane_specs)
        for i, spec in enumerate(lane_specs):
            names = tuple(c.state_node.name for c in spec)
            pods = [p for c in spec for p in c.reschedulable_pods]
            if any(not self.lane_solver.knows(n) for n in names) or (
                self._reserved_nodes and self._reserved_nodes & set(names)
            ):
                supported[i] = False
                names, pods = (), []
            lanes.append(ProbeLane(exclude_names=names, pods=pods))
            lane_pods.append(pods)
        union: dict[str, Pod] = {}
        for pods in lane_pods:
            for p in pods:
                union.setdefault(p.key, p)
        for p in self.pending:
            union.setdefault(p.key, p)
        ok_batch, dra = self._batch_eligible(list(union.values()))
        if not ok_batch:
            SOLVER_PROBE_BATCH.inc(
                {"outcome": "fallback_lane"}, value=float(len(lane_specs))
            )
            return None
        # DRA pods never enter the solve (Scheduler gates them first)
        if dra:
            lanes = [
                ProbeLane(
                    exclude_names=lane.exclude_names,
                    pods=[p for p in lane.pods if p.key not in dra],
                )
                for lane in lanes
            ]
            self.lane_solver.pending = [
                p for p in self.pending if p.key not in dra
            ]
        try:
            lazy = self.lane_solver.solve_lazy(lanes, mode="ffd")
        except Exception:
            log.exception("probe batch failed; falling back to sequential")
            SOLVER_PROBE_BATCH.inc(
                {"outcome": "fallback_lane"}, value=float(len(lane_specs))
            )
            return None

        def make_verdict(i, decode):
            cell: list = []

            def verdict():
                if not cell:
                    try:
                        cell.append(
                            self._to_results(lane_pods[i], decode(), dra)
                        )
                    except Exception:
                        log.exception("probe lane decode failed; "
                                      "falling back to sequential")
                        cell.append(None)
                    if cell[0] is None:
                        SOLVER_PROBE_BATCH.inc({"outcome": "fallback_lane"})
                return cell[0]

            return verdict

        out = []
        for i, decode in enumerate(lazy):
            if not supported[i]:
                SOLVER_PROBE_BATCH.inc({"outcome": "fallback_lane"})
                out.append(None)
                continue
            out.append(make_verdict(i, decode))
        return out

    def _to_results(self, lane_pods, sol: Solution, dra: set[str]):
        """One lane's Solution -> the (SchedulerResults, all_ok) tuple
        `simulate_scheduling` would return — or None when sequential-
        only machinery (eviction retries, the preference-relaxation
        ladder) would have engaged."""
        from karpenter_tpu.provisioning.scheduler import (
            DRA_ERROR,
            NO_CAPACITY_ERROR,
            SchedulerResults,
        )

        sched = self.scheduler
        if sol.evicted:
            return None
        if sol.unschedulable and sched.honor_preferences and any(
            _relaxable(p) for p in sol.unschedulable
        ):
            return None
        results = SchedulerResults(new_node_plans=[], existing_assignments={})
        kept = [
            plan for plan in sol.new_nodes
            if sched._enforce_min_values(plan, results)
        ]
        for a in sol.existing:
            name = sched.existing_inputs[a.existing_index].name
            results.existing_assignments.setdefault(name, []).extend(a.pods)
        for pod in sol.unschedulable:
            results.errors[pod.key] = NO_CAPACITY_ERROR
        for key in dra:
            results.errors[key] = DRA_ERROR
        for plan in kept:
            sched._finalize_plan(plan)
            if sched._enforce_min_values(plan, results):
                results.new_node_plans.append(plan)
        scheduled = {
            p.key for plan in results.new_node_plans for p in plan.pods
        } | {
            p.key for ps in results.existing_assignments.values() for p in ps
        }
        all_ok = all(p.key in scheduled for p in lane_pods)
        return results, all_ok
