"""Dense encoding of the scheduling problem for the TPU solver.

The reference evaluates pod x node x instance-type feasibility with
nested Go loops over set objects (scheduler.go:515-647,
nodeclaim.go:373-447). Here the same semantics become dense arrays:

- A **config** is one launchable node variant: (NodePool, InstanceType,
  Offering). Its requirement set is the intersection of the pool
  template's requirements/labels, the instance type's requirements and
  the offering's zone/capacity-type pins. Existing and in-flight nodes
  are appended as one-hot *pseudo-configs* carrying their own labels
  and remaining allocatable, which unifies the scheduler's three scan
  tiers (existing -> in-flight -> new) into one node axis.

- Pods with identical (requirements, tolerations, resources) collapse
  into **groups**; grouped first-fit is equivalent to per-pod FFD for
  identical pods under the lowest-index tie-break.

- Per label key, pod-side allowed values and config-side values are
  boolean masks over a finite vocabulary; compatibility per key is a
  (groups x vocab) @ (vocab x configs) matmul > 0 — MXU work — ANDed
  across keys, with the reference's undefined-key rules
  (requirements.go:175-191): undefined well-known keys match, undefined
  custom keys match only NotIn/DoesNotExist.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_LABEL,
    WELL_KNOWN_LABELS,
)
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    Offering,
    effective_price as _effective_price,
)
from karpenter_tpu.kube.objects import Pod, Taint
from karpenter_tpu.scheduling.requirement import (
    DOES_NOT_EXIST,
    IN,
    NOT_IN,
    Requirement,
)
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.scheduling.taints import tolerates
from karpenter_tpu.utils import resources as resutil

# Resource axis order: the well-known resources first, extended after.
BASE_RESOURCES = (resutil.CPU, resutil.MEMORY, resutil.PODS, resutil.EPHEMERAL_STORAGE)


@dataclass
class PodGroup:
    """Pods sharing requirements/tolerations/resources/priority."""

    requirements: Requirements
    tolerations: tuple
    resources: dict[str, float]
    pods: list[Pod] = field(default_factory=list)
    # resolved PriorityClass value shared by the group's pods: groups
    # order priority-major, so the encode's group axis IS the
    # degradation order priority admission truncates against
    priority: int = 0

    @property
    def count(self) -> int:
        return len(self.pods)


def group_pods(pods: Sequence[Pod], required_only: bool = False) -> list[PodGroup]:
    """Group pods by scheduling signature, sorted priority-descending,
    then CPU+memory descending within a priority band (the reference
    queue's FFD order, scheduling/queue.go:31-60). Pods of different
    priorities never share a group — a group's unplaced tail must be
    attributable to ONE priority for the admission contract to hold —
    and with uniform priority (every pod 0, the common case) the order
    is byte-identical to the pre-priority sort.

    Requirements/resource parsing is memoized on a cheap raw-spec key so
    a 50k-pod batch with a few hundred distinct shapes pays the parse
    cost once per shape, not once per pod.
    """
    groups: dict[tuple, PodGroup] = {}
    parsed: dict[tuple, tuple] = {}
    for pod in pods:
        spec = pod.spec
        # Cheap hashable key over the scheduling-relevant raw spec;
        # frozensets avoid per-pod sorts (Toleration is a frozen
        # dataclass, so the tuple hashes directly).
        raw = (
            frozenset(spec.node_selector.items()) if spec.node_selector else None,
            tuple(r.signature() for r in spec.injected_requirements)
            if spec.injected_requirements else None,
            repr(spec.affinity) if spec.affinity is not None else None,
            tuple(spec.tolerations) if spec.tolerations else None,
            tuple(
                frozenset(c.requests.items())
                for c in spec.containers
            ),
            tuple(
                (frozenset(c.requests.items()), c.restart_policy)
                for c in spec.init_containers
            ) if spec.init_containers else None,
            frozenset(spec.overhead.items()) if spec.overhead else None,
            frozenset(spec.resources.items()) if spec.resources else None,
            spec.priority,
        )
        hit = parsed.get(raw)
        if hit is None:
            reqs = Requirements.from_pod(pod, required_only=required_only)
            resources = resutil.pod_requests(pod)
            tols = tuple(sorted(pod.spec.tolerations, key=repr))
            signature = (
                reqs.signature(),
                tols,
                tuple(sorted(resources.items())),
                spec.priority,
            )
            hit = (signature, reqs, tols, resources, spec.priority)
            parsed[raw] = hit
        signature, reqs, tols, resources, priority = hit
        group = groups.get(signature)
        if group is None:
            group = PodGroup(requirements=reqs, tolerations=tols,
                             resources=resources, priority=priority)
            groups[signature] = group
        group.pods.append(pod)
    return sorted(
        groups.values(),
        key=lambda g: (
            -g.priority,
            -(g.resources.get(resutil.CPU, 0.0)),
            -(g.resources.get(resutil.MEMORY, 0.0)),
            g.requirements.signature(),
        ),
    )


@dataclass
class ConfigInfo:
    """Host-side identity of one config column."""

    pool: Optional[NodePool]          # None for pseudo-configs
    instance_type: Optional[InstanceType]
    offering: Optional[Offering]
    existing_index: int = -1          # >=0 for pseudo-configs
    requirements: Requirements = field(default_factory=Requirements)
    taints: tuple[Taint, ...] = ()
    # NOTE: per-encode dedupe membership lives on Encoded.cfg_alts, NOT
    # here — ConfigInfo objects are shared across encodes by the
    # incremental cache, and a solution's lazy option lists must keep
    # reading the members of the encode that produced them.


@dataclass
class ExistingNodeInput:
    """One existing or in-flight node offered to the solver."""

    name: str
    requirements: Requirements        # labels (+ claim requirements if in-flight)
    taints: tuple[Taint, ...]
    available: dict[str, float]       # allocatable minus current usage
    pool_name: str = ""
    pod_count: int = 0


@dataclass
class Encoded:
    """Arrays shipped to the device solver plus host decode tables."""

    resource_keys: list[str]
    groups: list[PodGroup]
    configs: list[ConfigInfo]
    n_existing: int                       # pseudo-config / reserved node slots
    group_req: np.ndarray                 # [G, R] float32
    group_count: np.ndarray               # [G] int32
    compat: np.ndarray                    # [G, C] bool
    cfg_alloc: np.ndarray                 # [C, R] float32
    cfg_price: np.ndarray                 # [C] float32
    cfg_pool: np.ndarray                  # [C] int32 (pool order index; -1 pseudo)
    pool_overhead: np.ndarray             # [P+1, R] float32 daemon overhead per pool
    existing_used: np.ndarray             # [E, R] float32 (all zeros: available baked in)
    # Capacity-reservation budgets are keyed by reservation id, not by
    # config column: several columns (zones, pools, dedupe survivors)
    # can draw on ONE reservation and must share its remaining budget
    # (ReservationManager semantics, scheduling/reservationmanager.go).
    cfg_rsv: np.ndarray = None            # [C] int32 reservation slot, -1 = none
    rsv_cap: np.ndarray = None            # [K] f32 remaining instances per slot
    # Topology constraints lowered to solver-native form (see
    # solver/topo_batch.py): per-node pod caps per group (hostname
    # spread) and pairwise node-sharing exclusions (hostname
    # anti-affinity, host-port collisions).
    group_cap: np.ndarray = None          # [G] int32 max pods of g per node
    conflict: np.ndarray = None           # [G, G] bool mutually exclusive groups
    existing_quota: np.ndarray = None     # [E, G] int32 remaining cap per
                                          # existing node (counts already there)
    loose_groups: np.ndarray = None       # [G] bool groups constraining a key
                                          # configs leave open (k-way check
                                          # at decode)
    pool_min_values: np.ndarray = None    # [P+1] bool pools with minValues
                                          # floors (host decode metadata;
                                          # not shipped to the service)
    group_priority: np.ndarray = None     # [G] int32 resolved PriorityClass
                                          # value per group (groups order
                                          # priority-major — the degradation
                                          # order priority admission
                                          # truncates against). Host decode
                                          # metadata; not shipped to the
                                          # service.
    # After column dedupe, every member (price, ConfigInfo) each column
    # represents — identical (pool, allocatable, compat column) configs
    # collapse to one device column and re-expand at decode. Aligned
    # with `configs`; empty for pseudo-configs. Host decode metadata
    # (not shipped to the service) and PER-ENCODE: the lists belong to
    # this Encoded, so a shared-config cache can never clobber them.
    cfg_alts: list = None                 # [C] list[(price, ConfigInfo)]


def pool_template_requirements(
    pool: NodePool, with_labels: bool = True, with_pool_pin: bool = False
) -> Requirements:
    """The pool template's requirement set (spec requirements incl.
    minValues, plus template labels as IN pins, plus — with
    `with_pool_pin` — the karpenter.sh/nodepool identity pin that
    NewNodeClaimTemplate adds). The single source for every consumer —
    config building, domain discovery, daemon-overhead gating,
    minValues enforcement — so the assembly can't drift between
    sites."""
    reqs = Requirements()
    for spec in pool.spec.template.spec.requirements:
        reqs.add(Requirement(spec.key, spec.operator, spec.values, spec.min_values))
    if with_labels:
        for key, value in pool.spec.template.labels.items():
            reqs.add(Requirement(key, IN, [value]))
    if with_pool_pin:
        reqs.add(Requirement(NODEPOOL_LABEL, IN, [pool.metadata.name]))
    return reqs


def _config_requirements(
    pool: NodePool, it: InstanceType, offering: Offering
) -> Requirements:
    reqs = pool_template_requirements(pool, with_pool_pin=True)
    reqs.add(*it.requirements.values())
    reqs.add(*offering.requirements.values())
    return reqs


def build_configs(
    pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
    existing: Sequence[ExistingNodeInput] = (),
) -> list[ConfigInfo]:
    """Enumerate launchable configs (pool-weight order, then price) and
    append pseudo-configs for existing nodes."""
    return launch_configs(pools_with_types) + pseudo_configs(existing)


def launch_configs(
    pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
) -> list[ConfigInfo]:
    """The launchable-config columns alone — a pure function of the
    catalog, so the incremental encoder cache can reuse the list across
    solves. Shared ConfigInfos are treated as immutable by encode:
    per-encode dedupe membership lives on Encoded.cfg_alts, never
    here."""
    configs: list[ConfigInfo] = []
    for pool, types in pools_with_types:
        # only the template's permanent taints gate pod placement:
        # startupTaints clear before initialization, so pods are
        # assumed to schedule past them (the reference's
        # NodeClaimTemplate exposes only Taints to the scheduler;
        # statenode.go:322-326 ignores a claim's own startup taints
        # while it initializes)
        taints = tuple(pool.spec.template.spec.taints)
        # the pool template's own requirements filter which types and
        # offerings may launch under it (InstanceTypes.Compatible,
        # types.go:243; offering filtering nodeclaim.go:373-447). A
        # conflicting (pool, type/offering) pair must never become a
        # config: no pod references the conflicting key, so the compat
        # matrix would not catch it.
        pool_reqs = pool_template_requirements(pool)
        for it in types:
            if pool_reqs.intersects(it.requirements) is not None:
                continue
            for offering in it.offerings:
                if not offering.available:
                    continue
                if pool_reqs.intersects(offering.requirements) is not None:
                    continue
                configs.append(
                    ConfigInfo(
                        pool=pool,
                        instance_type=it,
                        offering=offering,
                        requirements=_config_requirements(pool, it, offering),
                        taints=taints,
                    )
                )
    return configs


def pseudo_configs(
    existing: Sequence[ExistingNodeInput] = (),
) -> list[ConfigInfo]:
    """One-hot pseudo-config columns for existing/in-flight nodes."""
    configs: list[ConfigInfo] = []
    for idx, node in enumerate(existing):
        configs.append(
            ConfigInfo(
                pool=None,
                instance_type=None,
                offering=None,
                existing_index=idx,
                requirements=node.requirements,
                taints=tuple(node.taints),
            )
        )
    return configs


def encode(
    groups: Sequence[PodGroup],
    pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
    existing: Sequence[ExistingNodeInput] = (),
    daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
    reserved_in_use: Optional[dict[str, int]] = None,
    group_cap: Optional[np.ndarray] = None,
    conflict: Optional[np.ndarray] = None,
    existing_quota: Optional[np.ndarray] = None,
    compat_cache=None,
) -> Encoded:
    """Build the dense problem (see _encode_impl for the semantics) —
    under a flight-recorder span: encode is the solver's first phase
    and every caller (scheduler fast path, topology batch, incremental
    repack, probe staging) inherits the instrumentation here."""
    from karpenter_tpu import tracing

    with tracing.span("solve.encode") as sp:
        enc = _encode_impl(
            groups, pools_with_types, existing, daemon_overhead,
            reserved_in_use=reserved_in_use, group_cap=group_cap,
            conflict=conflict, existing_quota=existing_quota,
            compat_cache=compat_cache,
        )
        sp.annotate(
            groups=len(enc.groups), configs=len(enc.configs),
            existing=enc.n_existing,
        )
    return enc


def _encode_impl(
    groups: Sequence[PodGroup],
    pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
    existing: Sequence[ExistingNodeInput] = (),
    daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
    reserved_in_use: Optional[dict[str, int]] = None,
    group_cap: Optional[np.ndarray] = None,
    conflict: Optional[np.ndarray] = None,
    existing_quota: Optional[np.ndarray] = None,
    compat_cache=None,
) -> Encoded:
    """Build the dense problem. `daemon_overhead` maps pool name ->
    resource list of daemonset pods that will land on new nodes
    (reference scheduler.go:772-803). `reserved_in_use` maps
    reservation id -> instances already consumed by live nodes; the
    remainder caps how many nodes the solver may open against that
    reservation (ReservationManager semantics,
    scheduling/reservationmanager.go:28-110).

    `compat_cache` (solver/incremental.EncodedCache) memoizes the
    launchable-column compat rows across solves keyed on group
    signature: a steady-state tick whose pod shapes mostly repeat pays
    the G x C requirement matmul only for NEW signatures (dirty rows);
    pseudo-config columns for existing nodes are always computed fresh
    (their labels/usage change tick to tick)."""
    import time as _time

    _t_encode = _time.perf_counter()
    if compat_cache is not None:
        configs = compat_cache.configs(pools_with_types, existing)
    else:
        configs = build_configs(pools_with_types, existing)
    n_launch = len(configs) - len(existing)

    # Resource axis: union of base + whatever appears anywhere.
    keys: list[str] = list(BASE_RESOURCES)
    seen = set(keys)
    for group in groups:
        for key in group.resources:
            if key not in seen:
                seen.add(key)
                keys.append(key)
    R = len(keys)
    G = len(groups)
    C = len(configs)

    group_req = np.zeros((G, R), np.float32)
    group_count = np.zeros((G,), np.int32)
    group_priority = np.zeros((G,), np.int32)
    for gi, group in enumerate(groups):
        group_count[gi] = group.count
        group_priority[gi] = group.priority
        for ri, key in enumerate(keys):
            group_req[gi, ri] = group.resources.get(key, 0.0)

    cfg_alloc = np.zeros((C, R), np.float32)
    cfg_price = np.zeros((C,), np.float32)
    cfg_pool = np.full((C,), -1, np.int32)
    cfg_rsv = np.full((C,), -1, np.int32)
    rsv_slots: dict[str, int] = {}
    rsv_cap_list: list[float] = []
    in_use = reserved_in_use or {}
    pool_order = {pool.metadata.name: i for i, (pool, _) in enumerate(pools_with_types)}

    def _reserve(ci: int, rid: str) -> None:
        remaining = float(
            max(0, configs[ci].offering.reservation_capacity - in_use.get(rid, 0))
        )
        slot = rsv_slots.get(rid)
        if slot is None:
            slot = len(rsv_cap_list)
            rsv_slots[rid] = slot
            rsv_cap_list.append(remaining)
        else:
            rsv_cap_list[slot] = max(rsv_cap_list[slot], remaining)
        cfg_rsv[ci] = slot

    if compat_cache is not None:
        # launchable arrays are catalog-static per resource axis;
        # only existing-node rows and reservation budgets (round
        # usage) are per-call
        la, lpr, lpo, lrids, lstatics = compat_cache.launch_arrays(
            keys, configs, n_launch, pool_order
        )
        cfg_alloc[:n_launch] = la
        cfg_price[:n_launch] = lpr
        cfg_pool[:n_launch] = lpo
        for ci in range(n_launch, C):
            node = existing[configs[ci].existing_index]
            for ri, key in enumerate(keys):
                cfg_alloc[ci, ri] = node.available.get(key, 0.0)
        for ci, rid in lrids:
            _reserve(ci, rid)
    else:
        for ci, cfg in enumerate(configs):
            if cfg.existing_index >= 0:
                node = existing[cfg.existing_index]
                for ri, key in enumerate(keys):
                    cfg_alloc[ci, ri] = node.available.get(key, 0.0)
                cfg_price[ci] = 0.0
            else:
                for ri, key in enumerate(keys):
                    cfg_alloc[ci, ri] = cfg.instance_type.allocatable.get(key, 0.0)
                # spot offerings are priced at price x (1 + interruption
                # penalty): the packer's cost signal accounts for the
                # expected reclaim, while the raw price stays what the
                # fleet pays (cloudprovider.types.effective_price)
                cfg_price[ci] = _effective_price(cfg.offering)
                cfg_pool[ci] = pool_order[cfg.pool.metadata.name]
                rid = cfg.offering.reservation_id
                if rid:
                    _reserve(ci, rid)

    if compat_cache is not None:
        # catalog already synced by the configs() call above — compat
        # consults the row cache without re-fingerprinting
        compat = compat_cache.compat(groups, configs, n_launch)
    else:
        compat = _full_compat(groups, configs)

    # Mutual exclusion: two groups can each be compatible with a
    # config yet unable to SHARE one node — their requirements pin a
    # key the config leaves open to several values (tier=gold vs
    # tier=silver on a template admitting both). The reference's
    # in-flight NodeClaim catches this by tightening its requirement
    # set per added pod (nodeclaim.go:114-167); here it becomes a
    # pairwise conflict row. Keys every launchable config pins to ONE
    # value (zone, arch, ...) cannot cause it — disjoint pins already
    # make the compat columns disjoint — so only groups constraining
    # an open key enter the quadratic check (almost always none).
    loose_groups = np.zeros((G,), bool)
    if configs and G > 0:
        from karpenter_tpu.scheduling.requirement import IN as _IN

        # pinning is judged over ALL config columns, existing nodes
        # included: a BYO node missing a well-known label (say
        # capacity-type) leaves that key open even though every launch
        # config pins it, and two groups pinning different values must
        # not share that node
        if compat_cache is not None:
            # launchable stats are catalog-static; fold in the
            # per-call existing configs only
            cached_ok, cached_have = compat_cache.pin_stats(
                configs, n_launch
            )
            pin_ok = dict(cached_ok)
            n_have = dict(cached_have)
            scan = configs[n_launch:]
        else:
            pin_ok = {}
            n_have = {}
            scan = configs
        for cfg in scan:
            for req in cfg.requirements:
                single = req.operator() == _IN and len(req.values) == 1
                n_have[req.key] = n_have.get(req.key, 0) + 1
                pin_ok[req.key] = pin_ok.get(req.key, True) and single
        always_pinned = {
            k for k, ok in pin_ok.items()
            if ok and n_have[k] == len(configs)
        }
        cand = [
            gi for gi, g in enumerate(groups)
            if any(k not in always_pinned for k in g.requirements.keys())
        ]
        # groups constraining an open key need k-way re-validation at
        # decode: pairwise rows cannot see a three-way empty
        # intersection (e.g. In[g,s] / In[s,b] / In[g,b])
        loose_groups[cand] = True
        mutual = None
        for i, a in enumerate(cand):
            for b in cand[i + 1 :]:
                if (
                    groups[a].requirements.intersects(
                        groups[b].requirements
                    )
                    is not None
                ):
                    if mutual is None:
                        mutual = np.zeros((G, G), bool)
                    mutual[a, b] = mutual[b, a] = True
        if mutual is not None:
            conflict = mutual if conflict is None else (conflict | mutual)

    n_pools = len(pools_with_types)
    pool_overhead = np.zeros((n_pools + 1, R), np.float32)
    if daemon_overhead:
        for pname, overhead in daemon_overhead.items():
            if pname in pool_order:
                for ri, key in enumerate(keys):
                    pool_overhead[pool_order[pname], ri] = overhead.get(key, 0.0)
    # host-side decode metadata (not shipped to the solver service):
    # pools whose templates carry minValues floors — mask-narrowing
    # post-passes must leave their nodes alone or they could drop a
    # plan's type coverage below the floor
    pool_min_values = np.zeros(n_pools + 1, bool)
    for pool, _types in pools_with_types:
        if pool_template_requirements(pool).has_min_values():
            pool_min_values[pool_order[pool.metadata.name]] = True

    # Column dedupe: launchable configs with identical (pool,
    # allocatable, compat column) are indistinguishable to the packer —
    # e.g. the same instance type's spot/on-demand offerings when no pod
    # constrains capacity-type. Collapse them to one column carrying the
    # min price; decode re-expands members into the offering list. This
    # typically halves C on the kwok catalog (3 zones x 2 capacity
    # types) and cuts device time proportionally.
    keep: list[int] = []
    by_key: dict[tuple, int] = {}
    alts_by_ci: dict[int, list] = {}

    def _dedupe_one(ci: int, key: tuple) -> None:
        cfg = configs[ci]
        rep = by_key.get(key)
        if rep is None:
            by_key[key] = ci
            alts_by_ci[ci] = [(float(cfg_price[ci]), cfg)]
            keep.append(ci)
        else:
            alts_by_ci[rep].append((float(cfg_price[ci]), cfg))
            if cfg_price[ci] < cfg_price[rep]:
                cfg_price[rep] = cfg_price[ci]

    if compat_cache is not None:
        # cached path: (pool, reservation, alloc-bytes) prefixes come
        # from the catalog-static table; the per-solve compat columns
        # are bit-packed in ONE vectorized pass instead of C sliced
        # copies (packbits is injective at fixed G, so key equality is
        # exactly column equality)
        col_bytes = np.ascontiguousarray(
            np.packbits(compat[:, :n_launch], axis=0).T
        )
        for ci in range(n_launch):
            _dedupe_one(ci, lstatics[ci] + (col_bytes[ci].tobytes(),))
        keep.extend(range(n_launch, C))
    else:
        for ci, cfg in enumerate(configs):
            if cfg.existing_index >= 0:
                keep.append(ci)
                continue
            key = (
                int(cfg_pool[ci]),
                # distinct reservations must not merge (their budgets
                # would collapse to one cap instead of the sum)
                cfg.offering.reservation_id if cfg.offering is not None else "",
                cfg_alloc[ci].tobytes(),
                compat[:, ci].tobytes(),
            )
            _dedupe_one(ci, key)
    cfg_alts = [alts_by_ci.get(i, []) for i in keep]
    if len(keep) < len(configs):
        configs = [configs[i] for i in keep]
        compat = np.ascontiguousarray(compat[:, keep])
        cfg_alloc = np.ascontiguousarray(cfg_alloc[keep])
        cfg_price = np.ascontiguousarray(cfg_price[keep])
        cfg_pool = np.ascontiguousarray(cfg_pool[keep])
        cfg_rsv = np.ascontiguousarray(cfg_rsv[keep])

    from karpenter_tpu.metrics import sentinel
    from karpenter_tpu.metrics.store import SOLVER_PHASE_DURATION

    _encode_wall = _time.perf_counter() - _t_encode
    SOLVER_PHASE_DURATION.observe(_encode_wall, {"phase": "encode"})
    sentinel.observe_phase("encode", _encode_wall)
    return Encoded(
        resource_keys=keys,
        groups=list(groups),
        configs=configs,
        n_existing=len(existing),
        group_req=group_req,
        group_count=group_count,
        group_priority=group_priority,
        compat=compat,
        cfg_alloc=cfg_alloc,
        cfg_price=cfg_price,
        cfg_pool=cfg_pool,
        pool_overhead=pool_overhead,
        existing_used=np.zeros((len(existing), R), np.float32),
        cfg_rsv=cfg_rsv,
        rsv_cap=np.asarray(rsv_cap_list, np.float32),
        group_cap=group_cap,
        conflict=conflict,
        existing_quota=existing_quota,
        loose_groups=loose_groups,
        pool_min_values=pool_min_values,
        cfg_alts=cfg_alts,
    )


def requirement_compat(
    groups: Sequence[PodGroup], configs: Sequence[ConfigInfo]
) -> np.ndarray:
    """[G, C] requirement-only compatibility — the funnel stage the
    explainability plane (karpenter_tpu/explain/funnel.py) replays
    from the SAME vocab-mask machinery the solver encode uses, so an
    explanation can never disagree with what the device saw. Taint
    tolerance is deliberately excluded: the funnel accounts it as its
    own stage."""
    return _compat_matrix(groups, configs)


def _full_compat(
    groups: Sequence[PodGroup], configs: Sequence[ConfigInfo]
) -> np.ndarray:
    """[G, C] compat = requirement compatibility AND taint tolerance.
    The single compat assembly both the uncached encode and the
    incremental cache's miss path go through, so a cached row can never
    drift from what a fresh encode would compute."""
    compat = _compat_matrix(groups, configs)
    for ci, cfg in enumerate(configs):
        if not cfg.taints:
            continue
        for gi, group in enumerate(groups):
            if tolerates(cfg.taints, list(group.tolerations)) is not None:
                compat[gi, ci] = False
    return compat


def _compat_matrix(groups: Sequence[PodGroup], configs: Sequence[ConfigInfo]) -> np.ndarray:
    """[G, C] requirement compatibility via per-key vocab masks.

    Semantics mirror Requirements.compatible(pod, AllowUndefinedWellKnown)
    evaluated config-side: every pod-constrained key must intersect the
    config's values; keys the config doesn't define pass when well-known
    or when the pod operator is NotIn/DoesNotExist.
    """
    G, C = len(groups), len(configs)
    compat = np.ones((G, C), dtype=bool)

    # Keys constrained by any pod group.
    pod_keys: set[str] = set()
    for group in groups:
        pod_keys.update(group.requirements.keys())

    for key in pod_keys:
        vocab: dict[str, int] = {}
        for cfg in configs:
            if cfg.requirements.has(key):
                for value in cfg.requirements.get(key).values:
                    vocab.setdefault(value, len(vocab))
        for group in groups:
            if group.requirements.has(key):
                for value in group.requirements.get(key).values:
                    vocab.setdefault(value, len(vocab))
        values = list(vocab)
        V = len(values)

        cfg_defined = np.zeros((C,), dtype=bool)
        cfg_mask = np.zeros((C, V + 1), dtype=bool)  # last col: "any other value"
        for ci, cfg in enumerate(configs):
            if not cfg.requirements.has(key):
                continue
            cfg_defined[ci] = True
            req = cfg.requirements.get(key)
            for vi, value in enumerate(values):
                cfg_mask[ci, vi] = req.has(value)
            # complement config reqs admit values outside the vocab too
            cfg_mask[ci, V] = req.complement

        for gi, group in enumerate(groups):
            if not group.requirements.has(key):
                continue
            req = group.requirements.get(key)
            pod_mask = np.zeros((V + 1,), dtype=bool)
            for vi, value in enumerate(values):
                pod_mask[vi] = req.has(value)
            pod_mask[V] = req.complement and (
                req.greater_than is None and req.less_than is None
            )
            op = req.operator()
            undefined_ok = key in WELL_KNOWN_LABELS or op in (NOT_IN, DOES_NOT_EXIST)
            key_compat = np.where(
                cfg_defined,
                (cfg_mask & pod_mask[None, :]).any(axis=1),
                undefined_ok,
            )
            compat[gi] &= key_compat
    return compat
