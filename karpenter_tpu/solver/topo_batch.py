"""Lower topology constraints to solver-native form.

The reference schedules topology-constrained pods one at a time,
re-asking the Topology tracker which domains remain legal before every
placement (scheduler.go:434-647 + topologygroup.go:226-311). That
serial loop is exactly what a batched device solver cannot run — so
this module *lowers* the constraints instead, into three forms the
packing kernel understands:

1. **Domain pins** — zonal / capacity-type / custom-key topology
   spread, pod affinity and pod anti-affinity over node-level domains
   become per-pod domain assignments computed host-side (water-filling
   to the minimum-count domain always satisfies any maxSkew >= 1;
   affinity restricts to occupied domains; anti-affinity hands out
   distinct empty domains). The assignment becomes an ordinary
   requirement pin (e.g. zone IN [z]) on a pseudo pod-group, which the
   dense compat matmul already enforces against config columns.

2. **Per-node group caps** (`group_cap[G]`, `existing_quota[E, G]`) —
   hostname-keyed topology spread means "at most maxSkew matching pods
   per node"; existing nodes get the cap net of pods already there.

3. **Group conflicts** (`conflict[G, G]`) — hostname-keyed pod
   anti-affinity (owners exclude selector-matched pods from their node
   and vice versa, topology.go:280-327) and host-port collisions
   (hostportusage.go) become pairwise node-sharing exclusions the
   kernel enforces with one masked reduction over its live assignment
   state.

Anything the lowering cannot express routes to the per-pod fallback
path — correctness never depends on the lowering being complete.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis.v1.labels import HOSTNAME_LABEL
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.scheduling.hostports import pod_host_ports
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.scheduling.topology import (
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
    TopologyGroup,
)
from karpenter_tpu.solver.encode import ExistingNodeInput, PodGroup, group_pods
from karpenter_tpu.utils import resources as resutil

INT_MAX = np.iinfo(np.int32).max


@dataclass
class TopoBatch:
    """A device-solvable lowering of topology-constrained pods."""

    groups: list[PodGroup]
    group_cap: Optional[np.ndarray]        # [G] int32
    conflict: Optional[np.ndarray]         # [G, G] bool
    existing_quota: Optional[np.ndarray]   # [E, G] int32
    # pod key -> {topology key: domain} chosen host-side; hostname
    # domains are decided by the packer and filled in at registration
    assignments: dict[str, dict[str, str]]
    fallback: list[Pod]
    errors: dict[str, str] = field(default_factory=dict)


@dataclass
class _Partition:
    """Pods sharing the same constraint-group sets."""

    owned: list[TopologyGroup]
    foreign_anti: list[TopologyGroup]
    ports: frozenset
    pods: list[Pod] = field(default_factory=list)


def prepare(
    pods: Sequence[Pod],
    topology: Topology,
    existing_inputs: Sequence[ExistingNodeInput],
    host_ports: dict[str, object],
) -> TopoBatch:
    """Partition constrained pods and lower each partition, or route it
    to `fallback` when the constraint mix is not expressible."""
    partitions: dict[tuple, _Partition] = {}
    policy_fallback: list[Pod] = []
    for pod in pods:
        owned = topology._groups_for_pod(pod)
        if any(
            g.node_affinity_policy != "Honor"
            or g.node_taints_policy != "Ignore"
            for g in owned
        ):
            # non-default node-inclusion policies change the skew
            # ACCOUNTING (not just placement), which the water-fill
            # lowering does not express — the per-pod path implements
            # them via TopologyGroup.allowed_domains
            policy_fallback.append(pod)
            continue
        owned_ids = frozenset(id(g) for g in owned)
        foreign = [
            g
            for g in topology._groups.values()
            if g.type == TYPE_ANTI_AFFINITY
            and id(g) not in owned_ids
            and g.matches(pod.metadata.namespace, pod.metadata.labels)
        ]
        ports = frozenset(pod_host_ports(pod))
        key = (owned_ids, frozenset(id(g) for g in foreign), ports)
        part = partitions.get(key)
        if part is None:
            part = _Partition(owned=owned, foreign_anti=foreign, ports=ports)
            partitions[key] = part
        part.pods.append(pod)

    batch = TopoBatch(
        groups=[], group_cap=None, conflict=None, existing_quota=None,
        assignments={}, fallback=list(policy_fallback),
    )
    # local overlays so one prepare() run sees its own earlier
    # assignments without mutating the Topology before the solve
    local_counts: dict[tuple[int, str], int] = {}
    local_owner: dict[tuple[int, str], int] = {}

    # encoded-group metadata accumulated across partitions
    caps: list[int] = []
    # topo-group id -> encoded group indices owning / matched-by it
    anti_owners: dict[int, list[int]] = {}
    anti_matched: dict[int, list[int]] = {}
    spread_members: dict[int, list[int]] = {}
    group_ports: list[frozenset] = []

    # partitions owning domain-level anti-affinity claim their domains
    # first so selector-matched partitions see them excluded
    ordered = sorted(
        partitions.values(),
        key=lambda p: (
            0 if any(
                g.type == TYPE_ANTI_AFFINITY and g.key != HOSTNAME_LABEL
                for g in p.owned
            ) else 1
        ),
    )
    for part in ordered:
        _lower_partition(
            part, topology, batch, caps, anti_owners, anti_matched,
            spread_members, group_ports, local_counts, local_owner,
        )

    G = len(batch.groups)
    if G == 0:
        return batch

    group_cap = np.asarray(caps, np.int32)
    conflict = np.zeros((G, G), bool)
    # hostname anti-affinity: owners x (matched + owners) exclude each
    # other from sharing a node, both directions
    for gid, owners in anti_owners.items():
        matched = set(anti_matched.get(gid, ())) | set(owners)
        for o in owners:
            for m in matched:
                conflict[o, m] = True
                conflict[m, o] = True
    # host-port collisions: groups whose port sets intersect
    for a in range(G):
        if not group_ports[a]:
            continue
        for b in range(a, G):
            if _ports_conflict(group_ports[a], group_ports[b]):
                conflict[a, b] = True
                conflict[b, a] = True
    # a self-conflicting group must cap at one pod per node (the
    # kernel's fresh-node bulk open relies on it)
    for g in range(G):
        if conflict[g, g]:
            group_cap[g] = 1

    batch.group_cap = group_cap
    batch.conflict = conflict if conflict.any() else None
    batch.existing_quota = _existing_quota(
        batch, existing_inputs, topology, host_ports, anti_owners, anti_matched,
        spread_members, group_ports,
    )
    # FFD order (matches group_pods sorting) with metadata permuted
    order = sorted(
        range(G),
        key=lambda g: (
            -(batch.groups[g].resources.get(resutil.CPU, 0.0)),
            -(batch.groups[g].resources.get(resutil.MEMORY, 0.0)),
            batch.groups[g].requirements.signature(),
        ),
    )
    perm = np.asarray(order)
    batch.groups = [batch.groups[g] for g in order]
    batch.group_cap = batch.group_cap[perm]
    if batch.conflict is not None:
        batch.conflict = batch.conflict[np.ix_(perm, perm)]
    if batch.existing_quota is not None:
        batch.existing_quota = batch.existing_quota[:, perm]
    return batch


def _ports_conflict(a: frozenset, b: frozenset) -> bool:
    """(hostIP, port) overlap semantics (hostportusage.go: wildcard
    0.0.0.0 conflicts with any IP on the same port)."""
    return any(p1.conflicts(p2) for p1 in a for p2 in b)


def _lower_partition(
    part: _Partition,
    topology: Topology,
    batch: TopoBatch,
    caps: list[int],
    anti_owners: dict[int, list[int]],
    anti_matched: dict[int, list[int]],
    spread_members: dict[int, list[int]],
    group_ports: list[frozenset],
    local_counts: dict[tuple[int, str], int],
    local_owner: dict[tuple[int, str], int],
) -> None:
    domain_spread: list[TopologyGroup] = []
    host_spread: list[TopologyGroup] = []
    domain_affinity: list[TopologyGroup] = []
    domain_anti: list[TopologyGroup] = []
    host_anti: list[TopologyGroup] = []
    for g in part.owned:
        if g.type == TYPE_SPREAD:
            (host_spread if g.key == HOSTNAME_LABEL else domain_spread).append(g)
        elif g.type == TYPE_AFFINITY:
            if g.key == HOSTNAME_LABEL:
                batch.fallback.extend(part.pods)  # co-locate on one node:
                return                            # inherently sequential
            domain_affinity.append(g)
        else:
            (host_anti if g.key == HOSTNAME_LABEL else domain_anti).append(g)
    # (foreign domain-level anti-affinity is handled below via
    # candidate-domain subtraction)
    # min_domains beyond the candidate set flips the reference into its
    # "global min = 0" fallback rule, which water-filling cannot honor
    for g in domain_spread:
        if g.min_domains is not None and g.min_domains > len(
            topology.domains.get(g.key, ())
        ):
            batch.fallback.extend(part.pods)
            return

    shape_groups = group_pods(part.pods)
    if host_spread and len(shape_groups) > 1:
        # per-node spread counts would span several encoded groups,
        # which the static cap cannot express
        batch.fallback.extend(part.pods)
        return

    # per-key candidate domains and count overlays
    keys = sorted(
        {g.key for g in domain_spread}
        | {g.key for g in domain_affinity}
        | {g.key for g in domain_anti}
        | {g.key for g in part.foreign_anti if g.key != HOSTNAME_LABEL}
    )
    candidates: dict[str, list[str]] = {}
    for key in keys:
        cand = set(topology.domains.get(key, ()))
        for g in part.foreign_anti:
            if g.key == key:
                cand -= {
                    d for d in cand
                    if g.owner_counts.get(d, 0) + local_owner.get((id(g), d), 0) > 0
                }
        for g in domain_anti:
            cand -= {
                d for d in cand
                if g.counts.get(d, 0) + local_counts.get((id(g), d), 0) > 0
                or g.owner_counts.get(d, 0) + local_owner.get((id(g), d), 0) > 0
            }
        for g in domain_affinity:
            occupied = {
                d for d in g.counts
                if g.counts.get(d, 0) + local_counts.get((id(g), d), 0) > 0
            }
            if occupied:
                cand &= occupied
            else:
                sample = part.pods[0]
                if not g.matches(sample.metadata.namespace, sample.metadata.labels):
                    # no occupied domain yet and the pods can't seed
                    # their own — the per-pod path runs AFTER this
                    # round's other placements register, so the target
                    # may appear; defer rather than error
                    batch.fallback.extend(part.pods)
                    return
                # self-seeding: the whole partition lands in one
                # deterministic domain
                if cand:
                    cand = {sorted(cand)[0]}
        if not cand:
            batch.fallback.extend(part.pods)
            return
        candidates[key] = sorted(cand)

    cap = min((g.max_skew for g in host_spread), default=INT_MAX)

    # per-pod domain choice, bucketed into pinned pseudo-groups
    for shape in shape_groups:
        # the shape's OWN requirements (node selector, required node
        # affinity) restrict which domains its pods may use — and per
        # NodeAffinityPolicy=Honor semantics the skew is computed over
        # exactly that eligible set (topologygroup.go:226-311), so the
        # water-fill below must never pin a pod to an unreachable
        # domain nor count one in the minimum
        shape_cand: dict[str, list[str]] = {}
        reachable = True
        for key in keys:
            gate = shape.requirements.get(key)
            allowed = [d for d in candidates[key] if gate.has(d)]
            if not allowed:
                reachable = False
                break
            shape_cand[key] = allowed
        if not reachable:
            batch.fallback.extend(shape.pods)
            continue
        buckets: dict[tuple, list[Pod]] = {}
        for pod in shape.pods:
            assignment: dict[str, str] = {}
            dead = False
            for key in keys:
                cand = shape_cand[key]
                anti = [g for g in domain_anti if g.key == key]
                if anti:
                    # distinct empty domain per pod
                    free = [
                        d for d in cand
                        if all(
                            g.counts.get(d, 0) + local_counts.get((id(g), d), 0) == 0
                            for g in anti
                        )
                    ]
                    if not free:
                        batch.errors[pod.key] = (
                            f"pod anti-affinity on {key}: no empty domain left"
                        )
                        dead = True
                        break
                    choice = free[0]
                else:
                    spreads = [g for g in domain_spread if g.key == key]
                    if spreads:
                        # water-fill: the minimum-count domain always
                        # keeps skew <= maxSkew
                        def load(d):
                            return sum(
                                g.counts.get(d, 0)
                                + local_counts.get((id(g), d), 0)
                                for g in spreads
                            )

                        choice = min(cand, key=lambda d: (load(d), d))
                    else:
                        choice = cand[0]
                assignment[key] = choice
                for g in part.owned:
                    if g.key == key:
                        local_counts[(id(g), choice)] = (
                            local_counts.get((id(g), choice), 0) + 1
                        )
                        if g.type == TYPE_ANTI_AFFINITY:
                            local_owner[(id(g), choice)] = (
                                local_owner.get((id(g), choice), 0) + 1
                            )
            if dead:
                continue
            batch.assignments[pod.key] = assignment
            buckets.setdefault(tuple(assignment[k] for k in keys), []).append(pod)

        for domains, bucket in buckets.items():
            reqs = Requirements(list(shape.requirements.values()))
            for key, domain in zip(keys, domains):
                reqs.add(Requirement(key, IN, [domain]))
            gi = len(batch.groups)
            batch.groups.append(
                PodGroup(
                    requirements=reqs,
                    tolerations=shape.tolerations,
                    resources=shape.resources,
                    pods=bucket,
                )
            )
            caps.append(1 if host_anti else cap)
            group_ports.append(part.ports)
            for g in host_anti:
                anti_owners.setdefault(id(g), []).append(gi)
            for g in host_spread:
                spread_members.setdefault(id(g), []).append(gi)
            for g in part.foreign_anti:
                if g.key == HOSTNAME_LABEL:
                    anti_matched.setdefault(id(g), []).append(gi)


def _existing_quota(
    batch: TopoBatch,
    existing_inputs: Sequence[ExistingNodeInput],
    topology: Topology,
    host_ports: dict[str, object],
    anti_owners: dict[int, list[int]],
    anti_matched: dict[int, list[int]],
    spread_members: dict[int, list[int]],
    group_ports: list[frozenset],
) -> Optional[np.ndarray]:
    """Per-existing-node remaining capacity for each encoded group:
    hostname spread counts, anti-affinity owners already on the node,
    and host ports in use."""
    E = len(existing_inputs)
    G = len(batch.groups)
    if E == 0:
        return None
    quota = np.full((E, G), INT_MAX, np.int32)
    by_id = {id(g): g for g in topology._groups.values()}

    # invert the topo-group -> encoded-group maps once: the scan below
    # is O(E x G); per-cell list-membership tests would make it
    # quadratic in the batch size
    owners_of: dict[int, list[TopologyGroup]] = {}
    for gid, members in anti_owners.items():
        for gi in members:
            owners_of.setdefault(gi, []).append(by_id[gid])
    matched_of: dict[int, list[TopologyGroup]] = {}
    for gid, members in anti_matched.items():
        for gi in members:
            matched_of.setdefault(gi, []).append(by_id[gid])

    for gi in range(G):
        cap = int(batch.group_cap[gi]) if batch.group_cap is not None else INT_MAX
        for ei, inp in enumerate(existing_inputs):
            remaining = cap
            name = inp.name
            # hostname spread/anti counts live in the topo groups keyed
            # by node name
            for g in owners_of.get(gi, ()):
                if g.counts.get(name, 0) > 0:
                    remaining = 0
            for g in matched_of.get(gi, ()):
                if g.owner_counts.get(name, 0) > 0:
                    remaining = 0
            if remaining and group_ports[gi]:
                usage = host_ports.get(name)
                if usage is not None and _ports_conflict(
                    group_ports[gi],
                    frozenset(
                        p for ports in usage._reserved.values() for p in ports
                    ),
                ):
                    remaining = 0
            quota[ei, gi] = remaining
    # hostname spread: cap net of matching pods already on each node,
    # applied to the encoded groups that OWN the constraint
    for gid, members in spread_members.items():
        g = by_id[gid]
        for gi in members:
            for ei, inp in enumerate(existing_inputs):
                have = g.counts.get(inp.name, 0)
                quota[ei, gi] = min(quota[ei, gi], max(0, g.max_skew - have))
    return quota
