"""AOT compile warm pool + persistent compilation cache.

Cold-start JIT warmup on the headline 50k scenario costs ~10.8s
(BENCH_r05 reserved_50k.warmup_s) — all XLA compilation of the packing
kernels' shape buckets. Two layers remove it from the serving path:

1. **Persistent compilation cache** (`enable_persistent_cache`): JAX's
   on-disk cache keyed by HLO, tagged with a machine fingerprint so an
   image reused across heterogeneous hosts never loads a stale
   artifact. Restarts then skip XLA entirely for every shape bucket
   ever compiled on the host. TPU-only by default: XLA:CPU AOT
   artifacts serialize pseudo-features (+prefer-no-gather/-scatter)
   the loader's host-feature detection never reports, so every load
   fails validation and recompiles mid-run (measured 2x tail inflation
   — see BENCH r04 postmortem).

2. **AOT warm pool** (`warm`/`start_background`): at operator startup a
   background thread compiles the split packing kernel for the
   configured shape buckets via `jit(...).lower(...).compile()` —
   shape-only tracing, no device execution, no input allocation. With
   the persistent cache enabled the compiled artifacts land on disk,
   so the first REAL solve of each bucket hits the cache instead of
   XLA.

Shape buckets come from KARPENTER_WARM_SHAPES ("G:C:E:N[:R[:P]]"
semicolon list — pod groups, config columns, existing nodes, FRESH
node axis, optional resource-axis width (default 4) and NodePool count
(default 1); padded to the same buckets `_run_pack` uses) or a default
family covering the small/medium/large unconstrained solves plus a
bound-heavy steady-state shape. Clusters with several NodePools or
extended resources must say so via R/P — the jit cache keys on exact
shapes, so a (2, 4) pool_overhead program never serves a 3-pool
cluster. Every compile is best-effort: a failure is counted and
logged, never raised into the operator.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Iterable, Optional, Sequence

log = logging.getLogger("karpenter.solver.warmpool")

# (groups, configs, existing/bound rows, fresh node axis) per bucket. The
# default family mirrors the shapes the bench matrix and a steady-state
# operator actually hit: small catalog probes, the mid-size batched
# solve, the 50k-pod headline, and a bound-row-heavy incremental tick.
# The last two entries extend the large-(G, F) diagonal the 50k cost
# solve actually walks (selector-fragmented demand lands ~100-200
# group signatures against a multi-thousand-node fresh axis; BENCH_r05
# measured 10.8s of reserved_50k warmup, all XLA on exactly these
# buckets).
DEFAULT_SHAPES: tuple[tuple[int, int, int, int], ...] = (
    (16, 256, 0, 64),
    (64, 1024, 0, 512),
    (128, 4096, 0, 2048),
    (16, 1024, 1024, 64),
    (128, 4096, 0, 4096),
    (200, 4096, 0, 3200),
)

MODES = ("ffd", "cost")

# (lanes, groups, configs, existing/bound rows, fresh axis) buckets for
# the batched consolidation probe kernel (consolidation_batch.LaneSolver
# dispatches pack_probe_lanes_flat): a small-cluster rotation chunk and
# a mid-size prefix ladder. Probes run the engine's ffd objective only.
DEFAULT_PROBE_SHAPES: tuple[tuple[int, int, int, int, int], ...] = (
    (8, 16, 256, 64, 32),
    (32, 32, 512, 512, 32),
)


def cache_dir_default() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(here, ".jax_cache")


def machine_tag() -> str:
    """Stable host fingerprint for the cache directory: artifacts must
    never be shared across machines with different CPU features or JAX
    builds (stable cpuinfo lines only — MHz etc. vary per boot)."""
    import jax

    parts = []
    try:
        with open("/etc/machine-id") as fh:
            parts.append(fh.read().strip())
    except OSError:
        parts.append("no-machine-id")
    try:
        with open("/proc/cpuinfo") as fh:
            parts.extend(sorted({
                line.strip() for line in fh
                if line.startswith(("flags", "model name"))
            }))
    except OSError:
        parts.append("no-cpuinfo")
    parts.append(jax.__version__)
    return hashlib.md5("\n".join(parts).encode()).hexdigest()[:8]


def enable_persistent_cache(
    cache_dir: Optional[str] = None, force: bool = False
) -> Optional[str]:
    """Point JAX's persistent compilation cache at a machine-tagged
    directory (KARPENTER_JAX_CACHE_DIR overrides the repo-local
    default). Returns the directory in use, or None when skipped
    (CPU backend, unless `force`)."""
    import jax

    if jax.default_backend() == "cpu" and not force:
        return None
    base = (
        cache_dir
        or os.environ.get("KARPENTER_JAX_CACHE_DIR")
        or cache_dir_default()
    )
    path = os.path.join(base, f"{jax.default_backend()}-{machine_tag()}")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path


def shapes_from_env(spec: Optional[str] = None) -> list[tuple]:
    """Parse KARPENTER_WARM_SHAPES ("G:C:E:N[:R[:P]];..."). R is the
    resource-axis width (4 = the base resources; clusters with
    extended resources must widen it or the warmed programs never
    match) and P the NodePool count (pool_overhead ships as [P+1, R],
    so a 2-pool cluster needs P=2). Malformed entries are dropped
    (warm-up is best-effort by definition)."""
    spec = spec if spec is not None else os.environ.get(
        "KARPENTER_WARM_SHAPES", ""
    )
    if not spec:
        return list(DEFAULT_SHAPES)
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            fields = [int(x) for x in part.split(":")]
            if len(fields) < 4 or len(fields) > 6:
                raise ValueError(part)
            g, c, e, n = fields[:4]
            r = fields[4] if len(fields) > 4 else 4
            p = fields[5] if len(fields) > 5 else 1
            if g > 0 and c > 0 and e >= 0 and n > 0 and r > 0 and p > 0:
                out.append((g, c, e, n, r, p))
        except ValueError:
            log.warning("ignoring malformed warm shape %r", part)
    return out or list(DEFAULT_SHAPES)


def probe_shapes_from_env(spec: Optional[str] = None) -> list[tuple]:
    """Parse KARPENTER_WARM_PROBE_SHAPES ("L:G:C:E:N[:R[:P]];...") —
    the lane-batched probe kernel's buckets. L is the probe lane count
    (padded by the same lane bucket the LaneSolver uses); the rest
    mirror shapes_from_env. Malformed entries are dropped."""
    spec = spec if spec is not None else os.environ.get(
        "KARPENTER_WARM_PROBE_SHAPES", ""
    )
    if not spec:
        return [s + (4, 1) for s in DEFAULT_PROBE_SHAPES]
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            fields = [int(x) for x in part.split(":")]
            if len(fields) < 5 or len(fields) > 7:
                raise ValueError(part)
            l, g, c, e, n = fields[:5]
            r = fields[5] if len(fields) > 5 else 4
            p = fields[6] if len(fields) > 6 else 1
            if l > 0 and g > 0 and c > 0 and e >= 0 and n > 0 and r > 0 and p > 0:
                out.append((l, g, c, e, n, r, p))
        except ValueError:
            log.warning("ignoring malformed probe warm shape %r", part)
    return out or [s + (4, 1) for s in DEFAULT_PROBE_SHAPES]


def _compile_probe_bucket(
    L: int, G: int, C: int, E: int, N: int, mode: str,
    R: int = 4, P: int = 1,
) -> None:
    """AOT-compile the probe kernel(s) a real probe batch of this
    bucket would dispatch. Padding must mirror
    consolidation_batch.LaneSolver exactly (same _pad_axis /
    _lane_bucket / _bucket / level-coupling) or the warmed program
    never matches.

    Backend-aware like probe_batch_width(): width > 1 (accelerators)
    dispatches the vmapped pack_probe_lanes_flat, width == 1 (CPU)
    dispatches solo pack_split_flat programs on the level-coupled
    (G=16<<k, F=64<<k) diagonal — warming the vmapped kernel on CPU
    would pay its expensive XLA:CPU compile for programs no probe
    ever runs while leaving the solo shapes cold."""
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from karpenter_tpu.solver import faults, telemetry
    from karpenter_tpu.solver.pack import (
        _bucket,
        _lane_bucket,
        _pad_axis,
        pack_probe_lanes_flat,
        pack_split_flat,
        probe_batch_width,
        wavefront_plan,
    )

    faults.fire("warm")

    Cp = -(-_pad_axis(C) // 32) * 32
    Ep = _pad_axis(E) if E else 0
    if probe_batch_width() == 1:
        k_max = 0
        while (16 << k_max) < max(G, 1):
            k_max += 1
        for k in range(k_max + 1):
            Gp = 16 << k
            F = 64 << k
            args = (
                S((Gp, Cp), jnp.bool_),      # compat (compacted)
                S((Gp, R), jnp.float32),     # group_req
                S((Gp,), jnp.int32),         # group_count
                S((Cp, R), jnp.float32),     # cfg_alloc
                S((Cp,), jnp.int32),         # cfg_pool
                S((P + 1, R), jnp.float32),  # pool_overhead
                S((Gp, Ep), jnp.bool_),      # bound_compat
                S((Ep, R), jnp.float32),     # bound_alloc
                S((Ep, R), jnp.float32),     # bound_used0
                S((Ep,), jnp.int32),         # bound_slot
                S((Ep,), jnp.bool_),         # bound_live
                S((Cp,), jnp.float32),       # cfg_price
            )
            # solo probes dispatch the wavefront variant when the
            # lane's compacted group count clears the routing floor —
            # warm both, like _compile_bucket. Judged on the REAL
            # count this level serves (min of the spec's group count
            # and the level's padded axis), never the padding: a spec
            # below WAVEFRONT_MIN_GROUPS pads to 16 but every real
            # dispatch routes sequential, so warming its wavefront
            # variant would be pure wasted startup time.
            wf = wavefront_plan(min(G, Gp))
            if wf > 1:
                telemetry.record_compiled(
                    "probe_solo",
                    (Gp, Cp, Ep, F, mode, telemetry.variant_tag(wf)),
                    pack_split_flat.lower(
                        *args, max_free=F, mode=mode, wavefront=wf
                    ).compile(),
                )
            telemetry.record_compiled(
                "probe_solo",
                (Gp, Cp, Ep, F, mode, telemetry.variant_tag(0)),
                pack_split_flat.lower(
                    *args, max_free=F, mode=mode
                ).compile(),
            )
        return
    Gp = _pad_axis(G)
    Lp = _lane_bucket(L)
    F = _bucket(max(N, 1))
    args = (
        S((Gp, Cp), jnp.bool_),      # compat
        S((Gp, R), jnp.float32),     # group_req
        S((Lp, Gp), jnp.int32),      # lane_counts
        S((Cp, R), jnp.float32),     # cfg_alloc
        S((Cp,), jnp.int32),         # cfg_pool
        S((P + 1, R), jnp.float32),  # pool_overhead
        S((Gp, Ep), jnp.bool_),      # bound_compat
        S((Ep, R), jnp.float32),     # bound_alloc
        S((Ep, R), jnp.float32),     # bound_used0
        S((Ep,), jnp.int32),         # bound_slot
        S((Lp, Ep), jnp.bool_),      # lane_live
        S((Cp,), jnp.float32),       # cfg_price
    )
    # like _compile_bucket: a real batch dispatch judges the width on
    # its own union group count, so either variant can be asked of
    # this bucket — warm both (wavefront only when the spec's G clears
    # the routing floor)
    wf = wavefront_plan(G)
    if wf > 1:
        telemetry.record_compiled(
            "probe_lanes",
            (Lp, Gp, Cp, Ep, F, mode, telemetry.variant_tag(wf)),
            pack_probe_lanes_flat.lower(
                *args, max_free=F, mode=mode, wavefront=wf
            ).compile(),
        )
    telemetry.record_compiled(
        "probe_lanes",
        (Lp, Gp, Cp, Ep, F, mode, telemetry.variant_tag(0)),
        pack_probe_lanes_flat.lower(
            *args, max_free=F, mode=mode
        ).compile(),
    )


def warm_shards() -> int:
    """KARPENTER_WARM_SHARDS: mesh width the warm pool ALSO compiles
    each bucket for (the multi-host solver service's pjit shapes —
    ISSUE 11). "auto" spans every visible device; 0/unset skips the
    sharded variants (no startup cost for single-device fleets); a
    count above the visible devices is clamped to them (same graceful
    degradation as the solve path's default_shards fallback).

    Deliberately NOT shared with service.server.resolve_service_shards
    despite the similar spelling: an explicit service width is
    authoritative and lets _mesh raise on an impossible ask, while
    warm-up is best-effort by definition and clamps instead."""
    raw = os.environ.get("KARPENTER_WARM_SHARDS", "").strip().lower()
    if not raw or raw in ("0", "off", "false", "no"):
        return 0
    from karpenter_tpu.solver.pack import visible_devices

    visible = visible_devices(0)
    if visible == 0:
        return 0
    if raw == "auto":
        return visible if visible > 1 else 0
    try:
        want = int(raw)
    except ValueError:
        log.warning("ignoring malformed KARPENTER_WARM_SHARDS=%r", raw)
        return 0
    want = min(want, visible)
    return want if want > 1 else 0


def bucket_args(
    Gp: int, Cp: int, Ep: int, R: int, P: int,
    shards: int = 0, rsv_k: Optional[int] = None,
    group_cap: bool = False, conflict: bool = False,
    quota: bool = False,
) -> tuple[tuple, dict]:
    """ShapeDtypeStruct (args, kwargs) for one PADDED pack_split_flat
    bucket — the single source of the kernel's input signature, shared
    by the warm pool's AOT compiles and the telemetry capture worker
    (solver/telemetry.py), so the two can never drift. With
    `shards > 1` the structs carry the sharded solve's committed input
    shardings (config axis split over the mesh, everything else
    replicated). `rsv_k` is the rsv_cap row count (None: no
    reservation inputs at all; sharded buckets always pass them —
    pack._run_pack: an in-jit constant would fold the reservation
    reductions into regions the SPMD partitioner rejects)."""
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as _S

    if shards > 1:
        from jax.sharding import NamedSharding, PartitionSpec as _P

        from karpenter_tpu.solver.pack import _mesh

        mesh = _mesh(shards)
        _spec = {
            "cfg": NamedSharding(mesh, _P("cfg")),
            "nc": NamedSharding(mesh, _P(None, "cfg")),
            "cr": NamedSharding(mesh, _P("cfg", None)),
            "rep": NamedSharding(mesh, _P()),
        }

        def S(shape, dtype, part="rep"):
            return _S(shape, dtype, sharding=_spec[part])
    else:
        def S(shape, dtype, part=None):
            return _S(shape, dtype)
    args = (
        S((Gp, Cp), jnp.bool_, "nc"),       # compat
        S((Gp, R), jnp.float32),            # group_req
        S((Gp,), jnp.int32),                # group_count
        S((Cp, R), jnp.float32, "cr"),      # cfg_alloc
        S((Cp,), jnp.int32, "cfg"),         # cfg_pool
        S((P + 1, R), jnp.float32),         # pool_overhead
        S((Gp, Ep), jnp.bool_),             # bound_compat
        S((Ep, R), jnp.float32),            # bound_alloc
        S((Ep, R), jnp.float32),            # bound_used0
        S((Ep,), jnp.int32),                # bound_slot
        S((Ep,), jnp.bool_),                # bound_live
        S((Cp,), jnp.float32, "cfg"),       # cfg_price
    )
    kw = {}
    if shards > 1 and rsv_k is None:
        rsv_k = 0
    if rsv_k is not None:
        kw["cfg_rsv"] = S((Cp,), jnp.int32, "cfg")
        kw["rsv_cap"] = S((rsv_k,), jnp.float32)
    if group_cap:
        kw["group_cap"] = S((Gp,), jnp.int32)
    if conflict:
        kw["conflict"] = S((Gp, Gp), jnp.bool_)
    if quota and Ep:
        kw["bound_quota"] = S((Ep, Gp), jnp.int16)
    return args, kw


def _compile_bucket(
    G: int, C: int, E: int, N: int, mode: str,
    R: int = 4, P: int = 1, topo: bool = False, shards: int = 0,
) -> None:
    """AOT-compile pack_split_flat for one padded shape bucket using
    ShapeDtypeStructs (no real arrays, no execution). The padding must
    mirror _run_pack exactly or the warmed program never matches a real
    solve (the arg construction itself lives in `bucket_args`)."""
    import math

    from karpenter_tpu.solver import faults, telemetry
    from karpenter_tpu.solver.pack import (
        _bucket,
        _pad_axis,
        pack_split_flat,
    )

    faults.fire("warm")
    Gp = _pad_axis(G)
    step = math.lcm(32, shards) if shards > 1 else 32
    Cp = -(-_pad_axis(C) // step) * step
    Ep = _pad_axis(E) if E else 0
    # N names the FRESH node axis: solve_packing_async buckets the
    # fresh axis independently of the (already padded) bound block, so
    # only _bucket values ever reach the kernel as max_free — deriving
    # F any other way would compile programs no real solve can reuse
    F = _bucket(max(N, 1))
    rsv_k = 0 if shards > 1 else None
    quota = topo and Ep > 0
    args, kw = bucket_args(
        Gp, Cp, Ep, R, P, shards=shards, rsv_k=rsv_k,
        group_cap=topo, conflict=topo, quota=quota,
    )
    # a real solve of this bucket dispatches EITHER the wavefront or
    # the sequential jaxpr depending on its REAL (unpadded) group
    # count (pack.wavefront_plan); the bucket spec only knows G, so
    # warm both variants — solves below WAVEFRONT_MIN_GROUPS padded
    # into this bucket still hit the sequential program
    from karpenter_tpu.solver.pack import wavefront_plan

    wf = wavefront_plan(G, shards)
    if wf > 1:
        compiled = pack_split_flat.lower(
            *args, max_free=F, mode=mode, wavefront=wf, **kw
        ).compile()
        telemetry.record_compiled(
            "pack",
            (Gp, Cp, Ep, F, mode,
             telemetry.variant_tag(wf, rsv_k, topo, topo, quota)),
            compiled, shards=shards,
        )
    compiled = pack_split_flat.lower(
        *args, max_free=F, mode=mode, **kw
    ).compile()
    # the AOT compile already holds the Compiled object, so XLA's own
    # memory/cost analyses are recorded for free (solver/telemetry.py)
    telemetry.record_compiled(
        "pack",
        (Gp, Cp, Ep, F, mode,
         telemetry.variant_tag(0, rsv_k, topo, topo, quota)),
        compiled, shards=shards,
    )
    # padded-signature registry: lets the flight recorder attribute a
    # solve's compile span to a warm-pool hit (pack.py annotates
    # warm_hit when its padded shape matches a pre-compiled bucket)
    compiled_buckets.add((Gp, Cp, Ep, F, mode, shards))


# padded (Gp, Cp, Ep, F, mode, shards) signatures AOT-compiled by this
# process (see _compile_bucket); read via `warmed` from pack's
# dispatch path
compiled_buckets: set[tuple] = set()


def warmed(Gp: int, Cp: int, Ep: int, F: int, mode: str,
           shards: int = 0) -> bool:
    """True when a warm-pool bucket compile covered this exact padded
    shape — the deterministic warm-hit signal (the compile span's
    duration shows it; this attributes it). Sharded solves match only
    sharded-warmed buckets: the GSPMD program is a different compile."""
    return (Gp, Cp, Ep, F, mode, shards) in compiled_buckets


def rewarm_canary() -> bool:
    """One cheap canary compile of the smallest shape bucket, proving
    XLA and the device actually serve again. The resilience layer's
    device breaker uses this (KARPENTER_REWARM_ON_CLOSE=1) to gate the
    half-open -> closed transition: a device that answers one
    cached-shape probe but cannot compile would otherwise flap the
    breaker. Runs the `warm` fault site, so chaos specs keep the gate
    failing while the injected fault is live."""
    from karpenter_tpu.metrics.store import SOLVER_WARM_COMPILES

    try:
        _compile_bucket(*DEFAULT_SHAPES[0], "ffd")
        SOLVER_WARM_COMPILES.inc({"outcome": "ok"})
        return True
    except Exception as err:
        SOLVER_WARM_COMPILES.inc({"outcome": "error"})
        log.warning("re-warm canary compile failed: %s", err)
        return False


def warm(
    shapes: Optional[Iterable[tuple[int, int, int, int]]] = None,
    modes: Sequence[str] = MODES,
    topo: bool = True,
    stop: Optional[threading.Event] = None,
    probe_shapes: Optional[Iterable[tuple]] = None,
) -> dict[str, int]:
    """Compile every (shape bucket, mode[, topo variant]) combination,
    plus the batched consolidation probe buckets (ffd only — the
    engine's probes always pack ffd); returns {"ok": n, "error": n,
    "skipped": n}. Never raises. `stop` is polled between compiles
    (one bucket compile is the atomic unit); buckets run
    smallest-first so an early stop leaves the cheapest work in
    flight."""
    from karpenter_tpu.metrics.store import SOLVER_WARM_COMPILES

    shapes = list(shapes) if shapes is not None else shapes_from_env()
    shapes.sort(key=lambda s: s[0] * s[1] + s[2] + s[3])
    counts = {"ok": 0, "error": 0, "skipped": 0}
    if os.environ.get("KARPENTER_BATCH_PROBES", "1").lower() in (
        "0", "false", "off"
    ):
        # batching disabled: no probe kernel will ever dispatch
        probe_shapes = []
    probes = (
        list(probe_shapes) if probe_shapes is not None
        else probe_shapes_from_env()
    )
    probes.sort(key=lambda s: s[0] * (s[1] * s[2] + s[3] + s[4]))
    for shape in probes:
        L, G, C, E, N = shape[:5]
        R = shape[5] if len(shape) > 5 else 4
        P = shape[6] if len(shape) > 6 else 1
        if stop is not None and stop.is_set():
            counts["skipped"] += 1
            continue
        try:
            _compile_probe_bucket(L, G, C, E, N, "ffd", R=R, P=P)
            counts["ok"] += 1
            SOLVER_WARM_COMPILES.inc({"outcome": "ok"})
        except Exception as err:
            counts["error"] += 1
            SOLVER_WARM_COMPILES.inc({"outcome": "error"})
            log.warning(
                "probe warm compile (L=%d,G=%d,C=%d,E=%d,N=%d,R=%d,P=%d) "
                "failed: %s", L, G, C, E, N, R, P, err,
            )
    # device-LP ascent buckets (ISSUE 12): one tiny program per (G, C)
    # shape bucket so the first guided cost solve of a warmed bucket
    # skips the XLA trace; gated on the guidance knob the solve path
    # itself honors
    from karpenter_tpu.solver import lp_device

    if lp_device.enabled():
        lp_shapes = sorted(
            {(G, C, (s[4] if len(s) > 4 else 4)) for s in shapes
             for G, C in [(s[0], s[1])]}
        )
        for lp_shape in lp_shapes:
            if stop is not None and stop.is_set():
                counts["skipped"] += 1
                continue
            try:
                done = lp_device.warm([lp_shape])
                counts["ok"] += done
                if done:
                    SOLVER_WARM_COMPILES.inc(
                        {"outcome": "ok"}, value=float(done)
                    )
            except Exception as err:  # pragma: no cover - defensive
                counts["error"] += 1
                SOLVER_WARM_COMPILES.inc({"outcome": "error"})
                log.warning("lp warm compile %s failed: %s", lp_shape, err)
    # KARPENTER_WARM_SHARDS adds the GSPMD-partitioned variant of each
    # bucket (the multi-host solver service's pjit shapes): same
    # matrix, compiled with the config axis split over the mesh
    ws = warm_shards()
    shard_variants = (0, ws) if ws > 1 else (0,)
    for shape in shapes:
        G, C, E, N = shape[:4]
        R = shape[4] if len(shape) > 4 else 4
        P = shape[5] if len(shape) > 5 else 1
        for mode in modes:
            for with_topo in ((False, True) if topo else (False,)):
                for shards in shard_variants:
                    if stop is not None and stop.is_set():
                        counts["skipped"] += 1
                        continue
                    try:
                        _compile_bucket(G, C, E, N, mode, R=R, P=P,
                                        topo=with_topo, shards=shards)
                        counts["ok"] += 1
                        SOLVER_WARM_COMPILES.inc({"outcome": "ok"})
                    except Exception as err:
                        counts["error"] += 1
                        SOLVER_WARM_COMPILES.inc({"outcome": "error"})
                        log.warning(
                            "warm compile (G=%d,C=%d,E=%d,N=%d,R=%d,P=%d,"
                            "mode=%s,topo=%s,shards=%d) failed: %s",
                            G, C, E, N, R, P, mode, with_topo, shards, err,
                        )
    return counts


def start_background(
    shapes: Optional[Iterable[tuple[int, int, int, int]]] = None,
    enable_cache: bool = True,
) -> threading.Thread:
    """Operator-startup entry: enable the persistent cache, then AOT
    warm the shape buckets on a background thread so the first tick's
    solve never waits on XLA. Returns the (started) thread; its `stop`
    attribute is a threading.Event that abandons the remaining
    buckets.

    The thread is deliberately NON-daemon: a daemon thread killed
    mid-XLA-compile at interpreter exit takes the process down with a
    C++ `terminate` (observed: exit code 134 on a clean shutdown). The
    stop event is registered via threading's internal shutdown hooks —
    which run BEFORE non-daemon threads are joined, unlike atexit — so
    process exit waits for at most the one in-flight bucket compile."""
    stop = threading.Event()

    def _run() -> None:
        try:
            if enable_cache:
                path = enable_persistent_cache()
                if path:
                    log.info("persistent compile cache at %s", path)
            counts = warm(shapes, stop=stop)
            log.info(
                "warm pool compiled %d shape buckets "
                "(%d failed, %d skipped)",
                counts["ok"], counts["error"], counts["skipped"],
            )
            if not stop.is_set():
                # materialize any telemetry captures queued during the
                # warm-up (LP ascent buckets) — this thread is the one
                # place background XLA work is sanctioned to burn CPU
                from karpenter_tpu.solver import telemetry

                telemetry.drain(timeout=30.0)
        except Exception:  # never take the operator down
            log.exception("solver warm pool crashed")

    thread = threading.Thread(
        target=_run, name="solver-warm-pool", daemon=False
    )
    thread.stop = stop
    register = getattr(threading, "_register_atexit", None)
    if register is not None:  # CPython 3.9+ (concurrent.futures uses it)
        register(stop.set)
    else:  # pragma: no cover - very old interpreters: bounded daemon risk
        import atexit

        atexit.register(stop.set)
    thread.start()
    return thread
