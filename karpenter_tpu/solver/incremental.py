"""Incremental warm-start solve pipeline: dirty-set re-encoding and
residual repack.

BENCH_r05 showed the steady-state operator paying for a FULL encode +
pack of the entire fleet every tick even when only a handful of pods
changed. CvxCluster (PAPERS.md) gets its orders-of-magnitude wins by
re-solving only the perturbed subproblem against a cached
decomposition, and "Priority Matters" shows constraint-based packing
amortizes when the encoding persists across rounds. The same structure
applies to the tick loop here, in two layers:

1. **EncodedCache** (dirty-set re-encoding): the launchable half of
   the encoded problem — the ConfigInfo columns and the [G, C] compat
   rows — is a pure function of (catalog, group signature). Cache it
   across solves; a tick whose pod shapes mostly repeat recomputes
   compat only for NEW signatures (k dirty rows instead of the full
   G x C rebuild), and config construction is skipped entirely while
   the catalog fingerprint holds. Pseudo-config columns for existing
   nodes are always computed fresh (their labels/usage change tick to
   tick, and they are O(dirty-groups x nodes) anyway).

2. **IncrementalPipeline** (warm-start residual repack): the previous
   solution IS the warm start. Each tick diffs the pod set against the
   retained assignment, frees capacity for deleted pods, and routes
   only displaced/new pods through the split packing kernel against
   the residual node capacities (`pack_split`'s bound rows — existing
   nodes first, the reference's scan order). The kernel's fori_loop
   trip count drops from G (all groups) to G_dirty, and the dense
   fresh axis shrinks to the spill. Correctness backstops: a full
   re-solve when churn exceeds KARPENTER_INCR_CHURN_MAX, and a
   periodic full re-solve every KARPENTER_INCR_FULL_EVERY ticks that
   the incremental fleet must match within KARPENTER_INCR_DRIFT_EPS
   on price or be replaced by.

The pipeline is intentionally scoped to the batched fast path
(selector/resource demand, no topology constraints — the same pods the
scheduler's fast path batches); constrained pods keep going through
the full Scheduler machinery. Encode calls sharing one cache must be
serialized (the operator tick loop, the bench loop, and the pipeline
all are); the cache's own tables are lock-guarded.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis.v1.labels import HOSTNAME_LABEL
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.metrics.store import (
    SOLVER_ENCODE_CACHE,
    SOLVER_INCREMENTAL_TICKS,
)
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.solver.encode import (
    ConfigInfo,
    ExistingNodeInput,
    PodGroup,
    _config_requirements,
    _full_compat,
    launch_configs,
    pseudo_configs,
)
from karpenter_tpu.utils import resources as resutil


def catalog_fingerprint(pools_with_types) -> tuple:
    """Cheap identity of the launchable catalog: everything
    build_configs reads that can change which config columns exist or
    what they require. Instance types are fingerprinted by object
    identity + name (providers rebuild the objects when a type
    changes; the cache pins the referenced catalog so ids cannot be
    recycled while cached); pools by their spec hash, which covers
    template requirements, labels and taints."""
    # zone/capacity-type/reservation-id are construction-time constants
    # of an Offering (and reading them walks requirement lookups), so
    # object identity covers them; price/availability ARE flipped in
    # place by providers (ICE marking, overlays) and read as plain
    # attributes into FLAT tuples (this runs twice per steady tick —
    # nested per-offering tuples measurably showed up in profiles).
    # The spot interruption penalty is part of the fingerprint: cached
    # cfg_price arrays bake it in, so a flipped penalty must bust them.
    from karpenter_tpu.cloudprovider.types import interruption_penalty

    return (interruption_penalty(),) + tuple(
        (
            pool.metadata.name,
            pool.hash(),
            id(pool),
            tuple(id(it) for it in types),
            tuple(
                x for it in types for o in it.offerings
                for x in (o.price, o.available, o.reservation_capacity)
            ),
        )
        for pool, types in pools_with_types
    )


class EncodedCache:
    """Compat-row + config-column cache for encode() (dirty-set
    re-encoding). Rows are keyed by (group requirements signature,
    tolerations) under one catalog fingerprint; a catalog change busts
    everything. Bounded LRU-ish (insertion-order eviction)."""

    def __init__(self, max_rows: int = 4096):
        self.max_rows = max_rows
        self._lock = threading.Lock()
        self._fp: Optional[tuple] = None
        self._pin = None                  # strong ref: keeps catalog ids valid
        self._launch: Optional[list[ConfigInfo]] = None
        self._rows: dict[tuple, np.ndarray] = {}
        # launchable cfg_alloc/price/pool arrays + reservation ids,
        # keyed by the resource-axis tuple (extended resources extend
        # the axis per demand mix)
        self._arrays: dict[tuple, tuple] = {}
        self._pin_stats: Optional[tuple[dict, dict]] = None

    # -- invalidation ---------------------------------------------------------

    def invalidate(self) -> None:
        """Explicit bust (relist / resync boundary / NodePool event)."""
        with self._lock:
            if self._rows or self._launch is not None:
                SOLVER_ENCODE_CACHE.inc({"outcome": "bust"})
            self._fp = None
            self._pin = None
            self._launch = None
            self._rows.clear()
            self._arrays.clear()
            self._pin_stats = None

    def _sync_catalog(self, pools_with_types) -> None:
        """Under lock: bust on catalog fingerprint change."""
        fp = catalog_fingerprint(pools_with_types)
        if fp != self._fp:
            if self._fp is not None:
                SOLVER_ENCODE_CACHE.inc({"outcome": "bust"})
            self._fp = fp
            self._pin = [(pool, tuple(types)) for pool, types in pools_with_types]
            self._launch = None
            self._rows.clear()
            self._arrays.clear()
            self._pin_stats = None

    # -- encode() hooks -------------------------------------------------------

    def configs(self, pools_with_types, existing=()) -> list[ConfigInfo]:
        """build_configs with the launchable prefix cached per catalog.
        The returned list is fresh; the launchable ConfigInfo objects
        are shared across calls and treated as IMMUTABLE by encode
        (per-encode dedupe membership lives on Encoded.cfg_alts)."""
        with self._lock:
            self._sync_catalog(pools_with_types)
            if self._launch is None:
                self._launch = launch_configs(pools_with_types)
            launch = self._launch
        return list(launch) + pseudo_configs(existing)

    def launch_arrays(
        self,
        resource_keys: Sequence[str],
        configs: Sequence[ConfigInfo],
        n_launch: int,
        pool_order: dict[str, int],
    ):
        """(cfg_alloc, cfg_price, cfg_pool, [(ci, reservation_id)])
        for the launchable prefix — pure functions of the catalog and
        the resource axis, cached per axis under the current catalog
        fingerprint (encode copies the arrays into its padded output,
        so the cached originals are never mutated). Reservation
        BUDGETS are not cached: remaining capacity depends on
        per-round usage and is recomputed by encode from the returned
        (ci, rid) list."""
        keys = tuple(resource_keys)
        with self._lock:
            hit = self._arrays.get(keys)
            if hit is not None:
                return hit
        R = len(keys)
        alloc = np.zeros((n_launch, R), np.float32)
        price = np.zeros((n_launch,), np.float32)
        pool = np.full((n_launch,), -1, np.int32)
        rids: list[tuple[int, str]] = []
        statics: list[tuple] = []
        from karpenter_tpu.cloudprovider.types import effective_price

        for ci in range(n_launch):
            cfg = configs[ci]
            allocatable = cfg.instance_type.allocatable
            for ri, key in enumerate(keys):
                alloc[ci, ri] = allocatable.get(key, 0.0)
            # spot offerings enter the packing objective at their
            # interruption-penalized price (the penalty is part of the
            # catalog fingerprint, so a changed knob busts this cache)
            price[ci] = effective_price(cfg.offering)
            pool[ci] = pool_order[cfg.pool.metadata.name]
            rid = cfg.offering.reservation_id
            if rid:
                rids.append((ci, rid))
            # the catalog-static 3/4 of encode's dedupe key (the
            # fourth, the compat column, is per-solve)
            statics.append((int(pool[ci]), rid or "", alloc[ci].tobytes()))
        out = (alloc, price, pool, rids, statics)
        with self._lock:
            if len(self._arrays) > 8:  # distinct resource axes are few
                self._arrays.clear()
            self._arrays[keys] = out
        return out

    def pin_stats(self, configs: Sequence[ConfigInfo], n_launch: int):
        """(pin_ok, n_have) over the LAUNCHABLE configs for encode's
        always-pinned-key analysis — catalog-static; encode merges the
        per-call existing configs into copies."""
        with self._lock:
            if self._pin_stats is not None:
                return self._pin_stats
        pin_ok: dict[str, bool] = {}
        n_have: dict[str, int] = {}
        for ci in range(n_launch):
            for req in configs[ci].requirements:
                single = req.operator() == IN and len(req.values) == 1
                n_have[req.key] = n_have.get(req.key, 0) + 1
                pin_ok[req.key] = pin_ok.get(req.key, True) and single
        with self._lock:
            self._pin_stats = (pin_ok, n_have)
        return self._pin_stats

    def compat(
        self,
        groups: Sequence[PodGroup],
        configs: Sequence[ConfigInfo],
        n_launch: int,
        pools_with_types=None,
    ) -> np.ndarray:
        """[G, C] compat with the launchable columns served from cache
        per group signature; only signatures not seen under the current
        catalog (the dirty rows) pay the requirement/taint evaluation.
        Per-pair compat is independent of which other configs share the
        call, so splitting launchable/pseudo columns is exact."""
        G, C = len(groups), len(configs)
        if pools_with_types is not None:
            with self._lock:
                self._sync_catalog(pools_with_types)
        rows = np.empty((G, n_launch), dtype=bool)
        missing: list[tuple[int, tuple]] = []
        with self._lock:
            for gi, group in enumerate(groups):
                key = (group.requirements.signature(), group.tolerations)
                hit = self._rows.get(key)
                if hit is None or hit.shape[0] != n_launch:
                    missing.append((gi, key))
                else:
                    rows[gi] = hit
        hits = G - len(missing)
        if hits:
            SOLVER_ENCODE_CACHE.inc({"outcome": "hit"}, value=float(hits))
        if missing:
            SOLVER_ENCODE_CACHE.inc(
                {"outcome": "miss"}, value=float(len(missing))
            )
            fresh = _full_compat(
                [groups[gi] for gi, _ in missing], configs[:n_launch]
            )
            with self._lock:
                for row_i, (gi, key) in enumerate(missing):
                    rows[gi] = fresh[row_i]
                    self._rows[key] = fresh[row_i].copy()
                while len(self._rows) > self.max_rows:
                    self._rows.pop(next(iter(self._rows)))
        if n_launch < C:
            pseudo = _full_compat(groups, configs[n_launch:])
            return np.ascontiguousarray(
                np.concatenate([rows, pseudo], axis=1)
            )
        return rows


# -- residual repack ----------------------------------------------------------


@dataclass
class ResidualNode:
    """One node retained from the previous tick's solution, with its
    live load — the warm start the next tick packs against."""

    name: str
    pool: NodePool
    instance_type: InstanceType
    offering: Offering
    price: float
    requirements: Requirements
    taints: tuple
    used: dict[str, float]
    pods: dict[str, Pod] = field(default_factory=dict)

    def available(self) -> dict[str, float]:
        return resutil.positive(
            resutil.subtract(self.instance_type.allocatable, self.used)
        )


@dataclass
class TickResult:
    mode: str                  # "incremental" | "full"
    reason: str                # "steady" | "cold" | "churn" | "catalog"
                               # | "drift" | "checked" | "dual_floor"
                               # | "invalidate"
    scheduled: int
    unschedulable: int
    fleet_price: float
    nodes: int
    churn: float = 0.0
    placed: int = 0            # pods routed through the repack solve
    drift: Optional[float] = None  # backstop ticks: inc/full price - 1


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).lower() not in (
        "0", "false", "off"
    )


@dataclass
class _DualFloor:
    """The cached dual certificate the residual repack spends (ISSUE
    15): built from the device LP of the last FULL solve's encode and
    valid for as long as the catalog fingerprint holds (prices,
    offerings and the reprice/penalty knobs are all inside the
    fingerprint, so a reprice busts it through the normal full-tick
    path).

    - `lam_by_sig`: Farley-scaled demand duals keyed by group
      signature (requirements signature, tolerations, resource
      vector) — demand-INDEPENDENT dual feasibility means they bound
      any later tick's demand: unknown signatures price at 0
      (conservative), so `bound_for` is a valid weak-duality lower
      bound on ANY fresh-fleet covering of the current pod set.
    - `rank_launch`: the dual-adjusted reduced-cost price ordering
      over the launchable config prefix (lp_device.rank_prices) — the
      repack feeds it to the kernel as its type-preference input via
      solve_encoded(price_hint=...); decode keeps true prices.
    """

    lam_by_sig: dict
    cap_term: float
    rank_launch: np.ndarray
    n_launch: int

    def bound_for(self, groups: Sequence[PodGroup]) -> float:
        total = 0.0
        for g in groups:
            sig = (
                g.requirements.signature(),
                g.tolerations,
                tuple(sorted(g.resources.items())),
            )
            lam = self.lam_by_sig.get(sig)
            if lam:
                total += lam * len(g.pods)
        return max(0.0, total - self.cap_term)


def build_dual_floor(enc) -> Optional[_DualFloor]:
    """Construct the dual certificate from one solve's encode (shared
    by the repack pipeline and the live tick's micro path, ISSUE 17).
    Returns None when the device LP is unavailable or the derivation
    fails — callers run exactly the unguided path."""
    from karpenter_tpu.solver import lp_device

    dlp = lp_device.maybe_solve(enc)
    if dlp is None:
        return None
    try:
        launch = enc.cfg_pool >= 0
        n_launch = int(launch.sum())
        # plannability mask, exactly as lp_device._stage derives
        # it: the ascent prices only groups some launchable
        # machine can hold one pod of — duals of excluded groups
        # never entered the Farley scaling, so they must not
        # enter the floor either
        req = enc.group_req.astype(np.float64)
        eff = np.clip(
            enc.cfg_alloc
            - enc.pool_overhead[np.maximum(enc.cfg_pool, 0)],
            0.0, None,
        )
        eff = np.where(launch[:, None], eff, 0.0)
        safe = np.where(req > 0, req, 1.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            k = np.floor((eff[None, :, :] + 1e-4) / safe[:, None, :])
        k = np.where(req[:, None, :] > 0, k, np.inf).min(axis=2)
        k = np.where(enc.compat & launch[None, :], k, 0.0)
        plannable = np.asarray(k >= 1).any(axis=1)
        lam_by_sig: dict = {}
        for gi, g in enumerate(enc.groups):
            if not plannable[gi]:
                continue
            sig = (
                g.requirements.signature(),
                g.tolerations,
                tuple(sorted(g.resources.items())),
            )
            lam = float(dlp.lam[gi])
            prev = lam_by_sig.get(sig)
            # signature collisions keep the smaller dual: the
            # bound must stay valid for either group's demand
            lam_by_sig[sig] = lam if prev is None else min(prev, lam)
        cap_term = 0.0
        if enc.rsv_cap is not None and len(dlp.mu):
            cap_term = float(
                dlp.mu @ enc.rsv_cap.astype(np.float64)
            )
        return _DualFloor(
            lam_by_sig=lam_by_sig,
            cap_term=cap_term,
            rank_launch=lp_device.rank_prices(enc, dlp)[:n_launch],
            n_launch=n_launch,
        )
    except Exception:
        import logging

        logging.getLogger("karpenter.solver.incremental").exception(
            "dual certificate derivation failed; caller runs unguided"
        )
        return None


class IncrementalPipeline:
    """Tick-to-tick warm-start solver over one pod population.

    `solve_tick(pods, pools_with_types)` returns a TickResult. The
    first tick (and any tick after invalidate()/catalog change/churn
    blow-out) runs the normal full solve and adopts its fleet; steady
    ticks diff the pod set, free capacity for deletions, and repack
    only new/changed pods against the residual fleet.

    With a kube client, a DirtyTracker on Pods feeds the changed set so
    in-place mutations (which keep object identity) are still caught;
    without one, object identity is the change signal — callers that
    mutate pods in place must pass fresh objects or call mark_dirty().
    """

    def __init__(
        self,
        kube=None,
        churn_max: Optional[float] = None,
        full_every: Optional[int] = None,
        drift_eps: Optional[float] = None,
        daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
        repack_objective: str = "ffd",
    ):
        self.cache = EncodedCache()
        self.churn_max = (
            churn_max if churn_max is not None
            else _env_float("KARPENTER_INCR_CHURN_MAX", 0.25)
        )
        self.full_every = (
            full_every if full_every is not None
            else int(_env_float("KARPENTER_INCR_FULL_EVERY", 16))
        )
        self.drift_eps = (
            drift_eps if drift_eps is not None
            else _env_float("KARPENTER_INCR_DRIFT_EPS", 0.01)
        )
        self.daemon_overhead = daemon_overhead or {}
        self.repack_objective = repack_objective
        # dual certificate from the last full solve's encode
        # (KARPENTER_INCR_DUAL_RANK / KARPENTER_INCR_DUAL_FLOOR knobs)
        self._dual: Optional[_DualFloor] = None
        self._fleet: Optional[list[ResidualNode]] = None
        self._where: dict[str, ResidualNode] = {}
        self._pods: dict[str, Pod] = {}
        self._unplaced: set[str] = set()
        self._marked: set[str] = set()
        self._catalog_fp: Optional[tuple] = None
        self._seq = 0
        self._tick = 0
        self._tracker = None
        if kube is not None:
            from karpenter_tpu.kube.dirty import DirtyTracker

            self._tracker = DirtyTracker(kube).watch("Pod")

    # -- state management -----------------------------------------------------

    def invalidate(self) -> None:
        """Full bust: relist/resync boundaries, or any time the caller
        can no longer vouch for the retained assignment."""
        self._fleet = None
        self._where = {}
        self._pods = {}
        self._unplaced = set()
        self._marked = set()
        self._catalog_fp = None
        self._dual = None
        self.cache.invalidate()
        if self._tracker is not None:
            # the next tick rebuilds from scratch anyway; stale dirty
            # keys must not force a second rebuild after it
            self._tracker.clear()

    def mark_dirty(self, *pod_keys: str) -> None:
        """Force pods into the next tick's changed set (the manual
        analogue of the kube-wired DirtyTracker for in-place
        mutations)."""
        self._marked.update(pod_keys)

    @property
    def fleet_price(self) -> float:
        return sum(n.price for n in self._fleet) if self._fleet else 0.0

    def _node_from_plan(self, plan) -> Optional[ResidualNode]:
        it, off = plan.primary()
        if plan.pool is None or it is None or off is None:
            return None
        self._seq += 1
        name = f"inc-{self._seq}"
        reqs = _config_requirements(plan.pool, it, off)
        reqs.add(Requirement(HOSTNAME_LABEL, IN, [name]))
        used = resutil.merge(
            self.daemon_overhead.get(plan.pool.metadata.name, {}),
            resutil.requests_for_pods(plan.pods),
        )
        node = ResidualNode(
            name=name,
            pool=plan.pool,
            instance_type=it,
            offering=off,
            price=float(plan.price),
            requirements=reqs,
            taints=tuple(plan.pool.spec.template.spec.taints),
            used=used,
        )
        for p in plan.pods:
            node.pods[p.key] = p
            self._where[p.key] = node
        return node

    def adopt(self, pods: Sequence[Pod], solution, pools_with_types,
              existing: Optional[Sequence[ResidualNode]] = None) -> None:
        """Replace the retained fleet with a full Solution's (the drift
        backstop's adoption path; also usable by an external backstop
        that computed the full solve itself).

        A solution computed against an EXISTING fleet (live nodes +
        in-flight claims) is adopted by passing `existing`: the
        ResidualNode list aligned index-for-index with the
        ExistingNodeInput order the solve was encoded with. Each
        existing assignment folds its pods (and their usage) into the
        matching residual node, and the retained fleet becomes
        existing + new — the extension past the original fresh-fleets
        guard that lets the pipeline model the live operator's fleet."""
        if solution.existing and existing is None:
            raise ValueError(
                "solution assigns pods to existing nodes; pass the "
                "ResidualNode list aligned with the solve's "
                "ExistingNodeInput order"
            )
        # an externally-computed adoption invalidates the cached dual
        # certificate (its catalog may differ); _full_tick re-derives
        # it right after from its own encode
        self._dual = None
        self._fleet = []
        self._where = {}
        self._pods = {p.key: p for p in pods}
        if existing is not None:
            for node in existing:
                self._fleet.append(node)
                for key, pod in node.pods.items():
                    self._where[key] = node
                    self._pods.setdefault(key, pod)
            for a in solution.existing:
                node = existing[a.existing_index]
                for p in a.pods:
                    node.pods[p.key] = p
                    self._where[p.key] = node
                node.used = resutil.merge(
                    node.used, resutil.requests_for_pods(a.pods)
                )
        for plan in solution.new_nodes:
            node = self._node_from_plan(plan)
            if node is not None:
                self._fleet.append(node)
        self._unplaced = {p.key for p in solution.unschedulable}
        self._catalog_fp = catalog_fingerprint(pools_with_types)

    def state_fingerprint(self) -> str:
        """Stable identity of the retained fleet: what a self-audit
        (or a restart-convergence test) compares before trusting the
        cache. Name-insensitive for NEW nodes (inc-N names are
        process-local) but exact on the capacity ledger."""
        import hashlib

        if self._fleet is None:
            return ""
        rows = sorted(
            (
                node.pool.metadata.name,
                node.instance_type.name if node.instance_type else "",
                round(node.price, 6),
                tuple(sorted(node.pods)),
                tuple(sorted((k, round(v, 6)) for k, v in node.used.items())),
            )
            for node in self._fleet
        )
        return hashlib.sha256(repr(rows).encode()).hexdigest()

    # -- solving --------------------------------------------------------------

    def solve_tick(
        self,
        pods: Sequence[Pod],
        pools_with_types,
        objective: str = "cost",
        delta: Optional[tuple[Sequence[Pod], Sequence[str]]] = None,
    ) -> TickResult:
        """One tick. `delta=(added_pods, removed_keys)` lets an
        event-driven caller (watch stream / dirty tracker) skip the
        O(pods) reconciliation scan — the delta is TRUSTED to be the
        exact diff against the previous tick's pod set; `pods` must
        still be the full population (the full-solve backstops need
        it). Without `delta`, the diff is derived by scanning `pods`
        against the retained assignment (object identity + any
        dirty-tracker/mark_dirty keys as the change signal)."""
        self._tick += 1
        dirty = self._marked
        self._marked = set()
        if self._tracker is not None:
            dirty = dirty | self._tracker.drain("Pod")

        if self._fleet is None:
            return self._full_tick(pods, pools_with_types, objective, "cold")
        if self._catalog_fp != catalog_fingerprint(pools_with_types):
            return self._full_tick(
                pods, pools_with_types, objective, "catalog"
            )

        if delta is not None:
            added_pods, removed_keys = delta
            removed = [k for k in removed_keys if k in self._pods]
            # a deleted pod's DELETED event also lands in the dirty
            # set — it must not resurrect as a changed pod
            removed_set = set(removed)
            changed_keys: list[str] = [
                k for k in dirty
                if k in self._pods and k not in removed_set
            ]
            place_new = list(added_pods)
            n_after = len(self._pods) - len(removed) + len(place_new)
        else:
            cur: dict[str, Pod] = {p.key: p for p in pods}
            removed = [k for k in self._pods if k not in cur]
            place_new = [p for k, p in cur.items() if k not in self._pods]
            changed_keys = [
                k for k, p in cur.items()
                if k in self._pods and (k in dirty or self._pods[k] is not p)
            ]
            # pods that silently vanished from `cur` while unplaced
            self._unplaced = {k for k in self._unplaced if k in cur}
            n_after = len(cur)

        churn = (
            len(removed) + len(place_new) + len(changed_keys)
        ) / max(1, n_after)
        if churn > self.churn_max:
            return self._full_tick(
                pods, pools_with_types, objective, "churn", churn=churn
            )

        if delta is not None:
            if changed_keys:
                # dirty keys need the CURRENT objects: watch streams
                # deliver fresh Pod objects on MODIFIED, so the stored
                # ones may carry the pre-mutation spec. The O(pods)
                # lookup build is paid only on ticks that actually saw
                # in-place mutations.
                current = {p.key: p for p in pods}
                changed_pods = [
                    current.get(k, self._pods[k]) for k in changed_keys
                ]
            else:
                changed_pods = []
        else:
            changed_pods = [cur[k] for k in changed_keys]
        result = self._incremental_tick(
            pools_with_types, removed, changed_keys, changed_pods,
            place_new, churn,
        )
        if self.full_every > 0 and self._tick % self.full_every == 0:
            return self._drift_backstop(pods, pools_with_types, objective,
                                        result)
        SOLVER_INCREMENTAL_TICKS.inc(
            {"mode": "incremental", "reason": result.reason}
        )
        return result

    def _full_tick(
        self, pods, pools_with_types, objective, reason, churn=0.0
    ) -> TickResult:
        from karpenter_tpu.solver.encode import encode, group_pods
        from karpenter_tpu.solver.solver import solve_encoded

        # encode here (instead of delegating to solve()) so the full
        # problem's Encoded is in hand: the dual certificate the
        # residual repack spends is derived from it, and under the
        # cost objective the LP was already solved for this very
        # fingerprint (maybe_solve is a cache hit)
        groups = group_pods(pods)
        enc = encode(
            groups, pools_with_types, (),
            self.daemon_overhead or None,
            compat_cache=self.cache,
        )
        sol = solve_encoded(enc, objective=objective)
        self.adopt(pods, sol, pools_with_types)
        self._refresh_dual(enc)
        SOLVER_INCREMENTAL_TICKS.inc({"mode": "full", "reason": reason})
        return TickResult(
            mode="full",
            reason=reason,
            scheduled=len(pods) - len(sol.unschedulable),
            unschedulable=len(sol.unschedulable),
            fleet_price=self.fleet_price,
            nodes=len(self._fleet),
            churn=churn,
            placed=len(pods),
        )

    def _refresh_dual(self, enc) -> None:
        """Rebuild the cached dual certificate from one full solve's
        encode (see _DualFloor). Degrades to None — the repack then
        runs exactly the unguided path."""
        self._dual = None
        if not (
            _env_on("KARPENTER_INCR_DUAL_RANK")
            or _env_on("KARPENTER_INCR_DUAL_FLOOR")
        ):
            return
        self._dual = build_dual_floor(enc)

    def _repack_solve(self, enc):
        """One residual repack solve, dual-rank-guided when the cached
        certificate applies: the unguided pack runs first; only when
        it OPENS fresh nodes (the one case ordering can matter — the
        steady churn tick that lands every pod in freed slots pays
        nothing) is the reduced-cost-ordered arm raced, and the
        cheaper fleet kept (ties keep unguided). Decode prices are
        the true catalog prices on both arms (price_hint contract)."""
        from karpenter_tpu.metrics.store import SOLVER_INCREMENTAL_DUAL
        from karpenter_tpu.solver.solver import solve_encoded

        sol = solve_encoded(enc, objective=self.repack_objective)
        dual = self._dual
        if (
            dual is None
            or not sol.new_nodes
            or not _env_on("KARPENTER_INCR_DUAL_RANK")
            or self.repack_objective == "cost"  # has its own race
        ):
            return sol
        # race only when the repack's fresh-open SPEND is a real
        # fraction of the fleet (KARPENTER_INCR_DUAL_RANK_MIN,
        # default 2%): the steady churn tick that opens a node or two
        # has pennies of ordering headroom but would pay a second
        # kernel dispatch (and its fresh-axis regrow/compile churn)
        # every tick — the race engages on the scale-out bursts where
        # LP-efficient type selection actually moves the bill
        spend = sum(float(p.price) for p in sol.new_nodes)
        floor_frac = _env_float("KARPENTER_INCR_DUAL_RANK_MIN", 0.02)
        if spend < floor_frac * max(self.fleet_price, 1e-9):
            return sol
        launch = enc.cfg_pool >= 0
        if int(launch.sum()) != dual.n_launch:
            return sol
        hint = enc.cfg_price.astype(np.float32).copy()
        hint[: dual.n_launch] = dual.rank_launch
        guided = solve_encoded(
            enc, objective=self.repack_objective, price_hint=hint
        )

        def key(s):
            return (
                len(s.unschedulable),
                round(sum(float(p.price) for p in s.new_nodes), 9),
                len(s.new_nodes),
            )

        if key(guided) < key(sol):
            SOLVER_INCREMENTAL_DUAL.inc({"outcome": "rank_win"})
            return guided
        SOLVER_INCREMENTAL_DUAL.inc({"outcome": "rank_loss"})
        return sol

    def _incremental_tick(
        self, pools_with_types, removed, changed_keys, changed_pods,
        place_new, churn,
    ) -> TickResult:
        from karpenter_tpu.solver.encode import encode, group_pods

        # free capacity held by deleted/changed pods
        for key in list(removed) + list(changed_keys):
            node = self._where.pop(key, None)
            if node is not None:
                pod = node.pods.pop(key)
                node.used = resutil.positive(
                    resutil.subtract(node.used, resutil.pod_requests(pod))
                )
            else:
                self._unplaced.discard(key)
        for key in removed:
            self._pods.pop(key, None)
        # emptied nodes are released (their price comes off the fleet)
        if any(not n.pods for n in self._fleet):
            self._fleet = [n for n in self._fleet if n.pods]

        # place: new pods, changed pods (now freed), then the retry
        # backlog of previously-unplaced pods — de-duped by key
        retry = [
            self._pods[k] for k in sorted(self._unplaced)
            if k in self._pods
        ]
        seen: set[str] = set()
        place: list[Pod] = []
        for p in list(place_new) + list(changed_pods) + retry:
            if p.key not in seen:
                seen.add(p.key)
                place.append(p)
        for p in place_new:
            self._pods[p.key] = p
        for p in changed_pods:
            self._pods[p.key] = p

        placed_total = len(place)
        new_unplaced: set[str] = set()
        rounds = 0
        while place and rounds < 8:
            rounds += 1
            groups = group_pods(place)
            # Residual prune (exact): a node whose available capacity
            # is below the componentwise MINIMUM request over the
            # groups being placed can hold none of them now — and
            # nodes only get fuller during a solve, so its capacity
            # row would be zero at every step. Dropping it shrinks the
            # bound axis from the whole fleet to the nodes with real
            # headroom (most of a packed fleet is full) without
            # changing the FFD outcome: first-feasible order over the
            # survivors is first-feasible order over all. Only keys
            # EVERY group requests (>0) can prune — a group that
            # doesn't request a key imposes no floor on it, so its
            # componentwise minimum is 0 and the key must drop out
            # (e.g. a CPU-only pod must still see GPU-less nodes when
            # a GPU pod shares the tick).
            min_req: dict[str, float] = {}
            req_counts: dict[str, int] = {}
            for g in groups:
                for k, v in g.resources.items():
                    if v <= 0:
                        continue
                    req_counts[k] = req_counts.get(k, 0) + 1
                    have = min_req.get(k)
                    min_req[k] = v if have is None else min(have, v)
            min_req = {
                k: v for k, v in min_req.items()
                if req_counts[k] == len(groups)
            }
            inputs = []
            order: list[ResidualNode] = []
            for node in self._fleet:
                avail = node.available()
                # float32-scale margin, same as the live tick's prune:
                # a boundary-exact fill reads "full" in float64 but
                # exactly-fitting in the kernel's float32 — never drop
                # a node the kernel could still use
                if any(
                    avail.get(k, 0.0) < v * (1.0 - 1e-6)
                    for k, v in min_req.items()
                ):
                    continue
                inputs.append(
                    ExistingNodeInput(
                        name=node.name,
                        requirements=node.requirements,
                        taints=node.taints,
                        available=avail,
                        pool_name=node.pool.metadata.name,
                        pod_count=len(node.pods),
                    )
                )
                order.append(node)
            enc = encode(
                groups, pools_with_types, inputs,
                daemon_overhead=self.daemon_overhead or None,
                compat_cache=self.cache,
            )
            # rides the wavefront routing at the _solve_packing seam:
            # a churn-burst tick whose residual demand spans many group
            # signatures commits them in batched rounds, while the
            # typical small tick (few signatures) stays on the
            # sequential kernel via pack.WAVEFRONT_MIN_GROUPS.
            # Dual-rank-guided when fresh nodes open (ISSUE 15).
            sol = self._repack_solve(enc)
            for a in sol.existing:
                node = order[a.existing_index]
                for p in a.pods:
                    node.pods[p.key] = p
                    self._where[p.key] = node
                node.used = resutil.merge(
                    node.used, resutil.requests_for_pods(a.pods)
                )
            for plan in sol.new_nodes:
                node = self._node_from_plan(plan)
                if node is not None:
                    self._fleet.append(node)
            evicted_keys = {p.key for p in sol.evicted}
            new_unplaced.update(
                p.key for p in sol.unschedulable
                if p.key not in evicted_keys
            )
            # k-way-evicted pods are schedulable alone; retry them
            # against the now-updated residual fleet (bounded)
            place = list(sol.evicted)
        new_unplaced.update(p.key for p in place)  # retry bound hit
        self._unplaced = new_unplaced

        return TickResult(
            mode="incremental",
            reason="steady",
            scheduled=len(self._pods) - len(self._unplaced),
            unschedulable=len(self._unplaced),
            fleet_price=self.fleet_price,
            nodes=len(self._fleet),
            churn=churn,
            placed=placed_total,
        )

    def _drift_backstop(
        self, pods, pools_with_types, objective, result: TickResult
    ) -> TickResult:
        """Periodic correctness backstop: run the full solve and
        compare. The incremental fleet survives only while it prices
        within drift_eps of (or beats) the full re-solve AND places
        exactly as many pods; otherwise the full solution is adopted.

        Weak-duality short-circuit (ISSUE 15): with every pod placed
        and the retained fleet priced within drift_eps of the cached
        LP floor for the CURRENT demand, no full re-solve can beat it
        by more than epsilon — drift <= fleet/bound - 1 <= drift_eps
        and placed_fewer is impossible — so the backstop's adoption
        decision is already known and the O(pods) solve is skipped
        (decision-identical by construction; the floor is the
        float64-certified dual bound, conservative for new demand
        because unknown group signatures price at zero)."""
        from karpenter_tpu.solver.encode import group_pods
        from karpenter_tpu.solver.solver import solve

        if (
            self._dual is not None
            and not self._unplaced
            and _env_on("KARPENTER_INCR_DUAL_FLOOR")
            and result.unschedulable == 0
        ):
            bound = self._dual.bound_for(group_pods(pods))
            if bound > 0 and result.fleet_price <= bound * (
                1.0 + self.drift_eps
            ):
                from karpenter_tpu.metrics.store import (
                    SOLVER_INCREMENTAL_DUAL,
                )

                SOLVER_INCREMENTAL_DUAL.inc({"outcome": "floor_skip"})
                SOLVER_INCREMENTAL_TICKS.inc(
                    {"mode": "incremental", "reason": "dual_floor"}
                )
                result.reason = "dual_floor"
                # upper bound on true drift (the full solve prices
                # somewhere in [bound, fleet_price])
                result.drift = (
                    result.fleet_price / bound - 1.0 if bound > 0 else 0.0
                )
                return result

        sol = solve(
            pods, pools_with_types,
            daemon_overhead=self.daemon_overhead or None,
            objective=objective, compat_cache=self.cache,
        )
        full_price = float(sol.total_price)
        drift = (
            (result.fleet_price - full_price) / full_price
            if full_price > 0 else 0.0
        )
        # Adoption must never trade placed pods away: the incremental
        # path retries k-way-evicted pods against the updated residual
        # fleet, so it can legitimately place MORE pods than the
        # single-shot full solve — keep that fleet regardless of
        # price. Adopt only when the full solve places at least as
        # many pods AND (the incremental fleet placed fewer, or its
        # price drifted past epsilon).
        placed_fewer = result.unschedulable > len(sol.unschedulable)
        placed_more = result.unschedulable < len(sol.unschedulable)
        if placed_fewer or (drift > self.drift_eps and not placed_more):
            self.adopt(pods, sol, pools_with_types)
            SOLVER_INCREMENTAL_TICKS.inc({"mode": "full", "reason": "drift"})
            return TickResult(
                mode="full",
                reason="drift",
                scheduled=len(pods) - len(sol.unschedulable),
                unschedulable=len(sol.unschedulable),
                fleet_price=self.fleet_price,
                nodes=len(self._fleet),
                churn=result.churn,
                placed=result.placed,
                drift=drift,
            )
        SOLVER_INCREMENTAL_TICKS.inc(
            {"mode": "incremental", "reason": "checked"}
        )
        result.reason = "checked"
        result.drift = drift
        return result
