"""Deterministic, spec-driven fault injector for the solver stack.

The resilience layer (solver/resilience.py) only earns trust if its
failure paths can be driven on demand and REPLAYED exactly: a chaos
test that sometimes loses the device on the 3rd solve and sometimes on
the 4th proves nothing. This injector is therefore sequence-, not
time-based: every instrumented call site ("solve", "compile",
"execute", "probe", "warm", "rpc", "rpc_server") keeps a monotonically
increasing per-site counter, and a rule fires on exact occurrence
numbers of that counter. Two runs of the same workload under the same
spec produce byte-identical fault sequences (see `snapshot_log`).

Spec grammar (KARPENTER_FAULTS, comma-separated entries):

    entry  = kind [ "@" site ] [ ":" occ ] [ "=" param ] [ "#" seed ]
    kind   = device_lost | rpc_drop | compile_delay | exec_delay
           | kube_conflict | kube_throttle | kube_watch_drop
           | kube_stale_list | kube_write_partial | operator_crash
           | spot_interruption | cache_poison | demand_surge
    occ    = "*" | N | N "+" | N "-" M        (1-based, per site)
    param  = duration                         (delay / retry-after kinds)
           | rate                             (spot_interruption: 0 < r <= 1)
           | count                            (demand_surge: pods per burst)
    seed   = per-entry replay seed for rate-based admission and surge
             shapes; composed schedules (the scenario flywheel) layer
             independently-seeded storms into ONE spec this way.
             Entries without a "#seed" fall back to the injector-wide
             KARPENTER_FAULT_SEED.

Examples:
    device_lost@solve:3        third device solve raises DeviceLostError
    rpc_drop@probe:*           every batched-probe dispatch drops
    compile_delay=5s           every kernel dispatch sleeps 5s first
    rpc_drop@rpc:2-4           RPCs 2..4 drop, then the service heals
    kube_conflict@kube_write:2-4   writes 2..4 answer 409
    kube_throttle=250ms        every kube write 429s, Retry-After 250ms
    operator_crash@crash_bind:2    die just before the 2nd pod binding
    cache_poison@incremental:2     corrupt a retained capacity row at the
                                   2nd incremental live tick — the oracle
                                   audit must catch it and degrade to the
                                   full-solve decision
    spot_interruption@cloud_interrupt:3      3rd interruption check reclaims
    spot_interruption@cloud_interrupt:*=0.05 each check reclaims w.p. 5%,
                                             decided by a seeded hash of the
                                             check's sequence number — the
                                             deterministic stand-in for a
                                             5%/hr interruption regime when
                                             the provider polls hourly.
                                             KARPENTER_FAULT_SEED picks the
                                             schedule; same seed + same spec
                                             replay byte-identically.
    spot_interruption@cloud_interrupt:*=0.1#storm-a
                                             same, but the schedule is drawn
                                             from THIS entry's own seed — a
                                             composed spec can carry several
                                             independently-seeded storms
                                             without them aliasing each other
    demand_surge@provision_intake:2=500      the 2nd live provisioning intake
                                             absorbs a seeded burst of 500
                                             pending pods (mixed low/high
                                             PriorityClass values, shapes
                                             hashed from seed+occurrence) —
                                             the overload storm priority
                                             admission must degrade through

Default sites per kind: device_lost -> solve, rpc_drop -> rpc,
compile_delay -> compile, exec_delay -> execute, kube faults -> their
natural verb site, operator_crash -> crash_tick. Error kinds raise
their exception at the site; delay kinds sleep there (inflating the
phase the watchdog budgets). Instrumented sites:

    solve       pack._run_pack, once per kernel attempt
    compile     pack._run_pack, just before the jitted dispatch
    execute     pack fetch, just before blocking on the device buffer
    probe       consolidation_batch chunk dispatch (batched ladder)
    warm        warm_pool per-bucket AOT compile
    rpc         service client, before sending the RPC
    rpc_server  service server, inside the Solve handler

Cloud sites (hooked into the kwok/fake providers):

    incremental      one incremental live tick of the provisioner's
                     retained-state scheduler (provisioning/
                     incremental_tick.py); a firing cache_poison rule
                     raises CachePoisonError, which the tick CONSUMES —
                     one retained capacity row is corrupted
                     deterministically (the first fleet key in sorted
                     order gains phantom capacity), so the oracle audit
                     has a real stale-cache divergence to catch

    provision_intake one live provisioning intake of the provisioner
                     (Provisioner.schedule, the non-scripted path); a
                     firing demand_surge rule raises DemandSurgeError,
                     which the provisioner CONSUMES — a deterministic
                     burst of pending pods (names/shapes/priorities
                     hashed from the fault seed and the site sequence
                     number) is created in the kube store and joins the
                     round's solve, modeling a workload controller
                     scaling out mid-tick

    cloud_interrupt  one interruption check of one live spot instance
                     (providers iterate spot instances in sorted
                     provider-id order, so occurrence numbers map to
                     instances deterministically); a firing
                     spot_interruption rule raises SpotInterruptionError,
                     which the provider CONSUMES — the instance gets an
                     interruption notice, exactly like a cloud's
                     rebalance/termination warning

Kube sites (hooked into HTTPTransport.request/watch_events and
InMemoryApiServer — the transport maps the raised fault to the HTTP
status a real API server would answer; see kube/real.py):

    kube_read   GET of a single object
    kube_list   collection GET (LIST)
    kube_write  POST/PUT/DELETE incl. the eviction/binding subresources
    kube_watch  one watch_events() drain (drop -> 410 Gone -> relist)

Operator crash points (Operator.step and the controllers it drives;
`operator_crash` raises OperatorCrashError there — the restart-chaos
harness treats it as process death and boots a fresh operator against
the surviving API server):

    crash_tick                 tick start, right after the informer pump
    crash_claims               solver decided, before NodeClaims are written
    crash_provision            claims written, before the binding plan is queued
    crash_bind                 before the Nth pod binding of the tick
    crash_launch               provider launch succeeded, before the claim
                               records its provider id (the double-launch window)
    crash_disruption           disruption command computed, before it starts
    crash_disruption_started   command started (taints + replacements),
                               before its binding plan is queued
    crash_incr_solve           incremental tick drained its dirty sets,
                               before the residual solve runs
    crash_incr_commit          incremental tick solved, before its plans
                               are handed back for NodeClaim writes
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Optional, Sequence

log = logging.getLogger("karpenter.solver.faults")

ENV_SPEC = "KARPENTER_FAULTS"
ENV_SEED = "KARPENTER_FAULT_SEED"

CRASH_SITES = (
    "crash_tick", "crash_claims", "crash_provision", "crash_bind",
    "crash_launch", "crash_disruption", "crash_disruption_started",
    "crash_incr_solve", "crash_incr_commit",
)

SITES = (
    "solve", "compile", "execute", "probe", "warm", "rpc", "rpc_server",
    "kube_read", "kube_list", "kube_write", "kube_watch",
    "cloud_interrupt", "incremental", "provision_intake",
) + CRASH_SITES

_DEFAULT_SITE = {
    "device_lost": "solve",
    "rpc_drop": "rpc",
    "compile_delay": "compile",
    "exec_delay": "execute",
    "kube_conflict": "kube_write",
    "kube_throttle": "kube_write",
    "kube_watch_drop": "kube_watch",
    "kube_stale_list": "kube_list",
    "kube_write_partial": "kube_write",
    "operator_crash": "crash_tick",
    "spot_interruption": "cloud_interrupt",
    "cache_poison": "incremental",
    "demand_surge": "provision_intake",
}

_ERROR_KINDS = (
    "device_lost", "rpc_drop", "kube_conflict", "kube_throttle",
    "kube_watch_drop", "kube_stale_list", "kube_write_partial",
    "operator_crash", "spot_interruption", "cache_poison",
    "demand_surge",
)


class FaultError(RuntimeError):
    """Base class for injected faults (classified by resilience)."""


class DeviceLostError(FaultError):
    """Injected stand-in for an XLA runtime / device-lost failure."""


class RpcDropError(FaultError):
    """Injected stand-in for an unreachable solver service."""


class KubeFaultError(FaultError):
    """Base class for kube-API faults: raised at the transport's fault
    site and CONSUMED there — the transport answers the HTTP status the
    fault models, so clients exercise their real status-code paths
    instead of a foreign exception type."""


class KubeConflictError(KubeFaultError):
    """Injected 409: the write raced another actor."""


class KubeThrottleError(KubeFaultError):
    """Injected 429: API-server client-side throttling. `retry_after`
    (the entry's =duration) rides in the Status body the way a real
    apiserver ships details.retryAfterSeconds."""

    def __init__(self, message: str, retry_after: float = 0.0):
        super().__init__(message)
        self.retry_after = retry_after


class WatchDropError(KubeFaultError):
    """Injected watch-stream drop: the transport surfaces 410 Gone so
    the informer must relist."""


class StaleListError(KubeFaultError):
    """Injected stale LIST: the transport re-serves its previous LIST
    response (an etcd follower lagging behind a quorum write)."""


class WritePartialError(KubeFaultError):
    """Injected write-partial: the write LANDS server-side but the
    response is lost (connection cut after commit) — the client sees a
    500 for a mutation that actually happened."""


class OperatorCrashError(FaultError):
    """Injected operator death at a crash point. Never caught inside
    the operator: it must unwind the whole tick, exactly like SIGKILL
    between two writes would."""


class CachePoisonError(FaultError):
    """Injected retained-state corruption. Raised at the incremental
    live tick's `incremental` site and CONSUMED there — the tick
    corrupts one retained capacity row deterministically, modeling the
    stale-cache failure the oracle audit exists to catch."""


class DemandSurgeError(FaultError):
    """Injected demand surge: a workload controller scaled out between
    two ticks. Raised at the provisioner's `provision_intake` site and
    CONSUMED there — a seeded burst of `count` pending pods (mixed
    low/high PriorityClass values, deterministic names
    `surge-<seq>-<i>`) is created and joins the round's solve. `seq`
    and `seed` make the burst a pure function of the schedule, so two
    runs of the same spec inject byte-identical demand."""

    def __init__(self, message: str, count: int = 0, seq: int = 0,
                 seed: str = "0"):
        super().__init__(message)
        self.count = count
        self.seq = seq
        self.seed = seed


class SpotInterruptionError(FaultError):
    """Injected spot-capacity interruption notice. Raised at the
    provider's `cloud_interrupt` check for one instance and CONSUMED
    there — the provider marks the instance interrupted so the
    interruption controller sees the notice through its normal poll,
    exactly like a cloud's rebalance/termination warning."""


@dataclass(frozen=True)
class FaultRule:
    kind: str
    site: str
    lo: int            # 1-based first occurrence; 0 == every occurrence
    hi: int            # last occurrence inclusive; -1 == open-ended
    delay: float = 0.0
    rate: float = 1.0  # <1.0: fire w.p. rate, seeded-hash-decided per seq
    count: int = 0     # demand_surge: pods per injected burst
    # per-entry replay seed (the "#seed" suffix); None falls back to
    # the injector-wide KARPENTER_FAULT_SEED — composed specs carry
    # one independently-seeded schedule per layer this way
    seed: Optional[str] = None

    def matches(self, seq: int) -> bool:
        if self.lo == 0:
            return True
        if seq < self.lo:
            return False
        return self.hi < 0 or seq <= self.hi


def _parse_duration(text: str) -> float:
    """Bare seconds, or a `ms`/`s`/`m`/`h` suffix. The `ms` check must
    precede `m` and `s` (both are suffixes of it)."""
    text = text.strip().lower()
    if text.endswith("ms"):
        return float(text[:-2]) / 1000.0
    if text.endswith("h"):
        return float(text[:-1]) * 3600.0
    if text.endswith("m"):
        return float(text[:-1]) * 60.0
    if text.endswith("s"):
        return float(text[:-1])
    return float(text)


def _hash01(seed: str, site: str, seq: int) -> float:
    """Deterministic uniform-ish [0, 1) from (seed, site, seq) — the
    replay clock for rate-based rules. Pure function of the per-site
    sequence number, so two runs of the same workload under the same
    seed reclaim the same occurrences."""
    return (zlib.crc32(f"{seed}:{site}:{seq}".encode()) & 0xFFFFFFFF) / 2.0**32


def parse(spec: str, rejected: Optional[list] = None) -> list[FaultRule]:
    """Parse a KARPENTER_FAULTS spec. Malformed entries are dropped
    with a warning — chaos knobs must never take the operator down —
    but never silently: each drop increments
    karpenter_faults_rejected_total and lands in `rejected` (surfaced
    through readyz() so a typo'd chaos knob is visible)."""
    rules: list[FaultRule] = []
    for raw in (spec or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        try:
            # the "#seed" suffix splits off FIRST: params (durations,
            # rates, counts) never contain "#", and the seed must not
            # leak into the =param float parse
            entry, hash_sep, rule_seed = raw.partition("#")
            if hash_sep:
                rule_seed = rule_seed.strip()
                if not rule_seed or any(
                    c in rule_seed for c in "@:=#"
                ) or any(c.isspace() for c in rule_seed):
                    raise ValueError(f"bad per-entry seed {rule_seed!r}")
            else:
                rule_seed = None
            body, _, param = entry.partition("=")
            head, _, occ = body.partition(":")
            kind, _, site = head.partition("@")
            kind = kind.strip()
            site = site.strip() or _DEFAULT_SITE.get(kind, "solve")
            if kind not in _DEFAULT_SITE:
                raise ValueError(f"unknown kind {kind!r}")
            if site not in SITES:
                raise ValueError(f"unknown site {site!r}")
            occ = occ.strip()
            if not occ or occ == "*":
                lo, hi = 0, -1
            elif occ.endswith("+"):
                lo, hi = int(occ[:-1]), -1
            elif "-" in occ:
                a, b = occ.split("-", 1)
                lo, hi = int(a), int(b)
            else:
                lo = hi = int(occ)
            if (occ and occ != "*" and lo < 1) or (hi >= 0 and hi < lo):
                raise ValueError(f"bad occurrence range {occ!r}")
            rate = 1.0
            count = 0
            if kind == "spot_interruption":
                # the =param is a probability per occurrence, not a
                # duration (spec grammar: spot_interruption@...:occ=rate)
                rate = float(param) if param else 1.0
                if not 0.0 < rate <= 1.0:
                    raise ValueError(f"bad interruption rate {param!r}")
                delay = 0.0
            elif kind == "demand_surge":
                # the =param is the burst size in pods
                count = int(param) if param else 16
                if count < 1:
                    raise ValueError(f"bad surge count {param!r}")
                delay = 0.0
            else:
                delay = _parse_duration(param) if param else 0.0
            if kind.endswith("_delay") and delay <= 0.0:
                raise ValueError("delay kind needs a =duration")
            rules.append(FaultRule(kind, site, lo, hi, delay, rate,
                                   count, rule_seed))
        except (ValueError, IndexError) as err:
            log.warning("ignoring malformed fault entry %r: %s", raw, err)
            if rejected is not None:
                rejected.append(raw)
            from karpenter_tpu.metrics.store import FAULTS_REJECTED

            FAULTS_REJECTED.inc()
    return rules


class FaultInjector:
    """Applies parsed rules against per-site sequence counters.

    Thread-safe; the counters (not wall time) are the replay clock, so
    concurrent call sites interleave but each site's own sequence —
    and therefore which of its calls fault — is deterministic."""

    def __init__(self, rules: Sequence[FaultRule], sleep=time.sleep,
                 seed: str = "0", rejected: Optional[list] = None):
        self.rules = list(rules)
        self._sleep = sleep
        self.seed = seed
        # malformed spec entries dropped at parse time (readyz surfaces
        # them so a typo'd chaos knob is visible, not silent)
        self.rejected: list[str] = list(rejected or [])
        self._seq: dict[str, int] = {}
        self._lock = threading.Lock()
        # (site, seq, kind, trace_id): the trace id of the tick the
        # fault fired in — the provenance column joining a replay log
        # entry to its /debug/traces span tree. snapshot_log() strips
        # it (trace ids are per-run; the replay-identity artifact must
        # stay byte-identical across runs of the same schedule).
        self.log: list[tuple[str, int, str, str]] = []

    def _admits(self, rule: FaultRule, site: str, seq: int) -> bool:
        if not rule.matches(seq):
            return False
        if rule.rate >= 1.0:
            return True
        # a rule carrying its own "#seed" replays from that seed; the
        # injector-wide seed covers the rest — two rate rules in one
        # composed spec draw from independent schedules
        seed = rule.seed if rule.seed is not None else self.seed
        return _hash01(seed, site, seq) < rule.rate

    def fire(self, site: str) -> None:
        """Advance `site`'s sequence counter and apply matching rules:
        delays sleep in the caller, then the first error kind raises."""
        from karpenter_tpu import tracing

        trace_id = tracing.current_trace_id()
        with self._lock:
            seq = self._seq.get(site, 0) + 1
            self._seq[site] = seq
            hits = [r for r in self.rules
                    if r.site == site and self._admits(r, site, seq)]
            for rule in hits:
                self.log.append((site, seq, rule.kind, trace_id))
        if not hits:
            return
        from karpenter_tpu.metrics.store import SOLVER_FAULTS_INJECTED

        error: Optional[FaultError] = None
        for rule in hits:
            # fault attribution on the span tree: the innermost open
            # span of the tick carries every fault fired under it
            tracing.add_event("fault", kind=rule.kind, site=site, seq=seq)
            SOLVER_FAULTS_INJECTED.inc({"site": site, "kind": rule.kind})
            if rule.kind.endswith("_delay"):
                log.warning("fault injected: %s@%s:%d sleeping %.3fs",
                            rule.kind, site, seq, rule.delay)
                self._sleep(rule.delay)
            elif error is None:
                error = self._make_error(rule, site, seq)
        if error is not None:
            log.warning("fault injected: %s", error)
            raise error

    def _make_error(self, rule: FaultRule, site: str, seq: int) -> FaultError:
        message = f"injected {rule.kind}@{site}:{seq}"
        if rule.kind == "kube_throttle":
            return KubeThrottleError(message, retry_after=rule.delay)
        if rule.kind == "demand_surge":
            return DemandSurgeError(
                message, count=rule.count, seq=seq,
                seed=rule.seed if rule.seed is not None else self.seed,
            )
        cls = {
            "device_lost": DeviceLostError,
            "rpc_drop": RpcDropError,
            "kube_conflict": KubeConflictError,
            "kube_watch_drop": WatchDropError,
            "kube_stale_list": StaleListError,
            "kube_write_partial": WritePartialError,
            "operator_crash": OperatorCrashError,
            "spot_interruption": SpotInterruptionError,
            "cache_poison": CachePoisonError,
        }.get(rule.kind, FaultError)
        return cls(message)

    def snapshot_log(self) -> list[tuple[str, int, str]]:
        """Copy of the fired-fault log: (site, per-site seq, kind) in
        firing order — the replay-identity artifact chaos tests diff.
        The per-run trace-id column is deliberately stripped here (two
        replays of one schedule must compare byte-identical); use
        snapshot_log_traced() for the provenance view."""
        with self._lock:
            return [(site, seq, kind) for site, seq, kind, _ in self.log]

    def snapshot_log_traced(self) -> list[tuple[str, int, str, str]]:
        """The provenance view of the replay log: (site, seq, kind,
        trace_id) — each fired fault joined to the tick trace it fired
        in ("" outside any trace), resolvable via /debug/traces."""
        with self._lock:
            return list(self.log)


# -- env-driven singleton -----------------------------------------------------

_active: Optional[FaultInjector] = None
_active_spec: Optional[str] = None
_active_lock = threading.Lock()


def get() -> Optional[FaultInjector]:
    """The active injector per KARPENTER_FAULTS (+ the seed), or None.
    A changed spec or seed builds a fresh injector with zeroed
    counters, so tests that re-point the env replay from occurrence
    1."""
    spec = os.environ.get(ENV_SPEC, "")
    global _active, _active_spec
    if not spec:
        if _active is not None:
            with _active_lock:
                _active, _active_spec = None, None
        return None
    seed = os.environ.get(ENV_SEED, "0")
    key = f"{seed}|{spec}"
    if key != _active_spec:
        with _active_lock:
            if key != _active_spec:
                rejected: list[str] = []
                _active = FaultInjector(
                    parse(spec, rejected=rejected), seed=seed,
                    rejected=rejected,
                )
                _active_spec = key
    return _active


def rejected_specs() -> list[str]:
    """Malformed entries the ACTIVE spec dropped at parse time — the
    operator surfaces these through readyz() so a typo'd chaos knob is
    observable without grepping logs."""
    injector = get()
    return list(injector.rejected) if injector is not None else []


def reset() -> None:
    """Zero the active injector's counters (fresh replay, same spec)."""
    global _active, _active_spec
    with _active_lock:
        _active, _active_spec = None, None


def snapshot_active():
    """Opaque (injector, key) state for scoped spec overrides: callers
    that temporarily re-point KARPENTER_FAULTS (bench arms) save the
    ambient injector here and `restore_active` it afterwards, so an
    externally-set schedule keeps its occurrence counters and replay
    log across the override instead of being reset to occurrence 1."""
    with _active_lock:
        return _active, _active_spec


def restore_active(state) -> None:
    """Reinstate a `snapshot_active` state (see there)."""
    global _active, _active_spec
    with _active_lock:
        _active, _active_spec = state


def fire(site: str) -> None:
    """Module-level hook the instrumented sites call. No-op (one dict
    lookup) when KARPENTER_FAULTS is unset."""
    injector = get()
    if injector is not None:
        injector.fire(site)
