"""Test harness: environment + expectation DSL.

Counterpart of pkg/test (object factories, environment.go) and
pkg/test/expectations (ExpectProvisioned, ExpectMakeNodesInitialized):
wires the in-memory API, state mirror, provider and controllers
together and drives full provision cycles synchronously, the way the
reference's envtest suites call ExpectReconciled.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional, Sequence

from karpenter_tpu.apis.v1.nodepool import NodePool, NodePoolSpec
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import CloudProvider, InstanceType
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import (
    Container,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodSpec,
)
from karpenter_tpu.disruption.conditions import (
    DisruptionConditionsController,
    ExpirationController,
    PodEventsController,
)
from karpenter_tpu.disruption.engine import DisruptionEngine
from karpenter_tpu.lifecycle.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.lifecycle.termination import TerminationController
from karpenter_tpu.provisioning.provisioner import Provisioner
from karpenter_tpu.provisioning.scheduler import SchedulerResults
from karpenter_tpu.state.cluster import Cluster, attach_informers

_name_counter = itertools.count(1)


def interleaved_best_of(
    sides: dict,
    *,
    rounds: int,
    min_rounds: int = 5,
    satisfied=None,
    reduce=min,
    disable_gc: bool = True,
) -> dict:
    """Interleaved best-of-N with early exit — THE timing-guard
    pattern (ISSUE 13 satellite; grown across the resilience-wrapper,
    kube-funnel, and tracing guards before being extracted here).

    Measuring two sides in separate blocks lets a load shift between
    the blocks (another test's GC, CI noisy neighbors) masquerade as
    overhead; alternating per round exposes every side to the same
    noise. `sides` maps name -> zero-arg callable returning one float
    sample; each round samples every side once in dict order and folds
    it into that side's running best via `reduce` (min for wall-clock
    guards — both sides deterministic, so the minimum is the honest
    cost; max for succeed-at-least-once retry guards). Sampling stops
    the moment `satisfied(best)` holds after `min_rounds` rounds, so a
    single load spike early in the run cannot doom the remaining
    fixed-count samples — while a systematic failure still fails: no
    sample combination can satisfy the predicate. GC is disabled
    around the loop by default so a collection landing inside one
    side's sample can't masquerade as overhead.

    Returns {name: best_sample}."""
    import gc as _gc

    best: dict = {}
    if disable_gc:
        _gc.disable()
    try:
        for i in range(rounds):
            for name, fn in sides.items():
                sample = fn()
                best[name] = (
                    sample if name not in best
                    else reduce(best[name], sample)
                )
            if (
                satisfied is not None
                and i + 1 >= min_rounds
                and satisfied(dict(best))
            ):
                break
    finally:
        if disable_gc:
            _gc.enable()
    return best


def mk_pod(
    name: Optional[str] = None,
    cpu: float = 1.0,
    memory: float = 2**30,
    labels: Optional[dict] = None,
    node_selector: Optional[dict] = None,
    owner: Optional[str] = "ReplicaSet",
    **spec_kwargs,
) -> Pod:
    """`owner` is the controlling workload kind (the reference's
    test.Pod defaults to ReplicaSet-owned too — drain rebirth only
    applies to controller-owned pods); pass owner=None for a bare pod,
    which eviction terminates for good."""
    name = name or f"pod-{next(_name_counter):05d}"
    refs = []
    if owner:
        refs = [OwnerReference(kind=owner, name=f"{name}-owner",
                               uid=f"uid-{name}-owner", controller=True)]
    return Pod(
        metadata=ObjectMeta(
            name=name, labels=labels or {}, owner_references=refs
        ),
        spec=PodSpec(
            containers=[Container(requests={"cpu": cpu, "memory": memory})],
            node_selector=node_selector or {},
            **spec_kwargs,
        ),
    )


def mk_nodepool(name: Optional[str] = None, **kwargs) -> NodePool:
    return NodePool(
        metadata=ObjectMeta(name=name or f"pool-{next(_name_counter):05d}", namespace=""),
        spec=NodePoolSpec(**kwargs),
    )


@dataclass
class Environment:
    """One test cluster: in-memory API + state + controllers."""

    types: Optional[list[InstanceType]] = None
    registration_delay: float = 0.0
    options: Optional[object] = None  # operator Options; test default
                                      # enables SpotToSpotConsolidation
    kube: KubeClient = field(init=False)
    cluster: Cluster = field(init=False)
    cloud: KwokCloudProvider = field(init=False)
    provisioner: Provisioner = field(init=False)
    lifecycle: NodeClaimLifecycle = field(init=False)
    termination: TerminationController = field(init=False)

    def __post_init__(self) -> None:
        self.kube = KubeClient()
        self.cluster = Cluster(self.kube)
        attach_informers(self.kube, self.cluster)
        # one sim clock for the whole environment: every explicit
        # `now=` advances it, and the cloud's instance timestamps use
        # it too — mixing wall-clock created_at with simulated `now`
        # would gate registration delays forever
        self._sim_now: Optional[float] = None
        self.cloud = KwokCloudProvider(
            self.kube, types=self.types,
            registration_delay=self.registration_delay,
            clock=self._clock,
        )
        from karpenter_tpu.events.recorder import EventRecorder

        self.recorder = EventRecorder(kube=self.kube)
        self.provisioner = Provisioner(self.kube, self.cluster, self.cloud,
                                       recorder=self.recorder)
        self.lifecycle = NodeClaimLifecycle(self.kube, self.cloud)
        self.termination = TerminationController(self.kube, self.cluster,
                                                 recorder=self.recorder)
        self.conditions = DisruptionConditionsController(
            self.kube, self.cluster, self.cloud
        )
        self.expiration = ExpirationController(self.kube)
        self.pod_events = PodEventsController(self.kube, self.cluster)
        if self.options is None:
            from karpenter_tpu.operator.options import FeatureGates, Options

            self.options = Options(
                feature_gates=FeatureGates(spot_to_spot_consolidation=True)
            )
        self.disruption = DisruptionEngine(
            self.kube, self.cluster, self.cloud, self.provisioner,
            options=self.options, recorder=self.recorder,
        )
        from karpenter_tpu.disruption.interruption import (
            InterruptionController,
        )

        self.interruption = InterruptionController(
            self.kube, self.cluster, self.cloud, self.disruption,
            recorder=self.recorder,
        )
        from karpenter_tpu.provisioning.static import StaticCapacityController

        self.static = StaticCapacityController(
            self.kube, self.cluster, self.options
        )

    def _clock(self) -> float:
        import time as _time

        return self._sim_now if self._sim_now is not None else _time.time()

    def _advance(self, now: Optional[float]) -> None:
        if now is not None:
            self._sim_now = (
                now if self._sim_now is None else max(self._sim_now, now)
            )

    def reconcile_disruption(self, now: Optional[float] = None):
        """One disruption cycle: refresh conditions, run the engine,
        progress the orchestration queue and termination."""
        self._advance(now)
        self.pod_events.reconcile_all(now=now)
        self.conditions.reconcile_all(now=now)
        command = self.disruption.reconcile(now=now)
        self.lifecycle.reconcile_all(now=now)
        self.cloud.tick(now=now)
        self.lifecycle.reconcile_all(now=now)
        self.disruption.queue.reconcile(now=now)
        self.reconcile_termination(now=now)
        # evicted workload pods come back pending; rebind them
        if self.provisioner.get_pending_pods():
            self.provision(now=now)
        return command

    def reconcile_interruption(self, now: Optional[float] = None):
        """One spot-interruption cycle: poll the provider for notices,
        start drain-after-replace commands, progress the queue and
        termination, and rebind displaced/pending pods."""
        self._advance(now)
        commands = self.interruption.reconcile(now=now)
        for command in commands:
            if command.results is not None:
                self.bind_results(command.results)
        self.lifecycle.reconcile_all(now=now)
        self.cloud.tick(now=now)
        self.lifecycle.reconcile_all(now=now)
        self.disruption.queue.reconcile(now=now)
        self.reconcile_termination(now=now)
        if self.provisioner.get_pending_pods():
            self.provision(now=now)
        return commands

    def all_pods_bound(self) -> bool:
        return all(
            p.spec.node_name for p in self.kube.pods() if not p.is_terminal()
        )

    def reconcile_termination(self, now: Optional[float] = None, rounds: int = 4) -> None:
        """Drive claim finalize -> node drain -> instance delete to
        quiescence (each controller pass handles one stage)."""
        self._advance(now)
        for _ in range(rounds):
            self.lifecycle.reconcile_all(now=now)
            self.termination.reconcile_all(now=now)

    # -- expectation DSL ------------------------------------------------------

    def provision(self, *pods: Pod, bind: bool = True, now: Optional[float] = None
                  ) -> SchedulerResults:
        """ExpectProvisioned (expectations.go:299): create pods, run a
        provisioning cycle, launch claims through the lifecycle, tick
        the simulated cloud, register/initialize nodes, and bind pods
        to their planned nodes."""
        self._advance(now)
        for pod in pods:
            if self.kube.get_pod(pod.metadata.namespace, pod.metadata.name) is None:
                self.kube.create(pod)
        results = self.provisioner.reconcile(now=now)
        self.lifecycle.reconcile_all(now=now)
        self.cloud.tick(now=now)
        self.lifecycle.reconcile_all(now=now)
        if bind:
            self.bind_results(results)
        return results

    def bind_results(self, results: SchedulerResults) -> None:
        """Simulate kube-scheduler binding pods to their target nodes."""
        for plan in results.new_node_plans:
            if not plan.claim_name:
                continue
            claim = self.kube.get_node_claim(plan.claim_name)
            if claim is None or not claim.status.node_name:
                continue
            for pod in plan.pods:
                live = self.kube.get_pod(pod.metadata.namespace, pod.metadata.name)
                if live is not None and not live.spec.node_name:
                    self.kube.bind_pod(live, claim.status.node_name)
        for node_name, pods in results.existing_assignments.items():
            state = self.cluster.node_for_name(node_name)
            target = state.name if state is not None else ""
            if not target:
                # an in-flight assignment is keyed by claim name; by
                # bind time the tick may have materialized its node.
                # If it still hasn't, the pods stay pending and the
                # next round re-plans them (the reference leaves
                # binding to kube-scheduler once the node is Ready).
                claim = self.kube.get_node_claim(node_name)
                target = claim.status.node_name if claim is not None else ""
                if not target and claim is None:
                    # plain existing node — but only if it actually
                    # exists; a dead claim's key must leave the pods
                    # pending for re-planning, never pin them to a
                    # name that will not materialize
                    if any(
                        n.metadata.name == node_name
                        for n in self.kube.nodes()
                    ):
                        target = node_name
                if not target:
                    continue
            for pod in pods:
                live = self.kube.get_pod(pod.metadata.namespace, pod.metadata.name)
                if live is not None and not live.spec.node_name:
                    self.kube.bind_pod(live, target)

    def initialized_nodes(self) -> list:
        return [
            n for n in self.kube.nodes()
            if n.metadata.labels.get("karpenter.sh/initialized") == "true"
        ]


# -- live-churn harness (ISSUE 7) ------------------------------------------
#
# One fixture shared by tests/test_perf_floor.py and bench.py's
# steady_state_churn live_operator arm, so the perf guard and the bench
# measure the SAME workload: a settled Operator over a FULL fleet of
# 4x 0.9-cpu pods per 4-cpu node (allocatable 3.9 after kube-reserved,
# so a fifth pod can never fit) where churn pods can only land in the
# slots their deleted predecessors freed.

def build_churn_operator(n_pods: int):
    """Provision `n_pods` steady pods, settle a real Operator over the
    fleet, and return (env, operator, synthetic_now) ready for
    `churn_tick_walls`."""
    import time

    from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
    from karpenter_tpu.operator.operator import Operator
    from karpenter_tpu.operator.options import Options

    types = [make_instance_type("c4", cpu=4, memory=16 * GIB, price=1.0)]
    env = Environment(types=types)
    pool = mk_nodepool("churn")
    pool.spec.disruption.consolidate_after = "Never"
    env.kube.create(pool)
    env.provision(
        *[mk_pod(name=f"s-{i}", cpu=0.9, memory=2 * GIB)
          for i in range(n_pods)]
    )
    op = Operator(kube=env.kube, cloud_provider=env.cloud,
                  options=Options())
    now = time.time()
    for i in range(3):   # settle: recovery, cache warmup, residual dirt
        op.step(now=now + i * 2.0)
    return env, op, now + 10.0


def churn_tick_walls(env, op, now: float, ticks: int, churn_pods: int):
    """Per-tick wall of the operator step that runs the churn solve.
    Returns (p50_wall_seconds, now)."""
    walls, now = churn_tick_wall_series(env, op, now, ticks, churn_pods)
    return sorted(walls)[len(walls) // 2], now


def churn_tick_wall_series(env, op, now: float, ticks: int,
                           churn_pods: int):
    """Per-tick wall series of the operator step that runs the churn
    solve: each tick deletes `churn_pods` bound pods, creates as many
    same-shape ones, and measures the step where the batcher fires.
    Returns (walls, now) — callers pick their own percentiles (the
    100k bench arm reports p50 AND p99)."""
    import time

    from karpenter_tpu.cloudprovider.fake import GIB

    walls = []
    counter = 0
    for t in range(ticks):
        bound = sorted(
            (p for p in env.kube.pods() if p.spec.node_name),
            key=lambda p: p.metadata.name,
        )
        for pod in bound[:churn_pods]:
            env.kube.delete(pod)
        for _ in range(churn_pods):
            counter += 1
            env.kube.create(mk_pod(name=f"churn-{t}-{counter}", cpu=0.9,
                                   memory=2 * GIB))
        # the batcher keys off wall-clock event arrival while the
        # harness ticks synthetic time already offset past the idle
        # window, so the FIRST step after churn runs the solve
        now += 2.0
        t0 = time.perf_counter()
        op.step(now=now)
        walls.append(time.perf_counter() - t0)
        now += 2.0
        op.step(now=now)   # bind/settle
    return walls, now


def disruption_scan_walls(env, op, now: float, scans: int,
                          churn_pods: int):
    """Per-scan wall of one full disruption candidate scan — the
    engine's `get_candidates` pass plus the fleet snapshot a
    simulation would consume — with `churn_pods` pods churned between
    scans so a fraction of the retained rows goes dirty each round
    (the ISSUE-15 'dirty scan is O(changed nodes)' claim). Returns
    (p50_wall_seconds, now). Shares the build_churn_operator fixture
    so the bench arm and any perf guard measure ONE workload.

    A permissive match-all PodDisruptionBudget is installed first:
    production fleets carry PDBs, and the per-pod eviction-budget
    derivation (PdbLimits.can_evict walks the namespace's pod
    population per selecting PDB) is exactly the per-scan cost the
    retained candidate cores amortize — a PDB-free fixture would
    measure only the cheap residue."""
    import time

    from karpenter_tpu.apis.v1.nodepool import REASON_UNDERUTILIZED
    from karpenter_tpu.cloudprovider.fake import GIB
    from karpenter_tpu.kube.objects import (
        LabelSelector,
        PodDisruptionBudget,
        PodDisruptionBudgetSpec,
    )

    if not env.kube.pdbs():
        env.kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="scan-pdb"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector.of({}),
                max_unavailable="50%",
            ),
        ))
    # the churn fixture pins consolidate_after=Never (stable tick
    # walls); the SCAN measurement needs consolidatable candidates,
    # so stamp the condition directly — get_candidates reads claim
    # conditions live, and no operator step runs during the
    # measurement to clear them
    from karpenter_tpu.apis.v1.nodeclaim import COND_CONSOLIDATABLE

    for claim in op.kube.node_claims():
        claim.status_conditions.set_true(COND_CONSOLIDATABLE, now=now)
        op.kube.touch(claim)
    now += 60.0   # past every nomination window
    walls = []
    counter = 0
    for t in range(scans):
        # churn WITHOUT the operator: delete a few bound pods and bind
        # same-shape replacements straight onto the freed nodes — the
        # delete/bind events dirty exactly those nodes, which is the
        # 'dirty scan is O(changed nodes)' condition under test
        bound = sorted(
            (p for p in op.kube.pods() if p.spec.node_name),
            key=lambda p: p.metadata.name,
        )
        for pod in bound[:churn_pods]:
            target = pod.spec.node_name
            op.kube.delete(pod)
            counter += 1
            fresh = mk_pod(name=f"scan-{t}-{counter}", cpu=0.9,
                           memory=2 * GIB)
            op.kube.create(fresh)
            op.kube.bind_pod(fresh, target)
        t0 = time.perf_counter()
        op.disruption.get_candidates(REASON_UNDERUTILIZED, now)
        op.disruption.fleet_seam.fleet_snapshot()
        walls.append(time.perf_counter() - t0)
        now += 2.0
    return sorted(walls)[len(walls) // 2], now
