"""PodDisruptionBudget limits.

Counterpart of pkg/utils/pdb (506 LoC): map pods to the PDBs selecting
them and answer "can this pod be evicted right now" / "is this node's
pod set disruptable".
"""

from __future__ import annotations

import math
from typing import Optional

from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import Pod, PodDisruptionBudget


def _scaled(value: int | str, total: int, round_up: bool) -> int:
    if isinstance(value, int):
        return value
    if value.endswith("%"):
        pct = int(value[:-1])
        scaled = pct * total / 100.0
        return math.ceil(scaled) if round_up else math.floor(scaled)
    return int(value)


class PdbLimits:
    def __init__(self, kube: KubeClient, memoize_allowance: bool = False):
        """`memoize_allowance`: cache disruptions_allowed per PDB for
        this instance's lifetime. ONLY safe for read-only passes over
        a fixed pod population (the disruption candidate scan, which
        constructs a fresh instance per scan and evicts nothing while
        it runs) — eviction loops must keep the default so each
        verdict sees the shrinking budget."""
        self.kube = kube
        self.pdbs = kube.pdbs()
        self._allowance_cache: dict = {} if memoize_allowance else None

    def _matching(self, pod: Pod) -> list[PodDisruptionBudget]:
        return [
            pdb
            for pdb in self.pdbs
            if pdb.metadata.namespace == pod.metadata.namespace
            and pdb.spec.selector.matches(pod.metadata.labels)
        ]

    def matching(self, pod: Pod) -> list[PodDisruptionBudget]:
        """The PDBs selecting this pod — public for callers that plan
        multi-victim evictions (preemption) and must budget a WHOLE
        victim set against each selecting PDB, not just the first
        victim (can_evict is point-in-time per pod)."""
        return self._matching(pod)

    def disruptions_allowed(self, pdb: PodDisruptionBudget) -> int:
        """Compute allowed disruptions from live pod state (the real
        controller-manager maintains status; we derive it)."""
        if self._allowance_cache is not None:
            hit = self._allowance_cache.get(pdb.key)
            if hit is not None:
                return hit
        out = self._disruptions_allowed(pdb)
        if self._allowance_cache is not None:
            self._allowance_cache[pdb.key] = out
        return out

    def _disruptions_allowed(self, pdb: PodDisruptionBudget) -> int:
        pods = [
            p
            for p in self.kube.pods(namespace=pdb.metadata.namespace,
                                    selector=pdb.spec.selector)
            if not p.is_terminal()
        ]
        total = len(pods)
        healthy = sum(1 for p in pods if p.spec.node_name and not p.is_terminating())
        if pdb.spec.max_unavailable is not None:
            max_unavailable = _scaled(pdb.spec.max_unavailable, total, round_up=False)
            unavailable = total - healthy
            return max(0, max_unavailable - unavailable)
        if pdb.spec.min_available is not None:
            min_available = _scaled(pdb.spec.min_available, total, round_up=True)
            return max(0, healthy - min_available)
        return total

    @staticmethod
    def _evictable(pod: Pod, server_side: bool = False) -> bool:
        """pdb.go isEvictable gate via pod.IsEvictable
        (utils/pod/scheduling.go:56-61): pods karpenter will never
        call the eviction API on bypass PDB math entirely — terminal/
        terminating pods, mirror pods (Node-owned), pods tolerating the
        disrupted taint (they ride the node down), and do-not-disrupt
        pods (blocked earlier, by the annotation check).

        `server_side` models the API SERVER's view on the eviction
        subresource instead: it knows nothing of karpenter annotations
        or taints, so only terminal/terminating and mirror pods bypass
        the budget there."""
        from karpenter_tpu.apis.v1.labels import (
            DISRUPTED_NO_SCHEDULE_TAINT,
            DO_NOT_DISRUPT_ANNOTATION,
        )

        if pod.is_terminal() or pod.is_terminating():
            return False
        if pod.owner_kind() == "Node":
            return False
        if server_side:
            return True
        if pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION) == "true":
            return False
        from karpenter_tpu.scheduling.taints import tolerates_pod

        return tolerates_pod([DISRUPTED_NO_SCHEDULE_TAINT], pod) is not None

    def can_evict(self, pod: Pod, server_side: bool = False) -> Optional[str]:
        """None if eviction is permitted, else the blocking PDB
        name(s). Kubernetes refuses eviction outright when MULTIPLE
        PDBs select one pod (eviction.go:L226 upstream), budgets
        notwithstanding — pdb.go:98-103."""
        if not self._evictable(pod, server_side=server_side):
            return None
        matching = self._matching(pod)
        if len(matching) > 1:
            return ",".join(sorted(p.key for p in matching))
        for pdb in matching:
            if self.disruptions_allowed(pdb) <= 0:
                return pdb.key
        return None

