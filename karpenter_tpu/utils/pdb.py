"""PodDisruptionBudget limits.

Counterpart of pkg/utils/pdb (506 LoC): map pods to the PDBs selecting
them and answer "can this pod be evicted right now" / "is this node's
pod set disruptable".
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import Pod, PodDisruptionBudget


def _scaled(value: int | str, total: int, round_up: bool) -> int:
    if isinstance(value, int):
        return value
    if value.endswith("%"):
        pct = int(value[:-1])
        scaled = pct * total / 100.0
        return math.ceil(scaled) if round_up else math.floor(scaled)
    return int(value)


class PdbLimits:
    def __init__(self, kube: KubeClient):
        self.kube = kube
        self.pdbs = kube.pdbs()

    def _matching(self, pod: Pod) -> list[PodDisruptionBudget]:
        return [
            pdb
            for pdb in self.pdbs
            if pdb.metadata.namespace == pod.metadata.namespace
            and pdb.spec.selector.matches(pod.metadata.labels)
        ]

    def disruptions_allowed(self, pdb: PodDisruptionBudget) -> int:
        """Compute allowed disruptions from live pod state (the real
        controller-manager maintains status; we derive it)."""
        pods = [
            p
            for p in self.kube.pods(namespace=pdb.metadata.namespace,
                                    selector=pdb.spec.selector)
            if not p.is_terminal()
        ]
        total = len(pods)
        healthy = sum(1 for p in pods if p.spec.node_name and not p.is_terminating())
        if pdb.spec.max_unavailable is not None:
            max_unavailable = _scaled(pdb.spec.max_unavailable, total, round_up=False)
            unavailable = total - healthy
            return max(0, max_unavailable - unavailable)
        if pdb.spec.min_available is not None:
            min_available = _scaled(pdb.spec.min_available, total, round_up=True)
            return max(0, healthy - min_available)
        return total

    def can_evict(self, pod: Pod) -> Optional[str]:
        """None if eviction is permitted, else the blocking PDB name."""
        for pdb in self._matching(pod):
            if self.disruptions_allowed(pdb) <= 0:
                return pdb.key
        return None

    def blocking_pdbs(self, pods: Sequence[Pod]) -> dict[str, str]:
        """pod key -> blocking pdb key for every blocked pod."""
        out = {}
        for pod in pods:
            blocked = self.can_evict(pod)
            if blocked is not None:
                out[pod.key] = blocked
        return out
