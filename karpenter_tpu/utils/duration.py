"""Duration and cron-schedule helpers.

`parse_duration` accepts Go-style duration strings ("30s", "5m", "1h",
"1h30m") plus the CRD sentinel "Never" (returns None). `CronSchedule`
is a minimal 5-field cron matcher covering the reference's
NodePool.Budget schedule windows (robfig/cron semantics for the subset
used: numbers, ranges, steps, lists, `*`, and @hourly/@daily/@weekly/
@monthly/@yearly shortcuts).
"""

from __future__ import annotations

import re
import time as _time
from dataclasses import dataclass
from typing import Optional

_DUR_RE = re.compile(r"([0-9]*\.?[0-9]+)(ns|us|ms|s|m|h|d)")
_UNITS = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(value: str | int | float | None) -> Optional[float]:
    """Duration string -> seconds; "Never"/None -> None."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if value == "Never" or value == "":
        return None
    pos = 0
    total = 0.0
    for match in _DUR_RE.finditer(value):
        if match.start() != pos:
            raise ValueError(f"invalid duration {value!r}")
        total += float(match.group(1)) * _UNITS[match.group(2)]
        pos = match.end()
    if pos != len(value):
        raise ValueError(f"invalid duration {value!r}")
    return total


def format_duration(seconds: Optional[float]) -> str:
    if seconds is None:
        return "Never"
    if seconds >= 3600 and seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds >= 60 and seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


_SHORTCUTS = {
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
}

_DOW_NAMES = {"sun": 0, "mon": 1, "tue": 2, "wed": 3, "thu": 4, "fri": 5, "sat": 6}
_MON_NAMES = {
    "jan": 1, "feb": 2, "mar": 3, "apr": 4, "may": 5, "jun": 6,
    "jul": 7, "aug": 8, "sep": 9, "oct": 10, "nov": 11, "dec": 12,
}


def _parse_field(field: str, lo: int, hi: int, names: dict[str, int]) -> set[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start = names.get(a.lower(), None) if not a.isdigit() else int(a)
            end = names.get(b.lower(), None) if not b.isdigit() else int(b)
            if start is None or end is None:
                raise ValueError(f"bad cron field {part!r}")
        else:
            val = names.get(part.lower()) if not part.isdigit() else int(part)
            if val is None:
                raise ValueError(f"bad cron field {part!r}")
            start = end = val
        out.update(range(start, end + 1, step))
    return out


@dataclass
class CronSchedule:
    minutes: set[int]
    hours: set[int]
    days: set[int]
    months: set[int]
    weekdays: set[int]

    @classmethod
    def parse(cls, expr: str) -> "CronSchedule":
        expr = _SHORTCUTS.get(expr.strip(), expr.strip())
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron expression must have 5 fields: {expr!r}")
        return cls(
            minutes=_parse_field(fields[0], 0, 59, {}),
            hours=_parse_field(fields[1], 0, 23, {}),
            days=_parse_field(fields[2], 1, 31, {}),
            months=_parse_field(fields[3], 1, 12, _MON_NAMES),
            weekdays=_parse_field(fields[4], 0, 6, _DOW_NAMES),
        )

    def matches(self, ts: float) -> bool:
        tm = _time.gmtime(ts)
        weekday = (tm.tm_wday + 1) % 7  # go Sunday=0
        return (
            tm.tm_min in self.minutes
            and tm.tm_hour in self.hours
            and tm.tm_mday in self.days
            and tm.tm_mon in self.months
            and weekday in self.weekdays
        )

    def last_fire_before(self, ts: float) -> Optional[float]:
        """Most recent minute boundary <= ts matching the schedule.

        Scans back minute-by-minute bounded to 366 days (cron has at
        least one match per year for valid expressions we accept).
        """
        minute = int(ts // 60) * 60
        for _ in range(366 * 24 * 60):
            if self.matches(minute):
                return float(minute)
            minute -= 60
        return None
