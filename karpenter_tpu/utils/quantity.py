"""Kubernetes resource-quantity parsing and arithmetic.

Replaces apimachinery's `resource.Quantity` (used throughout the
reference, e.g. pkg/utils/resources/resources.go) with plain floats in
canonical units: cpu is measured in cores (float), memory/storage in
bytes (float), everything else in counts. Parsing accepts the k8s
suffix grammar ("100m", "1536Mi", "2Gi", "1e3", plain ints).
"""

from __future__ import annotations

import math
import re

_DECIMAL_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}
_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}

_QUANTITY_RE = re.compile(
    r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*"
    r"(n|u|m|k|M|G|T|P|E|Ki|Mi|Gi|Ti|Pi|Ei)?\s*$"
)


def parse_quantity(value: str | int | float) -> float:
    """Parse a k8s quantity string into a float in canonical units."""
    if isinstance(value, (int, float)):
        return float(value)
    match = _QUANTITY_RE.match(value)
    if match is None:
        raise ValueError(f"invalid quantity {value!r}")
    number, suffix = match.groups()
    suffix = suffix or ""
    scale = _BINARY_SUFFIXES.get(suffix) or _DECIMAL_SUFFIXES[suffix]
    return float(number) * scale


def format_quantity(value: float) -> str:
    """Render a canonical float back to a compact k8s-style string."""
    if value == 0:
        return "0"
    if value == int(value):
        intval = int(value)
        for suffix, scale in (("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
            if intval % scale == 0 and intval >= scale:
                return f"{intval // scale}{suffix}"
        return str(intval)
    milli = value * 1000
    if math.isclose(milli, round(milli)):
        return f"{round(milli)}m"
    return repr(value)
