"""Wall-clock profiler — the pprof-harness analogue.

The reference mounts net/http/pprof behind --enable-profiling
(operator.go:183-199) and its benchmark harness emits cpu/heap
profiles (scheduling_benchmark_test.go:114-160). This build's hot path
is a compiled XLA program (profiled via jax.profiler when needed), so
the operator-level equivalent is a label -> latency-histogram tracer:
cheap enough to leave on, queryable like a /debug/pprof summary, and
driving the per-controller step timings the operator exposes.

Backed by the ONE histogram implementation (metrics/store.Histogram) —
each Profiler keeps a private instance for its report(), and every
observation is mirrored into the shared registry series
`karpenter_operator_step_duration_seconds{step=...}`, so per-
controller step latencies land on /metrics, not just in report().
"""

from __future__ import annotations

import time
from contextlib import contextmanager

from karpenter_tpu.metrics.store import REGISTRY, Histogram

# fixed latency bucket edges (seconds); overflow rides the histogram's
# implicit +Inf (total - sum(buckets)) — a span slower than the
# largest edge must never masquerade as <= that edge
BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0)
_BUCKET_LABELS = tuple(f"le_{b}" for b in BUCKETS) + ("le_inf",)

# the registry-exported view: one series per (profiler step label),
# scraped from /metrics like every other karpenter_* histogram
STEP_DURATION = REGISTRY.histogram(
    "karpenter_operator_step_duration_seconds",
    "Per-controller step wall clock from the operator profiler, by "
    "step label (the /debug/profile report's backing series)",
    buckets=BUCKETS)


class Profiler:
    """Label -> wall-clock histogram with nesting support."""

    def __init__(self, enabled: bool = True, clock=None):
        self.enabled = enabled
        self.clock = clock if clock is not None else time.perf_counter
        # private store.Histogram: report() must describe THIS
        # profiler's spans, while the shared registry series (also
        # observed below) aggregates across the process
        self._hist = Histogram("profiler", buckets=BUCKETS)
        self._max: dict[str, float] = {}

    @contextmanager
    def span(self, label: str):
        if not self.enabled:
            yield
            return
        start = self.clock()
        try:
            yield
        finally:
            self.record(label, self.clock() - start)

    def record(self, label: str, seconds: float) -> None:
        if not self.enabled:
            return
        labels = {"step": label}
        self._hist.observe(seconds, labels)
        STEP_DURATION.observe(seconds, labels)
        if seconds > self._max.get(label, 0.0):
            self._max[label] = seconds

    def report(self) -> dict[str, dict]:
        """The /debug/pprof-style summary: per label, call count, mean,
        max and bucketed latency counts."""
        out: dict[str, dict] = {}
        for pairs, counts, total_s, count in self._hist.samples():
            label = dict(pairs)["step"]
            buckets = list(counts) + [count - sum(counts)]
            out[label] = {
                "count": count,
                "mean_s": round(total_s / count, 6) if count else 0.0,
                "total_s": round(total_s, 6),
                "max_s": round(self._max.get(label, 0.0), 6),
                "buckets": dict(zip(_BUCKET_LABELS, buckets)),
            }
        return dict(sorted(out.items()))

    def reset(self) -> None:
        self._hist = Histogram("profiler", buckets=BUCKETS)
        self._max.clear()
