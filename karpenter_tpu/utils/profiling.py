"""Wall-clock profiler — the pprof-harness analogue.

The reference mounts net/http/pprof behind --enable-profiling
(operator.go:183-199) and its benchmark harness emits cpu/heap
profiles (scheduling_benchmark_test.go:114-160). This build's hot path
is a compiled XLA program (profiled via jax.profiler when needed), so
the operator-level equivalent is a label -> latency-histogram tracer:
cheap enough to leave on, queryable like a /debug/pprof summary, and
driving the per-controller step timings the operator exposes.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field


# fixed latency bucket edges (seconds) + an explicit +Inf overflow,
# prometheus-histogram style — a span slower than the largest edge
# must never masquerade as <= that edge
BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0)
_BUCKET_LABELS = tuple(f"le_{b}" for b in BUCKETS) + ("le_inf",)


@dataclass
class _Series:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    buckets: list[int] = field(
        default_factory=lambda: [0] * (len(BUCKETS) + 1)
    )


class Profiler:
    """Label -> wall-clock histogram with nesting support."""

    def __init__(self, enabled: bool = True, clock=None):
        self.enabled = enabled
        self.clock = clock if clock is not None else time.perf_counter
        self._series: dict[str, _Series] = {}

    @contextmanager
    def span(self, label: str):
        if not self.enabled:
            yield
            return
        start = self.clock()
        try:
            yield
        finally:
            self.record(label, self.clock() - start)

    def record(self, label: str, seconds: float) -> None:
        if not self.enabled:
            return
        series = self._series.setdefault(label, _Series())
        series.count += 1
        series.total_s += seconds
        series.max_s = max(series.max_s, seconds)
        for i, edge in enumerate(BUCKETS):
            if seconds <= edge:
                series.buckets[i] += 1
                break
        else:
            series.buckets[-1] += 1  # the +Inf overflow bucket

    def report(self) -> dict[str, dict]:
        """The /debug/pprof-style summary: per label, call count, mean,
        max and bucketed latency counts."""
        return {
            label: {
                "count": s.count,
                "mean_s": round(s.total_s / s.count, 6) if s.count else 0.0,
                "total_s": round(s.total_s, 6),
                "max_s": round(s.max_s, 6),
                "buckets": dict(zip(_BUCKET_LABELS, s.buckets)),
            }
            for label, s in sorted(self._series.items())
        }

    def reset(self) -> None:
        self._series.clear()
