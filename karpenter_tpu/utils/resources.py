"""Resource-list arithmetic over `dict[str, float]` resource maps.

Counterpart of the reference's pkg/utils/resources/resources.go (822
LoC of Quantity arithmetic): merge/subtract/fits over resource lists,
and pod-request aggregation including init-container max semantics and
pod-overhead (resources.go RequestsForPods / Ceiling semantics).
"""

from __future__ import annotations

from typing import Iterable, Mapping, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.kube.objects import Pod

CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

ResourceList = dict[str, float]


def merge(*lists: Mapping[str, float]) -> ResourceList:
    """Sum resource lists key-wise."""
    out: ResourceList = {}
    for rl in lists:
        for key, value in rl.items():
            out[key] = out.get(key, 0.0) + value
    return out


def subtract(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    """a - b key-wise; keys only in b appear negated."""
    out: ResourceList = dict(a)
    for key, value in b.items():
        out[key] = out.get(key, 0.0) - value
    return out


def max_resources(*lists: Mapping[str, float]) -> ResourceList:
    """Key-wise maximum (reference MaxResources)."""
    out: ResourceList = {}
    for rl in lists:
        for key, value in rl.items():
            if value > out.get(key, float("-inf")):
                out[key] = value
    return out


def fits(candidate: Mapping[str, float], total: Mapping[str, float]) -> bool:
    """True if every requested resource is available in `total`.

    Mirrors resources.Fits: a resource requested but absent from the
    total is only OK when the request is zero.
    """
    for key, value in candidate.items():
        if value > total.get(key, 0.0) + 1e-9:
            return False
    return True


def fits_declared(candidate: Mapping[str, float], total: Mapping[str, float]) -> bool:
    """`fits` with leniency for undeclared EXTENDED resources only.

    Providers materializing a claim check size against the *raw*
    catalog; extended resources the raw type doesn't declare may be
    legitimately injected at scheduling time (NodeOverlay capacity, or
    a device plugin on the real node) and must not fail the launch.
    Core resources (cpu/memory/pods/ephemeral-storage) can never be
    injected that way — a type that doesn't declare them cannot run
    the pods, so they stay strict to catch solver/claim sizing bugs."""
    core = (CPU, MEMORY, PODS, EPHEMERAL_STORAGE)
    for key, value in candidate.items():
        if key in total:
            if value > total[key] + 1e-9:
                return False
        elif key in core and value > 1e-9:
            return False
    return True


def is_zero(rl: Mapping[str, float]) -> bool:
    return all(abs(v) < 1e-9 for v in rl.values())


def positive(rl: Mapping[str, float]) -> ResourceList:
    """Clamp all values to >= 0 and drop zero entries."""
    return {k: v for k, v in rl.items() if v > 1e-9}


def pod_requests(pod: "Pod") -> ResourceList:
    """Effective pod resource requests.

    k8s semantics (mirrored from resources.PodRequests, which defers
    to k8s resource helpers):

    - pod-level resources, when set, replace container aggregation
      (PodLevelResources feature; suite_test.go:684);
    - otherwise: walk init containers in order, where a RESTARTABLE
      init container (restartPolicy=Always — a native sidecar) keeps
      its requests for the pod's whole life and stacks under every
      later init container and the main containers, while a regular
      init container only peaks during its own run
      (suite_test.go:531-683 sidecar families);
    - plus pod overhead, plus one implicit "pods" unit.
    """
    sidecar_sum: ResourceList = {}
    init_peak: ResourceList = {}
    for c in pod.spec.init_containers:
        if c.restart_policy == "Always":
            sidecar_sum = merge(sidecar_sum, c.requests)
        else:
            init_peak = max_resources(
                init_peak, merge(sidecar_sum, c.requests)
            )
    main = merge(
        sidecar_sum, *(c.requests for c in pod.spec.containers)
    )
    out = max_resources(main, init_peak)
    if pod.spec.resources:
        # pod-level values override aggregation ONLY for the resources
        # k8s supports at pod level (cpu/memory/hugepages); extended
        # resources and everything else stay container-aggregated
        for key, value in pod.spec.resources.items():
            if key in (CPU, MEMORY) or key.startswith("hugepages-"):
                out[key] = value
    if pod.spec.overhead:
        out = merge(out, pod.spec.overhead)
    out[PODS] = out.get(PODS, 0.0) + 1.0
    return out


def requests_for_pods(pods: Iterable["Pod"]) -> ResourceList:
    return merge(*(pod_requests(p) for p in pods))
