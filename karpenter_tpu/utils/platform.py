"""JAX platform pinning for the single-tenant TPU environment.

The ambient environment points JAX at one real TPU chip behind a
tunnel (`JAX_PLATFORMS=axon`) and a site hook overwrites the
`jax_platforms` *config* at interpreter startup, so exporting the env
var alone doesn't stick — the config must be updated directly before
any backend initializes. Tests, the multichip dryrun, and the bench's
fallback path all need the same recipe; keep it in one place.
"""

from __future__ import annotations

import os
import re


def force_cpu_mesh(n_devices: int = 0) -> None:
    """Pin JAX to the CPU platform, optionally with `n_devices` virtual
    devices for sharding tests. Must be called before the first JAX
    backend touch in the process; raises if a non-CPU backend already
    initialized (too late to repin).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m:
            if int(m.group(1)) < n_devices:
                flags = flags.replace(
                    m.group(0),
                    f"--xla_force_host_platform_device_count={n_devices}",
                )
                os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    if backend != "cpu":
        raise RuntimeError(
            f"backend {backend!r} initialized before force_cpu_mesh() — "
            "too late to repin; call it before any JAX backend touch"
        )
    if n_devices:
        have = len(jax.devices())
        if have < n_devices:
            raise RuntimeError(
                f"need {n_devices} cpu devices, have {have} — a backend "
                "initialized before force_cpu_mesh() could set XLA_FLAGS"
            )
