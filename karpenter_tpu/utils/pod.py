"""Pod scheduling predicates.

Counterpart of pkg/utils/pod/scheduling.go (the slice the rest of the
repo doesn't already cover inline): Dynamic Resource Allocation
detection, pod/scheduling.go:211-224.
"""

from __future__ import annotations

from karpenter_tpu.kube.objects import Pod


def has_dra_requirements(pod: Pod) -> bool:
    """True if any container (init or main) consumes a ResourceClaim.

    Karpenter cannot simulate DRA device allocation, so such pods are
    gated out of scheduling with a permanent error while the
    ignore-dra-requests flag is on (scheduler.go:484-491).
    """
    return any(
        c.resource_claims
        for c in list(pod.spec.init_containers) + list(pod.spec.containers)
    )
