"""Shared retry-backoff math: capped exponential windows and the
desynchronizing jitter factor.

Every retry site in the control plane (eviction 429s, watch
reconnects, nodeclaim launch failures, solver-service and resilience
breakers) backs off through these two primitives, so the jitter band
and cap semantics can never silently diverge between sites — the
failure mode that makes a fleet retry in lockstep again one audit
later.
"""

from __future__ import annotations

import random as _random
from typing import Optional


def jitter(rng: Optional[_random.Random] = None) -> float:
    """Desynchronizing multiplier in [0.5, 1.0): cuts the window by at
    most half (so backoff stays a backoff) while spreading a cohort
    tripped by the same event across half the window."""
    return 0.5 + 0.5 * (rng or _random).random()


def full_jitter(window: float, rng: Optional[_random.Random] = None) -> float:
    """Full-jitter wait in [0, window): the AWS-architecture-blog
    variant for API-server retry storms, where spreading a throttled
    cohort across the WHOLE window (including ~0) empties the server's
    queue fastest. Use `jitter` instead when the wait must remain a
    lower-bounded backoff (breaker cooldowns, reconnects)."""
    return window * (rng or _random).random()


def capped_exponential(
    attempts: int, base: float, cap: float, max_exp: int = 16
) -> float:
    """The n-th (1-based) consecutive failure's backoff window:
    base * 2^(n-1), saturating at `cap` (exponent clamped long before
    float overflow)."""
    return min(cap, base * 2 ** min(max(attempts - 1, 0), max_exp))
