"""Conflict/throttle-aware retry policy for kube API requests.

Counterpart of client-go's rest.Request retry + controller-runtime's
RetryOnConflict: the reference controllers never see a transient 429
or a racy 409 — the client machinery re-reads and re-applies under
bounded backoff, and only a persistent failure surfaces. RealKubeClient
funnels every transport request through `RetryPolicy.execute` so this
module is the ONE place that decides what retries, how long, and with
what jitter (tests/test_kube_write_sites.py statically enforces the
funnel).

Semantics per status:

- 409 Conflict   -> the caller's `on_conflict` hook runs (targeted
                    re-GET + read-modify-write re-apply of the caller's
                    mutation), then the request retries. No hook, or a
                    hook returning False, makes the 409 terminal —
                    create-conflicts ("already exists") are semantic,
                    not transient.
- 429 TooManyRequests -> honored Retry-After (Status
                    details.retryAfterSeconds, where a real apiserver
                    puts it) combined with full-jitter exponential
                    backoff. A PDB-blocked eviction also answers 429
                    but with a DisruptionBudget cause: that one is a
                    policy decision owned by the eviction backoff
                    queue, never retried here.
- 5xx            -> full-jitter exponential backoff and retry (an
                    apiserver riding out an etcd leader election).
- anything else  -> returned to the caller unchanged.

Every retry burns from a per-call wall budget
(KARPENTER_KUBE_RETRY_BUDGET_MS): a throttled API server degrades the
tick (the last response surfaces and the controller requeues) instead
of wedging it.

Knobs (read per call, so tests can flip them without rebuilding
clients):

    KARPENTER_KUBE_RETRY_MAX        attempts per request   (default 5)
    KARPENTER_KUBE_RETRY_BASE_MS    first backoff window   (default 25)
    KARPENTER_KUBE_RETRY_CAP_MS     window cap             (default 1000)
    KARPENTER_KUBE_RETRY_BUDGET_MS  wall budget per call   (default 5000)
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Callable, Optional

from karpenter_tpu.metrics.store import KUBE_RETRIES
from karpenter_tpu.utils.backoff import capped_exponential, full_jitter

log = logging.getLogger("karpenter.kube.retry")

Attempt = Callable[[], tuple[int, dict]]

_ENV_DEFAULTS = (
    ("KARPENTER_KUBE_RETRY_MAX", 5.0),
    ("KARPENTER_KUBE_RETRY_BASE_MS", 25.0),
    ("KARPENTER_KUBE_RETRY_CAP_MS", 1000.0),
    ("KARPENTER_KUBE_RETRY_BUDGET_MS", 5000.0),
)


def _parse_env(raw: tuple) -> tuple[float, ...]:
    out = []
    for value, (_, default) in zip(raw, _ENV_DEFAULTS):
        try:
            out.append(float(value) if value else default)
        except ValueError:
            out.append(default)
    return tuple(out)


# Freshness probe for the policy cache. This runs on EVERY kube
# request (the <5% healthy-path guard), and os.environ.get pays
# ~1.3us/key in codec wrappers — on POSIX, read the raw bytes->bytes
# backing dict instead (~0.1us/key); values only need decoding on an
# actual cache miss.
try:
    _RAW_ENV = os.environ._data  # type: ignore[attr-defined]
    # encodekey is what _Environ.__getitem__ itself applies (bytes on
    # POSIX, upcased str on Windows) — hand-encoding would silently
    # miss every knob on str-keyed platforms
    _RAW_KEYS = tuple(
        os.environ.encodekey(key)  # type: ignore[attr-defined]
        for key, _ in _ENV_DEFAULTS
    )

    def _probe_env() -> tuple:
        get = _RAW_ENV.get
        return (get(_RAW_KEYS[0]), get(_RAW_KEYS[1]),
                get(_RAW_KEYS[2]), get(_RAW_KEYS[3]))

    def _decode_probe(raw: tuple) -> tuple:
        return tuple(
            v.decode(errors="replace") if isinstance(v, bytes) else v
            for v in raw
        )
except AttributeError:  # non-POSIX / exotic environ: plain reads
    def _probe_env() -> tuple:
        get = os.environ.get
        return tuple(get(key) for key, _ in _ENV_DEFAULTS)

    def _decode_probe(raw: tuple) -> tuple:
        return raw


def retry_after_seconds(body: dict) -> float:
    """Retry-After as a real apiserver ships it: Status
    details.retryAfterSeconds (HTTPTransport also folds the header in
    there)."""
    try:
        return float((body.get("details") or {}).get("retryAfterSeconds", 0))
    except (TypeError, ValueError):
        return 0.0


def is_pdb_eviction_block(body: dict) -> bool:
    """A 429 from the eviction subresource whose cause is a
    DisruptionBudget: policy, not load — the eviction queue owns its
    backoff."""
    causes = (body.get("details") or {}).get("causes") or []
    return any(c.get("reason") == "DisruptionBudget" for c in causes)


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 5
    base_seconds: float = 0.025
    cap_seconds: float = 1.0
    budget_seconds: float = 5.0

    @classmethod
    def current(cls) -> "RetryPolicy":
        """The env-configured policy, cached against the RAW env
        strings — this sits on every kube request's healthy path (the
        <5% overhead guard in test_perf_floor.py), so the cache check
        is four dict reads and a tuple compare, no parsing."""
        global _cached
        raw = _probe_env()
        if _cached is None or _cached[0] != raw:
            env = _parse_env(_decode_probe(raw))
            _cached = (raw, cls(
                max_attempts=max(1, int(env[0])),
                base_seconds=env[1] / 1000.0,
                cap_seconds=env[2] / 1000.0,
                budget_seconds=env[3] / 1000.0,
            ))
        return _cached[1]

    # verbs whose requests land a flight-recorder span (with retry
    # counts) on the open tick trace; reads stay span-free — a LIST
    # per tick per kind would swamp the ring with healthy noise
    WRITE_VERBS = frozenset(
        {"create", "update", "delete", "evict", "bind", "patch"})

    def execute(
        self,
        verb: str,
        attempt: Attempt,
        on_conflict: Optional[Callable[..., bool]] = None,
        sleep=time.sleep,
        clock=time.monotonic,
    ) -> tuple[int, dict]:
        """Run `attempt` (-> (status, body)) under the retry semantics
        above; returns the final response. `verb` labels the metric
        series (create/update/delete/evict/bind/get/list).
        `on_conflict` receives the statuses seen so far in this call
        (the current 409 included) — a 409 right after a 5xx is how a
        lost-response write that actually landed announces itself, and
        the hook must be able to tell that apart from a genuine race.

        Write verbs record a span on the open tick trace carrying the
        final status and the retry count — the per-write provenance
        the aggregate karpenter_kube_retries_total cannot give."""
        if verb in self.WRITE_VERBS:
            from karpenter_tpu import tracing

            with tracing.span(f"kube.{verb}") as sp:
                status, body, retries = self._execute(
                    verb, attempt, on_conflict, sleep, clock)
                sp.annotate(status=status, retries=retries)
            return status, body
        status, body, _ = self._execute(
            verb, attempt, on_conflict, sleep, clock)
        return status, body

    def _execute(
        self,
        verb: str,
        attempt: Attempt,
        on_conflict: Optional[Callable[..., bool]] = None,
        sleep=time.sleep,
        clock=time.monotonic,
    ) -> tuple[int, dict, int]:
        deadline = clock() + self.budget_seconds
        history: list[int] = []
        retries = 0
        status, body = attempt()
        for tries in range(1, self.max_attempts):
            history.append(status)
            if status == 409:
                if on_conflict is None or not on_conflict(tuple(history)):
                    return status, body, retries
                KUBE_RETRIES.inc({"verb": verb, "status": "409"})
            elif status == 429:
                if is_pdb_eviction_block(body):
                    return status, body, retries
                KUBE_RETRIES.inc({"verb": verb, "status": "429"})
                wait = max(
                    retry_after_seconds(body),
                    full_jitter(capped_exponential(
                        tries, self.base_seconds, self.cap_seconds)),
                )
                if clock() + wait > deadline:
                    break
                sleep(wait)
            elif status >= 500:
                KUBE_RETRIES.inc({"verb": verb, "status": str(status)})
                wait = full_jitter(capped_exponential(
                    tries, self.base_seconds, self.cap_seconds))
                if clock() + wait > deadline:
                    break
                sleep(wait)
            else:
                return status, body, retries
            if clock() > deadline:
                break
            retries += 1
            status, body = attempt()
        if status in (409, 429) or status >= 500:
            log.warning("kube %s still failing after retries: HTTP %s %s",
                        verb, status, (body or {}).get("message", ""))
        return status, body, retries


_cached: Optional[tuple[tuple, RetryPolicy]] = None
