"""Serve an InMemoryApiServer over a real HTTP listener.

The envtest analogue with the wire actually on a socket
(pkg/test/environment.go:138-197 boots a real apiserver binary for the
same reason): HTTPTransport's urllib request path, bearer-token auth +
refresh, 409/429 mapping, and the `watch=true` chunked streams execute
for real in tests instead of being short-circuited by the in-process
Transport protocol.

Wire behavior mirrors kube-apiserver where RealKubeClient depends on
it:
- JSON bodies, Content-Length framed; errors as {"message": ...} with
  the HTTP status carrying the semantics (404/409/422/429).
- GET with `watch=true` streams line-delimited watch events
  ({"type": ..., "object": ...}) until `timeoutSeconds` elapses, then
  closes cleanly (the client reconnects from its last rv).
- A watch from a compacted resourceVersion emits one
  {"type": "ERROR", "object": {"kind": "Status", "code": 410}} line —
  the informer's cue to re-list.
- Optional bearer auth: requests without the CURRENT token get 401
  (bound service-account tokens rotate; the transport re-reads its
  token file per request, which this exercises).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from karpenter_tpu.kube.real import ApiError, InMemoryApiServer

WATCH_POLL_SECONDS = 0.02  # server-side event-log poll for streams


class HttpApiServer:
    """Owns the listener; `base_url` plugs straight into HTTPTransport."""

    def __init__(self, api: InMemoryApiServer, token: str = "",
                 host: str = "127.0.0.1", port: int = 0):
        self.api = api
        self.token = token
        self.stopping = threading.Event()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.0: close-delimited responses let watch streams end
            # by connection close; urllib opens one connection per
            # request anyway, so keep-alive buys nothing here
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):  # quiet test output
                pass

            def _reject_unauthenticated(self) -> bool:
                if not outer.token:
                    return False
                got = self.headers.get("Authorization", "")
                if got == f"Bearer {outer.token}":
                    return False
                self._respond(401, {"message": "Unauthorized"})
                return True

            def _respond(self, status: int, body: dict) -> None:
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _body(self) -> Optional[dict]:
                length = int(self.headers.get("Content-Length", "0") or 0)
                if not length:
                    return None
                return json.loads(self.rfile.read(length))

            def _dispatch(self, method: str) -> None:
                if self._reject_unauthenticated():
                    return
                parsed = urlparse(self.path)
                params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                if method == "GET" and params.get("watch") == "true":
                    self._watch(parsed.path, params)
                    return
                status, body = outer.api.request(
                    method, parsed.path, self._body(), params or None
                )
                self._respond(status, body)

            def _watch(self, path: str, params: dict) -> None:
                kind, name, namespace, sub = outer.api._parse(path)
                if kind is None or name or sub:
                    self._respond(404, {"message": f"unknown watch {path}"})
                    return
                rv = int(params.get("resourceVersion", "0") or 0)
                timeout = float(params.get("timeoutSeconds", "60") or 60)
                if timeout <= 0:  # 0/absent = server default, not "expire now"
                    timeout = 60.0
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                import time as _time

                deadline = _time.monotonic() + timeout
                try:
                    self._stream(kind, namespace, rv, deadline)
                except (BrokenPipeError, ConnectionError, OSError):
                    pass  # client went away; the stream just ends

            def _stream(self, kind: str, namespace: str, rv: int,
                        deadline: float) -> None:
                import time as _time

                while (not outer.stopping.is_set()
                       and _time.monotonic() < deadline):
                    try:
                        events = outer.api.watch_events(kind, rv)
                    except ApiError as err:
                        self._line({"type": "ERROR", "object": {
                            "kind": "Status", "code": err.status,
                            "message": str(err),
                        }})
                        return
                    for ev, cr, ev_rv in events:
                        if namespace and cr.get("metadata", {}).get(
                            "namespace", ""
                        ) != namespace:
                            rv = max(rv, ev_rv)
                            continue
                        self._line({"type": ev, "object": cr})
                        rv = max(rv, ev_rv)
                    outer.stopping.wait(WATCH_POLL_SECONDS)

            def _line(self, event: dict) -> None:
                self.wfile.write(json.dumps(event).encode() + b"\n")
                self.wfile.flush()

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.base_url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="httpapi", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self.stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
