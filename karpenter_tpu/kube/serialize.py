"""CR dict codecs: typed objects <-> real Kubernetes resource dicts.

The in-memory store works on typed dataclasses; a real API server
speaks JSON resources shaped by the CRD schemas this repo generates
(apis/crds/*.json, mirroring pkg/apis/crds/*.yaml). This module is the
boundary: `to_cr` renders a typed object as the dict a real cluster
would accept (camelCase keys, RFC3339 timestamps, k8s quantity
strings), `from_cr` parses a watch/get payload back into the typed
object. Round-trip fidelity is tested field-for-field
(tests/test_real_client.py) and the rendered CRs are checked against
the generated openAPIV3Schema artifacts.

Covered kinds (the TO_CR/FROM_CR registries below are the source of
truth): NodePool, NodeClaim, NodeOverlay (the CRDs); Pod and Node
(requests, affinity, topology spread, tolerations, volumes, taints,
conditions, ownerReferences); DaemonSet, PodDisruptionBudget,
PersistentVolumeClaim, PriorityClass (read-side controller inputs);
Lease (leader election); and Event (write-side recorder output).
"""

from __future__ import annotations

import calendar
import time
from typing import Optional

from karpenter_tpu.apis.v1.condition import Condition, ConditionSet
from karpenter_tpu.apis.v1.nodeclaim import (
    NodeClaim,
    NodeClaimSpec,
    NodeClaimStatus,
    NodeClassRef,
    RequirementSpec,
)
from karpenter_tpu.apis.v1.nodepool import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    NodePoolSpec,
    NodePoolStatus,
)
from karpenter_tpu.apis.v1alpha1.nodeoverlay import NodeOverlay, NodeOverlaySpec
from karpenter_tpu.kube.objects import (
    Affinity,
    Container,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodSpec,
    PodStatus,
    PodVolume,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.utils.quantity import format_quantity, parse_quantity

GROUP_V1 = "karpenter.sh/v1"
GROUP_V1ALPHA1 = "karpenter.sh/v1alpha1"


# ---------------------------------------------------------------- scalars


def ts_to_rfc3339(ts: Optional[float]) -> Optional[str]:
    if ts is None:
        return None
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def ts_from_rfc3339(value) -> Optional[float]:
    if value in (None, ""):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    # metav1.MicroTime (Lease renewTime, Event eventTime) carries
    # fractional seconds: "2026-07-30T12:00:00.123456Z".
    base, frac = value, 0.0
    if "." in value:
        head, tail = value.split(".", 1)
        digits = tail.rstrip("Zz")
        base = head + "Z"
        if digits:
            frac = float("0." + digits)
    return float(calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%SZ"))) + frac


def _resources_to_cr(resources: dict) -> dict:
    return {k: format_quantity(v) for k, v in resources.items()}


def _resources_from_cr(resources: Optional[dict]) -> dict:
    return {k: parse_quantity(v) for k, v in (resources or {}).items()}


def _drop_none(d: dict) -> dict:
    return {k: v for k, v in d.items() if v not in (None, "", [], {}, ())}


# ---------------------------------------------------------------- metadata


def meta_to_cr(meta: ObjectMeta, namespaced: bool = False) -> dict:
    out = {
        "name": meta.name,
        "uid": meta.uid,
        "labels": dict(meta.labels),
        "annotations": dict(meta.annotations),
        "finalizers": list(meta.finalizers),
        "creationTimestamp": ts_to_rfc3339(meta.creation_timestamp),
        "deletionTimestamp": ts_to_rfc3339(meta.deletion_timestamp),
        # resourceVersion is an opaque STRING on the wire
        "resourceVersion": str(meta.resource_version),
        "generation": meta.generation,
        # controller ownership drives drain semantics (DaemonSet
        # detection, rebirth gating) — losing it on the wire would
        # make every real-cluster pod look bare
        "ownerReferences": [
            _drop_none({
                "apiVersion": ref.api_version,
                "kind": ref.kind, "name": ref.name, "uid": ref.uid,
                "controller": ref.controller or None,
            })
            for ref in meta.owner_references
        ] or None,
    }
    if namespaced:
        out["namespace"] = meta.namespace
    return _drop_none(out)


def meta_from_cr(cr: dict) -> ObjectMeta:
    meta = cr.get("metadata", {})
    return ObjectMeta(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        labels=dict(meta.get("labels", {})),
        annotations=dict(meta.get("annotations", {})),
        finalizers=list(meta.get("finalizers", [])),
        creation_timestamp=ts_from_rfc3339(meta.get("creationTimestamp"))
        or 0.0,
        deletion_timestamp=ts_from_rfc3339(meta.get("deletionTimestamp")),
        resource_version=int(meta.get("resourceVersion", "0") or 0),
        generation=int(meta.get("generation", 0)),
        owner_references=[
            OwnerReference(
                kind=ref.get("kind", ""), name=ref.get("name", ""),
                uid=ref.get("uid", ""),
                controller=bool(ref.get("controller", False)),
                api_version=ref.get("apiVersion", "apps/v1"),
            )
            for ref in meta.get("ownerReferences", [])
        ],
    )


# ---------------------------------------------------------------- shared


def _taints_to_cr(taints) -> list[dict]:
    return [
        _drop_none({"key": t.key, "value": t.value, "effect": t.effect})
        for t in taints
    ]


def _taints_from_cr(items) -> list[Taint]:
    return [
        Taint(key=t["key"], value=t.get("value", ""),
              effect=t.get("effect", "NoSchedule"))
        for t in (items or [])
    ]


def _conditions_to_cr(conditions: ConditionSet) -> list[dict]:
    return [
        _drop_none({
            "type": c.type,
            "status": c.status,
            "reason": c.reason,
            "message": c.message,
            "lastTransitionTime": ts_to_rfc3339(c.last_transition_time),
            "observedGeneration": c.observed_generation or None,
        })
        for c in conditions.conditions
    ]


def _conditions_from_cr(items, root_types: list[str]) -> ConditionSet:
    out = ConditionSet(root_types=list(root_types))
    for c in items or []:
        out.conditions.append(Condition(
            type=c["type"],
            status=c.get("status", "Unknown"),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_transition_time=ts_from_rfc3339(
                c.get("lastTransitionTime")) or 0.0,
            observed_generation=int(c.get("observedGeneration", 0)),
        ))
    return out


def _requirements_to_cr(reqs: list[RequirementSpec]) -> list[dict]:
    return [
        _drop_none({
            "key": r.key,
            "operator": r.operator,
            "values": list(r.values),
            "minValues": r.min_values,
        })
        for r in reqs
    ]


def _requirements_from_cr(items) -> list[RequirementSpec]:
    return [
        RequirementSpec(
            key=r["key"],
            operator=r["operator"],
            values=tuple(r.get("values", [])),
            min_values=r.get("minValues"),
        )
        for r in (items or [])
    ]


def _claim_spec_to_cr(spec: NodeClaimSpec) -> dict:
    out = {
        "requirements": _requirements_to_cr(spec.requirements),
        "resources": (
            {"requests": _resources_to_cr(spec.resources)}
            if spec.resources else None
        ),
        "taints": _taints_to_cr(spec.taints),
        "startupTaints": _taints_to_cr(spec.startup_taints),
        "expireAfter": spec.expire_after,
        "terminationGracePeriod": spec.termination_grace_period,
    }
    if spec.node_class_ref is not None:
        out["nodeClassRef"] = {
            "group": spec.node_class_ref.group,
            "kind": spec.node_class_ref.kind,
            "name": spec.node_class_ref.name,
        }
    return _drop_none(out)


def _claim_spec_from_cr(spec: dict) -> NodeClaimSpec:
    ref = spec.get("nodeClassRef")
    return NodeClaimSpec(
        requirements=_requirements_from_cr(spec.get("requirements")),
        resources=_resources_from_cr(
            (spec.get("resources") or {}).get("requests")
        ),
        taints=_taints_from_cr(spec.get("taints")),
        startup_taints=_taints_from_cr(spec.get("startupTaints")),
        node_class_ref=(
            NodeClassRef(group=ref.get("group", ""), kind=ref.get("kind", ""),
                         name=ref.get("name", ""))
            if ref else None
        ),
        expire_after=spec.get("expireAfter"),
        termination_grace_period=spec.get("terminationGracePeriod"),
    )


# ---------------------------------------------------------------- NodeClaim


def nodeclaim_to_cr(claim: NodeClaim) -> dict:
    return {
        "apiVersion": GROUP_V1,
        "kind": "NodeClaim",
        "metadata": meta_to_cr(claim.metadata),
        "spec": _claim_spec_to_cr(claim.spec),
        "status": _drop_none({
            "providerID": claim.status.provider_id,
            "imageID": claim.status.image_id,
            "nodeName": claim.status.node_name,
            "capacity": _resources_to_cr(claim.status.capacity),
            "allocatable": _resources_to_cr(claim.status.allocatable),
            "lastPodEventTime": ts_to_rfc3339(
                claim.status.last_pod_event_time
            ),
            "conditions": _conditions_to_cr(claim.status_conditions),
        }),
    }


def nodeclaim_from_cr(cr: dict) -> NodeClaim:
    from karpenter_tpu.apis.v1.nodeclaim import LIFECYCLE_ROOT_CONDITIONS

    status = cr.get("status", {})
    return NodeClaim(
        metadata=meta_from_cr(cr),
        spec=_claim_spec_from_cr(cr.get("spec", {})),
        status=NodeClaimStatus(
            provider_id=status.get("providerID", ""),
            image_id=status.get("imageID", ""),
            node_name=status.get("nodeName", ""),
            capacity=_resources_from_cr(status.get("capacity")),
            allocatable=_resources_from_cr(status.get("allocatable")),
            last_pod_event_time=ts_from_rfc3339(
                status.get("lastPodEventTime")
            ),
        ),
        status_conditions=_conditions_from_cr(
            status.get("conditions"), LIFECYCLE_ROOT_CONDITIONS
        ),
    )


# ---------------------------------------------------------------- NodePool


def nodepool_to_cr(pool: NodePool) -> dict:
    disruption = pool.spec.disruption
    return {
        "apiVersion": GROUP_V1,
        "kind": "NodePool",
        "metadata": meta_to_cr(pool.metadata),
        "spec": _drop_none({
            "template": _drop_none({
                "metadata": _drop_none({
                    "labels": dict(pool.spec.template.labels),
                    "annotations": dict(pool.spec.template.annotations),
                }),
                "spec": _claim_spec_to_cr(pool.spec.template.spec),
            }),
            "disruption": _drop_none({
                "consolidateAfter": disruption.consolidate_after,
                "consolidationPolicy": disruption.consolidation_policy,
                "budgets": [
                    _drop_none({
                        "nodes": b.nodes,
                        "schedule": b.schedule,
                        "duration": b.duration,
                        "reasons": b.reasons,
                    })
                    for b in disruption.budgets
                ],
            }),
            "limits": _resources_to_cr(pool.spec.limits),
            "weight": pool.spec.weight or None,
            "replicas": pool.spec.replicas,
        }),
        "status": _drop_none({
            "resources": _resources_to_cr(pool.status.resources),
            "nodes": pool.status.nodes or None,
            "conditions": _conditions_to_cr(pool.status_conditions),
        }),
    }


def nodepool_from_cr(cr: dict) -> NodePool:
    from karpenter_tpu.apis.v1.nodepool import (
        COND_NODE_CLASS_READY,
        COND_VALIDATION_SUCCEEDED,
    )

    spec = cr.get("spec", {})
    template = spec.get("template", {})
    disruption = spec.get("disruption", {})
    status = cr.get("status", {})
    return NodePool(
        metadata=meta_from_cr(cr),
        spec=NodePoolSpec(
            template=NodeClaimTemplate(
                labels=dict((template.get("metadata") or {}).get("labels", {})),
                annotations=dict(
                    (template.get("metadata") or {}).get("annotations", {})
                ),
                spec=_claim_spec_from_cr(template.get("spec", {})),
            ),
            disruption=Disruption(
                consolidate_after=disruption.get("consolidateAfter", "0s"),
                consolidation_policy=disruption.get(
                    "consolidationPolicy", "WhenEmptyOrUnderutilized"
                ),
                budgets=[
                    Budget(
                        nodes=b.get("nodes", "10%"),
                        schedule=b.get("schedule"),
                        duration=b.get("duration"),
                        reasons=b.get("reasons"),
                    )
                    for b in disruption.get("budgets", [])
                ],
            ),
            limits=_resources_from_cr(spec.get("limits")),
            weight=int(spec.get("weight", 0)),
            replicas=spec.get("replicas"),
        ),
        status=NodePoolStatus(
            resources=_resources_from_cr(status.get("resources")),
            nodes=int(status.get("nodes", 0)),
        ),
        status_conditions=_conditions_from_cr(
            status.get("conditions"),
            [COND_VALIDATION_SUCCEEDED, COND_NODE_CLASS_READY],
        ),
    )


# ---------------------------------------------------------------- NodeOverlay


def nodeoverlay_to_cr(overlay: NodeOverlay) -> dict:
    return {
        "apiVersion": GROUP_V1ALPHA1,
        "kind": "NodeOverlay",
        "metadata": meta_to_cr(overlay.metadata),
        "spec": _drop_none({
            "requirements": [
                _drop_none({
                    "key": r.key,
                    "operator": r.operator,
                    "values": list(r.values),
                })
                for r in overlay.spec.requirements
            ],
            "priceAdjustment": overlay.spec.price_adjustment,
            "price": overlay.spec.price,
            "capacity": _resources_to_cr(overlay.spec.capacity),
            "weight": overlay.spec.weight or None,
        }),
        "status": _drop_none({
            "conditions": _conditions_to_cr(overlay.status_conditions),
        }),
    }


def nodeoverlay_from_cr(cr: dict) -> NodeOverlay:
    from karpenter_tpu.apis.v1alpha1.nodeoverlay import COND_OVERLAY_VALIDATION

    spec = cr.get("spec", {})
    return NodeOverlay(
        metadata=meta_from_cr(cr),
        spec=NodeOverlaySpec(
            requirements=[
                NodeSelectorRequirement(
                    key=r["key"], operator=r["operator"],
                    values=tuple(r.get("values", [])),
                )
                for r in spec.get("requirements", [])
            ],
            price_adjustment=spec.get("priceAdjustment"),
            price=spec.get("price"),
            capacity=_resources_from_cr(spec.get("capacity")),
            weight=int(spec.get("weight", 0)),
        ),
        status_conditions=_conditions_from_cr(
            (cr.get("status") or {}).get("conditions"),
            [COND_OVERLAY_VALIDATION],
        ),
    )


# ---------------------------------------------------------------- Pod


def _label_selector_to_cr(sel: LabelSelector) -> dict:
    return _drop_none({
        "matchLabels": dict(sel.match_labels),
        "matchExpressions": [
            _drop_none({"key": e.key, "operator": e.operator,
                        "values": list(e.values)})
            for e in sel.match_expressions
        ],
    })


def _label_selector_from_cr(sel: Optional[dict]) -> LabelSelector:
    sel = sel or {}
    return LabelSelector.of(
        labels=sel.get("matchLabels", {}),
        expressions=[
            LabelSelectorRequirement(
                key=e["key"], operator=e["operator"],
                values=tuple(e.get("values", [])),
            )
            for e in sel.get("matchExpressions", [])
        ],
    )


def _node_term_to_cr(term: NodeSelectorTerm) -> dict:
    return {
        "matchExpressions": [
            _drop_none({"key": e.key, "operator": e.operator,
                        "values": list(e.values)})
            for e in term.match_expressions
        ]
    }


def _node_term_from_cr(term: dict) -> NodeSelectorTerm:
    return NodeSelectorTerm(match_expressions=tuple(
        NodeSelectorRequirement(
            key=e["key"], operator=e["operator"],
            values=tuple(e.get("values", [])),
        )
        for e in term.get("matchExpressions", [])
    ))


def _pod_term_to_cr(term: PodAffinityTerm) -> dict:
    return _drop_none({
        "labelSelector": _label_selector_to_cr(term.label_selector),
        "topologyKey": term.topology_key,
        "namespaces": list(term.namespaces),
    })


def _pod_term_from_cr(term: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_label_selector_from_cr(term.get("labelSelector")),
        topology_key=term.get("topologyKey", ""),
        namespaces=tuple(term.get("namespaces", [])),
    )


def _affinity_to_cr(affinity: Affinity) -> dict:
    out: dict = {}
    if affinity.node_affinity is not None:
        na = affinity.node_affinity
        out["nodeAffinity"] = _drop_none({
            "requiredDuringSchedulingIgnoredDuringExecution": (
                {"nodeSelectorTerms": [_node_term_to_cr(t) for t in na.required]}
                if na.required else None
            ),
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": p.weight, "preference": _node_term_to_cr(p.preference)}
                for p in na.preferred
            ],
        })
    for attr, key in (("pod_affinity", "podAffinity"),
                      ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(affinity, attr)
        if pa is not None:
            out[key] = _drop_none({
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    _pod_term_to_cr(t) for t in pa.required
                ],
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": w.weight,
                     "podAffinityTerm": _pod_term_to_cr(w.pod_affinity_term)}
                    for w in pa.preferred
                ],
            })
    return out


def _affinity_from_cr(cr: Optional[dict]) -> Optional[Affinity]:
    if not cr:
        return None
    node_affinity = None
    na = cr.get("nodeAffinity")
    if na:
        required = (
            na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
        ).get("nodeSelectorTerms", [])
        preferred = na.get(
            "preferredDuringSchedulingIgnoredDuringExecution", []
        )
        node_affinity = NodeAffinity(
            required=tuple(_node_term_from_cr(t) for t in required),
            preferred=tuple(
                PreferredSchedulingTerm(
                    weight=p.get("weight", 1),
                    preference=_node_term_from_cr(p.get("preference", {})),
                )
                for p in preferred
            ),
        )

    def pod_aff(key):
        pa = cr.get(key)
        if not pa:
            return None
        return PodAffinity(
            required=tuple(
                _pod_term_from_cr(t)
                for t in pa.get(
                    "requiredDuringSchedulingIgnoredDuringExecution", []
                )
            ),
            preferred=tuple(
                WeightedPodAffinityTerm(
                    weight=w.get("weight", 1),
                    pod_affinity_term=_pod_term_from_cr(
                        w.get("podAffinityTerm", {})
                    ),
                )
                for w in pa.get(
                    "preferredDuringSchedulingIgnoredDuringExecution", []
                )
            ),
        )

    if node_affinity is None and pod_aff("podAffinity") is None and pod_aff(
        "podAntiAffinity"
    ) is None:
        return None
    return Affinity(
        node_affinity=node_affinity,
        pod_affinity=pod_aff("podAffinity"),
        pod_anti_affinity=pod_aff("podAntiAffinity"),
    )


def _container_to_cr(c: Container) -> dict:
    return _drop_none({
        "name": c.name,
        "resources": (
            {"requests": _resources_to_cr(c.requests)} if c.requests else None
        ),
        "ports": [{"hostPort": p} for p in c.ports] or None,
        "restartPolicy": c.restart_policy,
    })


def _container_from_cr(c: dict) -> Container:
    return Container(
        name=c.get("name", "main"),
        requests=_resources_from_cr(
            (c.get("resources") or {}).get("requests")
        ),
        ports=[p["hostPort"] for p in c.get("ports", []) if "hostPort" in p],
        restart_policy=c.get("restartPolicy"),
    )


def pod_to_cr(pod: Pod) -> dict:
    spec = pod.spec
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta_to_cr(pod.metadata, namespaced=True),
        "spec": _drop_none({
            "nodeSelector": dict(spec.node_selector),
            "affinity": _affinity_to_cr(spec.affinity) if spec.affinity else None,
            "tolerations": [
                _drop_none({
                    "key": t.key, "operator": t.operator, "value": t.value,
                    "effect": t.effect,
                    "tolerationSeconds": t.toleration_seconds,
                })
                for t in spec.tolerations
            ],
            "topologySpreadConstraints": [
                _drop_none({
                    "maxSkew": t.max_skew,
                    "topologyKey": t.topology_key,
                    "whenUnsatisfiable": t.when_unsatisfiable,
                    "labelSelector": _label_selector_to_cr(t.label_selector),
                    "minDomains": t.min_domains,
                    "nodeAffinityPolicy": t.node_affinity_policy,
                    "nodeTaintsPolicy": t.node_taints_policy,
                })
                for t in spec.topology_spread_constraints
            ],
            "containers": [_container_to_cr(c) for c in spec.containers],
            "initContainers": [
                _container_to_cr(c) for c in spec.init_containers
            ],
            "overhead": _resources_to_cr(spec.overhead),
            "volumes": [
                _drop_none({
                    "name": v.name,
                    "persistentVolumeClaim": (
                        {"claimName": v.pvc_name} if v.pvc_name else None
                    ),
                    "ephemeral": {} if v.ephemeral else None,
                })
                for v in spec.volumes
            ],
            "nodeName": spec.node_name,
            "priority": spec.priority or None,
            "priorityClassName": spec.priority_class_name,
            "schedulerName": spec.scheduler_name,
            "terminationGracePeriodSeconds": spec.termination_grace_period_seconds,
            "restartPolicy": spec.restart_policy,
        }),
        "status": _drop_none({
            "phase": pod.status.phase,
            "startTime": ts_to_rfc3339(pod.status.start_time),
            "nominatedNodeName": pod.status.nominated_node_name,
        }),
    }


def pod_from_cr(cr: dict) -> Pod:
    spec = cr.get("spec", {})
    status = cr.get("status", {})
    return Pod(
        metadata=meta_from_cr(cr),
        spec=PodSpec(
            node_selector=dict(spec.get("nodeSelector", {})),
            affinity=_affinity_from_cr(spec.get("affinity")),
            tolerations=[
                Toleration(
                    key=t.get("key", ""),
                    operator=t.get("operator", "Equal"),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                    toleration_seconds=t.get("tolerationSeconds"),
                )
                for t in spec.get("tolerations", [])
            ],
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=t.get("maxSkew", 1),
                    topology_key=t.get("topologyKey", ""),
                    when_unsatisfiable=t.get(
                        "whenUnsatisfiable", "DoNotSchedule"
                    ),
                    label_selector=_label_selector_from_cr(
                        t.get("labelSelector")
                    ),
                    min_domains=t.get("minDomains"),
                    node_affinity_policy=t.get("nodeAffinityPolicy", "Honor"),
                    node_taints_policy=t.get("nodeTaintsPolicy", "Ignore"),
                )
                for t in spec.get("topologySpreadConstraints", [])
            ],
            containers=[
                _container_from_cr(c) for c in spec.get("containers", [])
            ],
            init_containers=[
                _container_from_cr(c) for c in spec.get("initContainers", [])
            ],
            overhead=_resources_from_cr(spec.get("overhead")),
            volumes=[
                PodVolume(
                    name=v.get("name", ""),
                    pvc_name=(
                        (v.get("persistentVolumeClaim") or {}).get("claimName")
                    ),
                    ephemeral="ephemeral" in v,
                )
                for v in spec.get("volumes", [])
            ],
            node_name=spec.get("nodeName", ""),
            priority=int(spec.get("priority", 0)),
            priority_class_name=spec.get("priorityClassName", ""),
            scheduler_name=spec.get("schedulerName", "default-scheduler"),
            termination_grace_period_seconds=spec.get(
                "terminationGracePeriodSeconds", 30
            ),
            restart_policy=spec.get("restartPolicy", "Always"),
        ),
        status=PodStatus(
            phase=status.get("phase", "Pending"),
            start_time=ts_from_rfc3339(status.get("startTime")),
            nominated_node_name=status.get("nominatedNodeName", ""),
        ),
    )


# ---------------------------------------------------------------- Node


def node_to_cr(node: Node) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": meta_to_cr(node.metadata),
        "spec": _drop_none({
            "taints": _taints_to_cr(node.spec.taints),
            "unschedulable": node.spec.unschedulable or None,
            "providerID": node.spec.provider_id,
        }),
        "status": _drop_none({
            "capacity": _resources_to_cr(node.status.capacity),
            "allocatable": _resources_to_cr(node.status.allocatable),
            "conditions": [
                _drop_none({
                    "type": c.type,
                    "status": c.status,
                    "reason": c.reason,
                    "lastTransitionTime": ts_to_rfc3339(
                        c.last_transition_time
                    ),
                })
                for c in node.status.conditions
            ],
        }),
    }


def node_from_cr(cr: dict) -> Node:
    spec = cr.get("spec", {})
    status = cr.get("status", {})
    return Node(
        metadata=meta_from_cr(cr),
        spec=NodeSpec(
            taints=_taints_from_cr(spec.get("taints")),
            unschedulable=bool(spec.get("unschedulable", False)),
            provider_id=spec.get("providerID", ""),
        ),
        status=NodeStatus(
            capacity=_resources_from_cr(status.get("capacity")),
            allocatable=_resources_from_cr(status.get("allocatable")),
            conditions=[
                NodeCondition(
                    type=c["type"],
                    status=c.get("status", "Unknown"),
                    reason=c.get("reason", ""),
                    last_transition_time=ts_from_rfc3339(
                        c.get("lastTransitionTime")
                    ) or 0.0,
                )
                for c in status.get("conditions", [])
            ],
        ),
    )


# ------------------------------------------------- workload/storage kinds


def daemonset_to_cr(ds) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": meta_to_cr(ds.metadata, namespaced=True),
        "spec": _drop_none({
            "selector": _label_selector_to_cr(ds.spec.selector),
            "template": _drop_none({
                "metadata": _drop_none({
                    "labels": dict(ds.spec.template.metadata.labels),
                }),
                "spec": pod_to_cr(
                    Pod(spec=ds.spec.template.spec)
                )["spec"],
            }),
        }),
    }


def daemonset_from_cr(cr: dict):
    from karpenter_tpu.kube.objects import DaemonSet, DaemonSetSpec, PodTemplateSpec

    spec = cr.get("spec", {})
    template = spec.get("template", {})
    pod = pod_from_cr({"metadata": template.get("metadata", {}),
                       "spec": template.get("spec", {})})
    return DaemonSet(
        metadata=meta_from_cr(cr),
        spec=DaemonSetSpec(
            selector=_label_selector_from_cr(spec.get("selector")),
            template=PodTemplateSpec(metadata=pod.metadata, spec=pod.spec),
        ),
    )


def pdb_to_cr(pdb) -> dict:
    return {
        "apiVersion": "policy/v1",
        "kind": "PodDisruptionBudget",
        "metadata": meta_to_cr(pdb.metadata, namespaced=True),
        "spec": _drop_none({
            "selector": _label_selector_to_cr(pdb.spec.selector),
            "minAvailable": pdb.spec.min_available,
            "maxUnavailable": pdb.spec.max_unavailable,
        }),
        "status": _drop_none({
            "disruptionsAllowed": pdb.status.disruptions_allowed or None,
            "currentHealthy": pdb.status.current_healthy or None,
            "desiredHealthy": pdb.status.desired_healthy or None,
            "expectedPods": pdb.status.expected_pods or None,
        }),
    }


def pdb_from_cr(cr: dict):
    from karpenter_tpu.kube.objects import (
        PodDisruptionBudget,
        PodDisruptionBudgetSpec,
        PodDisruptionBudgetStatus,
    )

    spec = cr.get("spec", {})
    status = cr.get("status", {})
    return PodDisruptionBudget(
        metadata=meta_from_cr(cr),
        spec=PodDisruptionBudgetSpec(
            selector=_label_selector_from_cr(spec.get("selector")),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
        ),
        status=PodDisruptionBudgetStatus(
            disruptions_allowed=int(status.get("disruptionsAllowed", 0)),
            current_healthy=int(status.get("currentHealthy", 0)),
            desired_healthy=int(status.get("desiredHealthy", 0)),
            expected_pods=int(status.get("expectedPods", 0)),
        ),
    )


def pvc_to_cr(pvc) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": meta_to_cr(pvc.metadata, namespaced=True),
        "spec": _drop_none({
            "storageClassName": pvc.spec.storage_class_name,
            "volumeName": pvc.spec.volume_name,
        }),
        "status": _drop_none({"phase": pvc.phase}),
    }


def pvc_from_cr(cr: dict):
    from karpenter_tpu.kube.objects import (
        PersistentVolumeClaim,
        PersistentVolumeClaimSpec,
    )

    spec = cr.get("spec", {})
    return PersistentVolumeClaim(
        metadata=meta_from_cr(cr),
        spec=PersistentVolumeClaimSpec(
            storage_class_name=spec.get("storageClassName"),
            volume_name=spec.get("volumeName", ""),
        ),
        phase=(cr.get("status") or {}).get("phase", ""),
    )


# ---------------------------------------------------------------- Lease


def lease_to_cr(lease) -> dict:
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": meta_to_cr(lease.metadata, namespaced=True),
        "spec": _drop_none({
            "holderIdentity": lease.holder,
            "leaseDurationSeconds": int(lease.lease_duration),
            "renewTime": ts_to_rfc3339(lease.renew_time),
        }),
    }


def lease_from_cr(cr: dict):
    from karpenter_tpu.operator.leader import Lease

    spec = cr.get("spec", {})
    return Lease(
        metadata=meta_from_cr(cr),
        holder=spec.get("holderIdentity", ""),
        renew_time=ts_from_rfc3339(spec.get("renewTime")) or 0.0,
        lease_duration=float(spec.get("leaseDurationSeconds", 15)),
    )


def priorityclass_to_cr(pc) -> dict:
    """scheduling.k8s.io/v1 PriorityClass wire form (value /
    globalDefault / preemptionPolicy are the fields admission-time
    priority resolution reads)."""
    return {
        "apiVersion": "scheduling.k8s.io/v1",
        "kind": "PriorityClass",
        "metadata": meta_to_cr(pc.metadata),
        "value": pc.value,
        "globalDefault": pc.global_default,
        "preemptionPolicy": pc.preemption_policy,
    }


def priorityclass_from_cr(cr: dict):
    from karpenter_tpu.kube.objects import PriorityClass

    meta = meta_from_cr(cr)
    meta.namespace = ""  # cluster-scoped
    return PriorityClass(
        metadata=meta,
        value=int(cr.get("value", 0)),
        global_default=bool(cr.get("globalDefault", False)),
        preemption_policy=cr.get("preemptionPolicy", "PreemptLowerPriority"),
    )


# ---------------------------------------------------------------- registry

def event_to_cr(ev) -> dict:
    """corev1.Event wire form (pkg/events/recorder.go publishes these
    through record.EventRecorder; kubectl describe joins them on
    involvedObject)."""
    return {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": meta_to_cr(ev.metadata, namespaced=True),
        "involvedObject": _drop_none({
            "kind": ev.involved_kind,
            "name": ev.involved_name,
            "namespace": ev.involved_namespace or None,
        }),
        "type": ev.type,
        "reason": ev.reason,
        "message": ev.message,
        "count": ev.count,
        "firstTimestamp": ts_to_rfc3339(ev.first_timestamp or None),
        "lastTimestamp": ts_to_rfc3339(ev.last_timestamp or None),
        "source": {"component": ev.source_component},
        "reportingComponent": ev.source_component,
    }


def event_from_cr(cr: dict):
    from karpenter_tpu.kube.objects import KubeEvent

    involved = cr.get("involvedObject", {})
    return KubeEvent(
        metadata=meta_from_cr(cr),
        involved_kind=involved.get("kind", ""),
        involved_name=involved.get("name", ""),
        involved_namespace=involved.get("namespace", ""),
        type=cr.get("type", "Normal"),
        reason=cr.get("reason", ""),
        message=cr.get("message", ""),
        count=int(cr.get("count", 1)),
        first_timestamp=ts_from_rfc3339(cr.get("firstTimestamp")) or 0.0,
        last_timestamp=ts_from_rfc3339(cr.get("lastTimestamp")) or 0.0,
        source_component=(cr.get("source") or {}).get("component", ""),
    )


TO_CR = {
    "Event": event_to_cr,
    "NodePool": nodepool_to_cr,
    "NodeClaim": nodeclaim_to_cr,
    "NodeOverlay": nodeoverlay_to_cr,
    "Pod": pod_to_cr,
    "Node": node_to_cr,
    "DaemonSet": daemonset_to_cr,
    "PodDisruptionBudget": pdb_to_cr,
    "PersistentVolumeClaim": pvc_to_cr,
    "PriorityClass": priorityclass_to_cr,
    "Lease": lease_to_cr,
}

FROM_CR = {
    "Event": event_from_cr,
    "NodePool": nodepool_from_cr,
    "NodeClaim": nodeclaim_from_cr,
    "NodeOverlay": nodeoverlay_from_cr,
    "Pod": pod_from_cr,
    "Node": node_from_cr,
    "DaemonSet": daemonset_from_cr,
    "PodDisruptionBudget": pdb_from_cr,
    "PersistentVolumeClaim": pvc_from_cr,
    "PriorityClass": priorityclass_from_cr,
    "Lease": lease_from_cr,
}


def to_cr(obj) -> dict:
    return TO_CR[obj.kind](obj)


def from_cr(cr: dict) -> object:
    return FROM_CR[cr["kind"]](cr)
