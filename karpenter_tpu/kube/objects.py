"""Lightweight Kubernetes-shaped object model.

The reference runs against a real API server (controller-runtime +
envtest). This build has no kube cluster in the loop, so the framework
defines its own typed object model carrying exactly the fields the
scheduling/disruption engines consume, plus an in-memory API server
(`karpenter_tpu.kube.client`) with watch/patch/finalizer semantics the
controllers are written against. Field names follow the k8s API
(snake_cased) so a thin adapter can map to real CRs later.

Covers: Pod (affinity/anti-affinity, topology spread, tolerations,
host ports, PVCs, overhead), Node, DaemonSet, PDB, PVC/StorageClass,
PriorityClass.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.utils.resources import ResourceList

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter):08d}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list["OwnerReference"] = field(default_factory=list)
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    generation: int = 0


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = False
    # required on the wire: a real apiserver 422s ownerReferences
    # missing apiVersion
    api_version: str = "apps/v1"


# ---------------------------------------------------------------- taints


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute

    def matches(self, other: "Taint") -> bool:
        return self.key == other.key and self.value == other.value and self.effect == other.effect


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """Mirrors corev1.Toleration.ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


# ---------------------------------------------------------------- selectors


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: match_labels AND match_expressions."""

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[LabelSelectorRequirement, ...] = ()

    @staticmethod
    def of(labels: dict[str, str] | None = None,
           expressions: list[LabelSelectorRequirement] | None = None) -> "LabelSelector":
        return LabelSelector(
            match_labels=tuple(sorted((labels or {}).items())),
            match_expressions=tuple(expressions or ()),
        )

    def matches(self, labels: dict[str, str]) -> bool:
        for key, value in self.match_labels:
            if labels.get(key) != value:
                return False
        for expr in self.match_expressions:
            has = expr.key in labels
            if expr.operator == "In":
                if not has or labels[expr.key] not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if has and labels[expr.key] in expr.values:
                    return False
            elif expr.operator == "Exists":
                if not has:
                    return False
            elif expr.operator == "DoesNotExist":
                if has:
                    return False
        return True


# ---------------------------------------------------------------- affinity


@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelectorTerm:
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: tuple[NodeSelectorTerm, ...] = ()   # OR of terms
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class PodAffinityTerm:
    label_selector: LabelSelector = field(default_factory=LabelSelector)
    topology_key: str = ""
    namespaces: tuple[str, ...] = ()  # empty -> pod's own namespace


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: LabelSelector = field(default_factory=LabelSelector)
    min_domains: Optional[int] = None
    node_affinity_policy: str = "Honor"  # Honor | Ignore
    node_taints_policy: str = "Ignore"   # Honor | Ignore


# ---------------------------------------------------------------- pod


@dataclass
class Container:
    name: str = "main"
    requests: ResourceList = field(default_factory=dict)
    ports: list[int] = field(default_factory=list)  # host ports only
    host_ip: str = ""
    # Dynamic Resource Allocation: names of pod-level resourceClaims
    # this container consumes (corev1 Container.Resources.Claims)
    resource_claims: list[str] = field(default_factory=list)
    # init containers with restartPolicy=Always are native sidecars:
    # their requests persist for the pod's lifetime
    restart_policy: Optional[str] = None


@dataclass
class PodVolume:
    name: str = ""
    pvc_name: Optional[str] = None  # persistentVolumeClaim.claimName
    ephemeral: bool = False         # generic ephemeral volume -> PVC "<pod>-<vol>"


@dataclass
class PodSpec:
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: ResourceList = field(default_factory=dict)
    # pod-level resource requests (PodLevelResources feature): when
    # set, these replace container aggregation for scheduling
    resources: ResourceList = field(default_factory=dict)
    volumes: list[PodVolume] = field(default_factory=list)
    node_name: str = ""
    priority: int = 0
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"
    termination_grace_period_seconds: Optional[int] = 30
    restart_policy: str = "Always"
    # transient, re-derived each scheduling round: zonal requirements
    # injected from the pod's PVCs (volumetopology.go:51-160); consumed
    # by Requirements.from_pod, never part of the API object proper
    injected_requirements: list = field(default_factory=list)


@dataclass
class PodCondition:
    type: str
    status: str
    reason: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: list[PodCondition] = field(default_factory=list)
    start_time: Optional[float] = None
    nominated_node_name: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_terminal(self) -> bool:
        return self.status.phase in ("Succeeded", "Failed")

    def is_terminating(self) -> bool:
        return self.metadata.deletion_timestamp is not None

    def is_scheduled(self) -> bool:
        return bool(self.spec.node_name)

    def owner_kind(self) -> str:
        for ref in self.metadata.owner_references:
            if ref.controller:
                return ref.kind
        return ""


# ---------------------------------------------------------------- node


@dataclass
class NodeSpec:
    taints: list[Taint] = field(default_factory=list)
    unschedulable: bool = False
    provider_id: str = ""


@dataclass
class NodeCondition:
    type: str
    status: str
    reason: str = ""
    last_transition_time: float = 0.0


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    @property
    def key(self) -> str:
        return self.metadata.name

    def condition(self, ctype: str) -> Optional[NodeCondition]:
        for cond in self.status.conditions:
            if cond.type == ctype:
                return cond
        return None

    def is_ready(self) -> bool:
        cond = self.condition("Ready")
        return cond is not None and cond.status == "True"


# ---------------------------------------------------------------- priority


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass, trimmed to the fields the
    admission-time priority resolution consumes
    (scheduling/priority.py): a named integer priority, the
    cluster-wide default flag, and the preemption policy gate the
    provisioner's preemption controller honors."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    # PreemptLowerPriority | Never — pods of a Never class still sort
    # above lower priorities but never nominate victims
    preemption_policy: str = "PreemptLowerPriority"

    kind = "PriorityClass"

    @property
    def key(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------- workloads


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class DaemonSetSpec:
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)

    kind = "DaemonSet"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class PodDisruptionBudgetSpec:
    selector: LabelSelector = field(default_factory=LabelSelector)
    min_available: Optional[int | str] = None    # int or percentage "50%"
    max_unavailable: Optional[int | str] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)

    kind = "PodDisruptionBudget"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


# ---------------------------------------------------------------- storage


@dataclass
class PersistentVolumeClaimSpec:
    storage_class_name: Optional[str] = None
    volume_name: str = ""  # bound PV name


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    # claim phase; "Lost" marks a claim bound to a vanished volume
    # (kube-scheduler rejects such pods, volumetopology.go:178-181)
    phase: str = ""

    kind = "PersistentVolumeClaim"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    zones: Optional[list[str]] = None  # allowedTopologies zones, None = any
    # "Immediate" claims must already be bound before scheduling;
    # "WaitForFirstConsumer" claims bind after placement. Default
    # mirrors the API server's defaulting of an unset field.
    volume_binding_mode: str = "Immediate"

    kind = "StorageClass"

    @property
    def key(self) -> str:
        return self.metadata.name


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    zones: Optional[list[str]] = None  # nodeAffinity-derived zone restriction
    attached_node: str = ""            # for volume-detachment tracking

    kind = "PersistentVolume"

    @property
    def key(self) -> str:
        return self.metadata.name


@dataclass
class CSINode:
    """Per-node CSI driver attach limits (the reference reads these
    from CSINode.spec.drivers[].allocatable.count, volumeusage.go)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    volume_limits: dict[str, int] = field(default_factory=dict)  # driver -> max

    kind = "CSINode"

    @property
    def key(self) -> str:
        return self.metadata.name


@dataclass
class KubeEvent:
    """corev1.Event, trimmed to what the recorder emits
    (pkg/events/recorder.go:52-72 publishes through
    record.EventRecorder; operators debug real clusters by reading
    these off `kubectl describe`)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    type: str = "Normal"      # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    source_component: str = "karpenter"

    kind = "Event"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"
