"""Real-API-server client: the KubeClient surface over HTTP CRs.

Counterpart of the controller-runtime client+cache stack the reference
builds in pkg/operator/operator.go:117-249. The in-memory
`kube.client.KubeClient` IS this framework's API server for
simulation; this module is the adapter that lets the same controllers
run against a real cluster:

- `RealKubeClient` implements the KubeClient surface (create / get /
  list / update / delete / touch / remove_finalizer / watch / deliver
  / typed sugar) on top of a `Transport` speaking Kubernetes REST:
  GET/POST/PUT/DELETE on resource paths, `409` mapped to
  ConflictError (optimistic concurrency on metadata.resourceVersion),
  and incremental WATCH streams.
- Reads are INFORMER-CACHE reads: a local mirror of typed objects fed
  by watch events, pumped by `deliver()` once per operator tick —
  identical staleness semantics to the in-memory client's
  async-delivery mode, which is why `Cluster.synced()` just works.
- Writes push the typed object as a CR dict (kube/serialize.py) and
  stamp the server-assigned resourceVersion back onto the SAME typed
  instance, preserving the in-place-mutation controller pattern.
- Self-originated watch events (resourceVersion <= mirror's) are
  deduped, so a controller never has its canonical object replaced by
  the echo of its own write.
- Every transport request funnels through `_request` and the
  kube/retry.py RetryPolicy: 429s honor Retry-After under full-jitter
  backoff, 5xx retries within a per-call budget, and PUT 409s resolve
  through targeted re-GET + read-modify-write re-apply (`update`
  takes an optional mutation fn); 410 Gone on a watch triggers a
  bounded relist. Fault sites (solver/faults.py kube_* kinds) hook
  both transports so chaos specs replay deterministically over HTTP
  or in memory.

Transports:
- `HTTPTransport`: stdlib urllib against an API server URL with a
  bearer token / client CA (kubeconfig-lite); used on a live cluster.
- `InMemoryApiServer`: a faithful server-side implementation (CR dict
  store, resourceVersion counters, finalizer-aware deletes, watch
  event log, admission validation) used by tests and sims — the
  recorded-fixture stand-in for etcd+apiserver, mirroring what
  pkg/test/environment.go:138-197 does with envtest.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Iterable, Optional

from karpenter_tpu.kube.client import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    EvictionBlockedError,
    InvalidError,
    NotFoundError,
    WatchHandler,
)
from karpenter_tpu.kube.objects import LabelSelector
from karpenter_tpu.kube.retry import RetryPolicy
from karpenter_tpu.kube.serialize import FROM_CR, from_cr, to_cr
from karpenter_tpu.metrics.store import (
    KUBE_RELIST,
    STATE_SHARD_RELIST,
    STATE_SHARDS,
)
from karpenter_tpu.state.shards import route_key, shard_count, shard_of, SHARDED_KINDS
from karpenter_tpu.solver import faults as _faults

# kind -> (api prefix, plural, namespaced)
RESOURCES = {
    "NodePool": ("/apis/karpenter.sh/v1", "nodepools", False),
    "NodeClaim": ("/apis/karpenter.sh/v1", "nodeclaims", False),
    "NodeOverlay": ("/apis/karpenter.sh/v1alpha1", "nodeoverlays", False),
    "Pod": ("/api/v1", "pods", True),
    "Node": ("/api/v1", "nodes", False),
    "DaemonSet": ("/apis/apps/v1", "daemonsets", True),
    "PodDisruptionBudget": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "PersistentVolumeClaim": ("/api/v1", "persistentvolumeclaims", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
    "Event": ("/api/v1", "events", True),
}

# kinds the client writes but neither LISTs on boot nor watches —
# Events flow one way (recorder -> apiserver), and LISTing every Event
# cluster-wide would be pure load (the reference's EventRecorder never
# reads them back either)
WRITE_ONLY_KINDS = ("Event",)

# kinds the simulation store carries that have no real-cluster codec
# yet; list() returns empty for them rather than failing the operator
UNMAPPED_KINDS = ("StorageClass", "PersistentVolume", "CSINode")


def _path(kind: str, name: str = "", namespace: str = "") -> str:
    prefix, plural, namespaced = RESOURCES[kind]
    parts = [prefix]
    if namespaced and namespace:
        parts += ["namespaces", namespace]
    parts.append(plural)
    if name:
        parts.append(name)
    return "/".join(parts)


def _refresh_in_place(dst, src) -> None:
    """Copy `src`'s data onto `dst` preserving `dst`'s identity (the
    informer-cache replace minus the identity break, shared by _apply
    and the 409-recovery _graft so the two can't drift). Not every
    kind is spec/status shaped (Lease carries holder/renew fields), so
    copy whatever data attributes the fresh object has."""
    dst.metadata = src.metadata
    for attr in ("spec", "status", "status_conditions",
                 "holder", "renew_time", "lease_duration"):
        if hasattr(src, attr):
            setattr(dst, attr, getattr(src, attr))


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        self.status = status
        super().__init__(f"HTTP {status}: {message}")


# -- kube fault sites (solver/faults.py kinds kube_* / operator_crash) --------
#
# Both transports route every request/watch drain through these hooks,
# so a KARPENTER_FAULTS spec drives the SAME deterministic sequence
# counters whether the stack runs over HTTP or in memory. The raised
# fault is consumed here and mapped to the HTTP status a real API
# server would answer — clients exercise their genuine status-code
# paths, never a foreign exception type.

_PLURALS = frozenset(plural for _, plural, _ in RESOURCES.values())


def _fault_site(method: str, path: str) -> str:
    if method != "GET":
        return "kube_write"
    last = path.rstrip("/").rsplit("/", 1)[-1]
    return "kube_list" if last in _PLURALS else "kube_read"


def _fire_request_fault(method: str, path: str):
    """Fire the request's fault site. Returns None (no fault), a
    ("respond", status, body) synthesized answer, ("stale",) to
    re-serve the previous LIST, or ("partial",) to land the write but
    lose the response."""
    try:
        _faults.fire(_fault_site(method, path))
    except _faults.KubeConflictError as err:
        return ("respond", 409, {"message": str(err), "reason": "Conflict"})
    except _faults.KubeThrottleError as err:
        return ("respond", 429, {
            "message": str(err), "reason": "TooManyRequests",
            "details": {"retryAfterSeconds": err.retry_after},
        })
    except _faults.StaleListError:
        return ("stale",)
    except _faults.WritePartialError:
        return ("partial",)
    return None


def _fire_watch_fault(kind: str) -> None:
    """Fire the kube_watch site; a drop surfaces as the 410 Gone a
    real apiserver answers when the stream's resourceVersion fell off
    its event horizon."""
    try:
        _faults.fire("kube_watch")
    except _faults.WatchDropError as err:
        raise ApiError(410, f"watch of {kind} dropped: {err}") from None


class _KindWatch:
    """One kind's long-lived watch stream: a daemon thread holds the
    chunked `watch=true` response open, parses line-delimited watch
    events, and queues (event, object-CR, rv) tuples for the pump.
    Reconnects from the last seen rv when the server closes the
    stream (timeoutSeconds); BOOKMARK events advance rv without
    queueing; an ERROR/410 marks the stream `gone` for re-list."""

    def __init__(self, transport: "HTTPTransport", kind: str, since_rv: int):
        self.transport = transport
        self.kind = kind
        self.rv = since_rv
        self._queue: list[tuple[str, dict, int]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.gone = False
        self.dead = False
        self._resp = None
        self._thread = threading.Thread(
            target=self._run, name=f"watch-{kind}", daemon=True
        )
        self._thread.start()

    def drain(self) -> list[tuple[str, dict, int]]:
        with self._lock:
            out, self._queue = self._queue, []
        return out

    def stop(self) -> None:
        self._stop.set()
        resp = self._resp
        if resp is not None:
            # close() alone does NOT interrupt a readline blocked in
            # recv(); shutting the socket down does, immediately
            try:
                import socket as _socket

                sock = getattr(getattr(resp, "fp", None), "raw", None)
                sock = getattr(sock, "_sock", None)
                if sock is not None:
                    sock.shutdown(_socket.SHUT_RDWR)
            except Exception:
                pass
            try:
                resp.close()
            except Exception:
                pass
        self._thread.join(timeout=2.0)

    # -- reader thread ---------------------------------------------------

    def _run(self) -> None:
        import urllib.error

        from karpenter_tpu.utils.backoff import jitter

        # reconnect backoff is jittered ([0.5, 1.0) of the exponential
        # window): an API-server restart drops EVERY watcher at once,
        # and synchronized un-jittered reconnects would stampede it at
        # exactly 0.2s, 0.4s, ... after it comes back
        backoff = 0.2
        while not self._stop.is_set():
            try:
                self._read_stream()
                if self.gone:
                    break  # in-band ERROR/410: caller must re-list
                backoff = 0.2  # clean server-side timeout; reconnect
            except urllib.error.HTTPError as err:
                if err.code == 410:
                    self.gone = True
                    break
                self._stop.wait(backoff * jitter())
                backoff = min(10.0, backoff * 2)
            except Exception:
                if self._stop.is_set():
                    break
                self._stop.wait(backoff * jitter())
                backoff = min(10.0, backoff * 2)
        self.dead = True

    def _read_stream(self) -> None:
        import ssl
        import urllib.parse
        import urllib.request

        params = {
            "watch": "true",
            "resourceVersion": str(self.rv),
            "allowWatchBookmarks": "true",
            # never 0: sub-second configs would truncate to "expire
            # immediately" and tight-loop reconnects
            "timeoutSeconds": str(max(
                1, int(self.transport.watch_timeout_seconds)
            )),
        }
        url = (self.transport.base_url + _path(self.kind)
               + "?" + urllib.parse.urlencode(params))
        req = urllib.request.Request(url, method="GET")
        req.add_header("Accept", "application/json")
        token = self.transport._bearer()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        context = None
        if self.transport.ca_file:
            context = ssl.create_default_context(cafile=self.transport.ca_file)
        # read timeout must outlast server-side quiet periods between
        # bookmarks; a timeout just forces a clean reconnect
        with urllib.request.urlopen(
            req, timeout=self.transport.watch_timeout_seconds + 30.0,
            context=context,
        ) as resp:
            self._resp = resp
            try:
                for raw in resp:
                    if self._stop.is_set():
                        return
                    line = raw.strip()
                    if not line:
                        continue
                    self._handle(json.loads(line))
                    if self.gone:
                        return
            finally:
                self._resp = None

    def _handle(self, event: dict) -> None:
        etype = event.get("type", "")
        obj = event.get("object", {}) or {}
        if etype == "ERROR":
            if obj.get("code") == 410:
                self.gone = True
            return
        rv = int(obj.get("metadata", {}).get("resourceVersion", "0") or 0)
        if rv:
            self.rv = max(self.rv, rv)
        if etype == "BOOKMARK":
            return
        with self._lock:
            self._queue.append((etype, obj, rv))
        # reactive wake (ISSUE 17): tell the embedder an event is
        # queued so its live loop runs deliver() now instead of
        # sleeping the tick interval out. Fired outside the lock; the
        # hook must be cheap and thread-safe (threading.Event.set)
        hook = getattr(self.transport, "on_watch_event", None)
        if hook is not None:
            try:
                hook(self.kind)
            except Exception:
                pass


class HTTPTransport:
    """Kubernetes REST over stdlib urllib (kubeconfig-lite: host +
    bearer token). Watch is the real protocol: one background reader
    per kind holds a `watch=true&allowWatchBookmarks=true` chunked
    stream open (operator.go:157-201's informer machinery), queueing
    events that `watch_events()` drains on each deliver() pump; a
    410 Gone surfaces as ApiError(410) so the client re-lists. The
    old LIST-diff snapshot poll remains available as an explicit
    fallback (`snapshot_watch=True`) for API servers without watch."""

    def __init__(self, base_url: str, token: str = "",
                 ca_file: Optional[str] = None, timeout: float = 30.0,
                 token_file: Optional[str] = None,
                 snapshot_watch: bool = False,
                 watch_timeout_seconds: float = 290.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        # bound service-account tokens expire (~1h) and the kubelet
        # refreshes the projected file: re-read per request (mtime-
        # cached) instead of pinning the boot-time value
        self.token_file = token_file
        self._token_mtime = 0.0
        self.ca_file = ca_file
        self.timeout = timeout
        self.snapshot_watch = snapshot_watch
        self.watch_timeout_seconds = watch_timeout_seconds
        self._streams: dict[str, _KindWatch] = {}
        self._gone_pending: set[str] = set()  # kinds owing a 410
        self._streams_lock = threading.Lock()
        self._list_cache: dict[str, dict] = {}  # path -> last LIST body
        # queued-event hook (ISSUE 17): the watch reader threads call
        # this (with the kind) the moment an event lands, so an
        # event-driven embedder can wake its loop sub-tick
        self.on_watch_event = None

    def set_event_hook(self, hook) -> None:
        self.on_watch_event = hook

    def _bearer(self) -> str:
        if self.token_file:
            import os as _os

            try:
                mtime = _os.stat(self.token_file).st_mtime
                if mtime != self._token_mtime:
                    with open(self.token_file) as fh:
                        self.token = fh.read().strip()
                    self._token_mtime = mtime
            except OSError:
                pass
        return self.token

    def request(self, method: str, path: str, body: Optional[dict] = None,
                params: Optional[dict] = None) -> tuple[int, dict]:
        injected = _fire_request_fault(method, path)
        if injected is not None:
            if injected[0] == "respond":
                return injected[1], injected[2]
            if injected[0] == "stale" and path in self._list_cache:
                return 200, json.loads(json.dumps(self._list_cache[path]))
            # "partial": perform the request, then lose the response
        status, detail = self._request_raw(method, path, body, params)
        if injected is not None and injected[0] == "partial":
            return 500, {"message": "injected write-partial: response lost"}
        if (method == "GET" and status == 200 and "items" in detail
                and _faults.get() is not None):
            # remember the last good LIST so an injected stale read has
            # a genuinely old snapshot to serve; only while a fault
            # spec is live — the deep copy is O(cluster) per LIST and
            # the healthy path must not pay it
            self._list_cache[path] = json.loads(json.dumps(detail))
        return status, detail

    def _request_raw(self, method: str, path: str, body: Optional[dict],
                     params: Optional[dict]) -> tuple[int, dict]:
        import ssl
        import urllib.error
        import urllib.parse
        import urllib.request

        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        req.add_header("Accept", "application/json")
        token = self._bearer()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        context = None
        if self.ca_file:
            context = ssl.create_default_context(cafile=self.ca_file)
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=context
            ) as resp:
                payload = resp.read()
                return resp.status, json.loads(payload) if payload else {}
        except urllib.error.HTTPError as err:
            payload = err.read()
            try:
                detail = json.loads(payload) if payload else {}
            except ValueError:
                detail = {"message": payload.decode(errors="replace")}
            retry_after = err.headers.get("Retry-After") if err.headers else None
            if retry_after is not None:
                # fold the header into the Status body where
                # kube/retry.py reads it (apiservers ship both)
                try:
                    detail.setdefault("details", {}).setdefault(
                        "retryAfterSeconds", float(retry_after)
                    )
                except (ValueError, AttributeError):
                    pass
            return err.code, detail

    # LIST-diff fallback (snapshot_watch=True): the client re-lists
    # every kind per pump and diffs against its mirror. O(cluster)
    # apiserver load, so RealKubeClient throttles these pumps
    # (snapshot_poll_seconds); streaming is the default.
    snapshot_poll_seconds = 5.0

    def watch_events(self, kind: str, since_rv: int) -> list:
        """Drain the kind's background stream (starting it on first
        use at `since_rv`). Raises ApiError(410) when the server
        declared the resourceVersion too old — the caller re-lists
        and the next call restarts the stream from the fresh rv."""
        try:
            _fire_watch_fault(kind)
        except ApiError:
            # injected drop: kill the live stream too, so the next
            # call restarts one from the post-relist rv
            with self._streams_lock:
                stream = self._streams.pop(kind, None)
            if stream is not None:
                stream.stop()
            raise
        with self._streams_lock:
            if kind in self._gone_pending:
                # consume the deferred 410 exactly once; the NEXT call
                # (post-re-list) starts a fresh stream
                self._gone_pending.discard(kind)
                raise ApiError(410, f"watch of {kind} too old")
            stream = self._streams.get(kind)
            if stream is None or stream.dead:
                if stream is not None and stream.gone:
                    self._streams.pop(kind, None)
                    stream.stop()
                    raise ApiError(410, f"watch of {kind} too old")
                stream = _KindWatch(self, kind, since_rv)
                self._streams[kind] = stream
        events = stream.drain()
        if stream.gone:
            with self._streams_lock:
                self._streams.pop(kind, None)
            stream.stop()
            if events:
                # deliver what arrived; the 410 stays PENDING so the
                # next pump re-lists instead of spinning up another
                # stream at a still-compacted rv
                with self._streams_lock:
                    self._gone_pending.add(kind)
                return events
            raise ApiError(410, f"watch of {kind} too old")
        return events

    def close(self) -> None:
        with self._streams_lock:
            streams, self._streams = dict(self._streams), {}
        for stream in streams.values():
            stream.stop()


class _ServerPdbView:
    """Just enough of the KubeClient read surface for PdbLimits to run
    INSIDE the API server (the server enforces PDBs on the eviction
    subresource; clients never see the math, only the 429)."""

    def __init__(self, server: "InMemoryApiServer"):
        self._server = server

    def pdbs(self):
        return [
            from_cr(cr)
            for cr in self._server._bucket("PodDisruptionBudget").values()
        ]

    def pods(self, namespace: Optional[str] = None, selector=None):
        out = []
        for cr in self._server._bucket("Pod").values():
            if namespace and cr["metadata"].get("namespace", "") != namespace:
                continue
            pod = from_cr(cr)
            if selector is not None and not selector.matches(
                pod.metadata.labels
            ):
                continue
            out.append(pod)
        return out


class InMemoryApiServer:
    """Server-side semantics of a real API server over CR dicts: RV
    counters, conflict checks, finalizer-aware deletion, a watch event
    log, and the same admission validation the CRDs carry as CEL."""

    snapshot_watch = False  # serves a true event log incl. DELETED

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._store: dict[str, dict[str, dict]] = {}
        self._rv = 0
        self._events: list[tuple[str, str, dict, int]] = []  # kind, ev, cr, rv
        # rv horizon: events at or below this were compacted away; a
        # watch resuming from below it gets 410 Gone (etcd compaction)
        self._compacted_rv = 0
        self._list_cache: dict[str, dict] = {}  # path -> last LIST body

    # -- request API (the Transport protocol) ---------------------------

    def request(self, method: str, path: str, body: Optional[dict] = None,
                params: Optional[dict] = None) -> tuple[int, dict]:
        injected = _fire_request_fault(method, path)
        if injected is not None:
            if injected[0] == "respond":
                return injected[1], injected[2]
            if injected[0] == "stale" and path in self._list_cache:
                return 200, json.loads(json.dumps(self._list_cache[path]))
        status, detail = self._handle(method, path, body)
        if injected is not None and injected[0] == "partial":
            # the write LANDED; the response is lost on the wire
            return 500, {"message": "injected write-partial: response lost"}
        if (method == "GET" and status == 200 and "items" in detail
                and _faults.get() is not None):
            # last-good-LIST snapshot for kube_stale_list; fault runs
            # only (the copy is O(cluster) per LIST)
            self._list_cache[path] = json.loads(json.dumps(detail))
        return status, detail

    def _handle(self, method: str, path: str,
                body: Optional[dict]) -> tuple[int, dict]:
        kind, name, namespace, subresource = self._parse(path)
        if kind is None:
            return 404, {"message": f"unknown path {path}"}
        with self._lock:
            if subresource == "binding" and method == "POST":
                return self._bind(kind, namespace, name, body or {})
            if subresource == "eviction" and method == "POST":
                return self._evict(kind, namespace, name)
            if method == "GET" and not name:
                items = list(self._bucket(kind).values())
                if namespace:
                    items = [
                        i for i in items
                        if i["metadata"].get("namespace") == namespace
                    ]
                return 200, {"items": [json.loads(json.dumps(i)) for i in items],
                             "metadata": {"resourceVersion": str(self._rv)}}
            if method == "GET":
                cr = self._bucket(kind).get(self._key(kind, namespace, name))
                if cr is None:
                    return 404, {"message": "not found"}
                return 200, json.loads(json.dumps(cr))
            if method == "POST":
                return self._create(kind, body or {})
            if method == "PUT":
                return self._update(kind, namespace, name, body or {})
            if method == "DELETE":
                return self._delete(kind, namespace, name)
        return 405, {"message": method}

    def watch_events(self, kind: str, since_rv: int) -> list[tuple[str, dict, int]]:
        _fire_watch_fault(kind)
        with self._lock:
            if since_rv < self._compacted_rv:
                raise ApiError(
                    410, f"resourceVersion {since_rv} is too old "
                         f"(compacted through {self._compacted_rv})"
                )
            return [
                (ev, json.loads(json.dumps(cr)), rv)
                for k, ev, cr, rv in self._events
                if k == kind and rv > since_rv
            ]

    def compact(self, keep: int = 0) -> None:
        """Discard the event log except the last `keep` entries (etcd
        compaction analogue — watchers resuming from before the new
        horizon get 410 Gone and must re-list)."""
        with self._lock:
            cut = len(self._events) - keep
            if cut > 0:
                self._compacted_rv = self._events[cut - 1][3]
                del self._events[:cut]

    # -- internals -------------------------------------------------------

    def _bucket(self, kind: str) -> dict[str, dict]:
        return self._store.setdefault(kind, {})

    @staticmethod
    def _key(kind: str, namespace: str, name: str) -> str:
        _, _, namespaced = RESOURCES[kind]
        return f"{namespace}/{name}" if namespaced else name

    def _parse(self, path: str):
        for kind, (prefix, plural, namespaced) in RESOURCES.items():
            if not path.startswith(prefix + "/"):
                continue
            rest = path[len(prefix) + 1:].split("/")
            namespace = ""
            if rest and rest[0] == "namespaces" and len(rest) >= 2:
                namespace = rest[1]
                rest = rest[2:]
            if not rest or rest[0] != plural:
                continue
            name = rest[1] if len(rest) > 1 else ""
            subresource = rest[2] if len(rest) > 2 else ""
            return kind, name, namespace, subresource
        return None, "", "", ""

    def _admit(self, kind: str, cr: dict, old: Optional[dict]) -> Optional[str]:
        """CRD admission (apis/v1/validation.py — the CEL analogue)."""
        from karpenter_tpu.apis.v1.validation import (
            ValidationError,
            validate_node_claim,
            validate_node_pool,
        )

        try:
            if kind == "NodePool":
                validate_node_pool(
                    from_cr(cr), old=from_cr(old) if old else None
                )
            elif kind == "NodeClaim":
                if old is None:
                    validate_node_claim(from_cr(cr))
                elif old.get("spec") != cr.get("spec"):
                    return "NodeClaim spec is immutable"
        except ValidationError as err:
            return str(err)
        return None

    def _emit(self, kind: str, event: str, cr: dict) -> None:
        self._events.append((kind, event, json.loads(json.dumps(cr)), self._rv))
        if len(self._events) > 100_000:
            self.compact(keep=50_000)

    def _create(self, kind: str, cr: dict) -> tuple[int, dict]:
        meta = cr.setdefault("metadata", {})
        key = self._key(kind, meta.get("namespace", ""), meta.get("name", ""))
        bucket = self._bucket(kind)
        if key in bucket:
            return 409, {"message": f"{kind} {key} already exists"}
        reason = self._admit(kind, cr, None)
        if reason is not None:
            return 422, {"message": reason}
        self._rv += 1
        meta["resourceVersion"] = str(self._rv)
        meta["generation"] = 1
        bucket[key] = json.loads(json.dumps(cr))
        self._emit(kind, ADDED, bucket[key])
        return 201, json.loads(json.dumps(bucket[key]))

    def _update(self, kind: str, namespace: str, name: str,
                cr: dict) -> tuple[int, dict]:
        key = self._key(kind, namespace, name)
        bucket = self._bucket(kind)
        existing = bucket.get(key)
        if existing is None:
            return 404, {"message": "not found"}
        sent_rv = int(cr.get("metadata", {}).get("resourceVersion", "0") or 0)
        have_rv = int(existing["metadata"].get("resourceVersion", "0"))
        if sent_rv and sent_rv != have_rv:
            # full optimistic concurrency, as a real apiserver enforces
            # it: ANY mismatch is a conflict, not just a stale-older
            # write — last-write-wins must never silently clobber a
            # concurrent actor (the conflict-retry wrapper in
            # RealKubeClient re-GETs and re-applies)
            return 409, {
                "message": "resourceVersion conflict: "
                           f"sent {sent_rv}, have {have_rv}",
                "reason": "Conflict",
            }
        reason = self._admit(kind, cr, existing)
        if reason is not None:
            return 422, {"message": reason}
        self._rv += 1
        cr = json.loads(json.dumps(cr))
        cr["metadata"]["resourceVersion"] = str(self._rv)
        # deletion progresses server-side: with a deletionTimestamp set
        # and the last finalizer gone, the write finalizes the delete
        if cr["metadata"].get("deletionTimestamp") and not cr["metadata"].get(
            "finalizers"
        ):
            del bucket[key]
            self._emit(kind, DELETED, cr)
            return 200, cr
        bucket[key] = cr
        self._emit(kind, MODIFIED, cr)
        return 200, json.loads(json.dumps(cr))

    def _delete(self, kind: str, namespace: str, name: str) -> tuple[int, dict]:
        key = self._key(kind, namespace, name)
        bucket = self._bucket(kind)
        cr = bucket.get(key)
        if cr is None:
            return 404, {"message": "not found"}
        meta = cr["metadata"]
        if meta.get("finalizers"):
            if not meta.get("deletionTimestamp"):
                from karpenter_tpu.kube.serialize import ts_to_rfc3339
                import time as _time

                self._rv += 1
                meta["deletionTimestamp"] = ts_to_rfc3339(_time.time())
                meta["resourceVersion"] = str(self._rv)
                self._emit(kind, MODIFIED, cr)
            return 200, json.loads(json.dumps(cr))
        self._rv += 1
        # stamp the deletion rv (real apiservers do): watch clients
        # advance their cursor from the OBJECT's rv, so a stale
        # embedded rv would make them replay this DELETED forever
        meta["resourceVersion"] = str(self._rv)
        del bucket[key]
        self._emit(kind, DELETED, cr)
        return 200, json.loads(json.dumps(cr))

    def _evict(self, kind: str, namespace: str,
               name: str) -> tuple[int, dict]:
        """policy/v1 Eviction subresource: PDBs are consulted SERVER-
        side (what the real API server does; eviction.go:170-185 is
        the client reacting to this 429). Allowed evictions proceed as
        graceful deletes, finalizer semantics included."""
        if kind != "Pod":
            return 404, {"message": "eviction is a pod subresource"}
        key = self._key(kind, namespace, name)
        cr = self._bucket(kind).get(key)
        if cr is None:
            return 404, {"message": "not found"}
        from karpenter_tpu.utils.pdb import PdbLimits

        blocking = PdbLimits(_ServerPdbView(self)).can_evict(
            from_cr(cr), server_side=True
        )
        if blocking is not None:
            # one source of truth for the denial text (the client's
            # exception renders it identically)
            return 429, {
                "message": str(EvictionBlockedError(blocking)),
                "reason": "TooManyRequests",
                "details": {"causes": [{"reason": "DisruptionBudget",
                                        "message": blocking}]},
            }
        return self._delete(kind, namespace, name)

    def _bind(self, kind: str, namespace: str, name: str,
              binding: dict) -> tuple[int, dict]:
        if kind != "Pod":
            return 404, {"message": "binding is a pod subresource"}
        key = self._key(kind, namespace, name)
        cr = self._bucket(kind).get(key)
        if cr is None:
            return 404, {"message": "not found"}
        self._rv += 1
        cr.setdefault("spec", {})["nodeName"] = (
            binding.get("target", {}).get("name", "")
        )
        cr["metadata"]["resourceVersion"] = str(self._rv)
        self._emit(kind, MODIFIED, cr)
        return 201, {}


class RealKubeClient:
    """KubeClient surface over a Transport (see module docstring)."""

    # A real cluster HAS workload controllers (ReplicaSets recreate
    # evicted replicas; kube-scheduler binds them): controllers must
    # never fabricate pods here — see EvictionQueue.
    simulates_workload_controllers = False

    def __init__(self, transport, kinds: Optional[Iterable[str]] = None):
        self.transport = transport
        self.kinds = (list(kinds) if kinds is not None
                      else [k for k in RESOURCES if k not in WRITE_ONLY_KINDS])
        self._lock = threading.RLock()
        self._mirror: dict[str, dict[str, object]] = {k: {} for k in self.kinds}
        self._last_rv: dict[str, int] = {k: 0 for k in self.kinds}
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._pending_events: list[tuple[str, str, object]] = []
        self._pods_by_node: dict[str, set[str]] = {}
        self._pod_node: dict[str, str] = {}
        self.async_delivery = True  # cache semantics are inherent here
        self._last_pump = 0.0
        self._relist_at: dict[str, float] = {}  # kind -> last 410 relist
        # monotone per-kind relist counter: DirtyTracker.relisted reads
        # it so retained-state consumers can mark everything dirty once
        # per lost-continuity window (the relist's diff events alone
        # cannot prove nothing else changed while the watch was stale)
        self._relist_gen: dict[str, int] = {}
        # sharded logical streams (state/shards.py): per-shard watch
        # cursors + relist generations for the node-keyed kinds. The
        # pump groups shards by cursor value, so the steady state (all
        # cursors equal) is ONE watch scan with zero routing work;
        # cursors diverge only across a shard-scoped relist window.
        self._shards = shard_count()
        STATE_SHARDS.set(float(self._shards))
        self._shard_rv: dict[str, list[int]] = {
            k: [0] * self._shards for k in self.kinds if k in SHARDED_KINDS
        }
        self._shard_relist_gen: dict[str, list[int]] = {
            k: [0] * self._shards for k in self._shard_rv
        }
        # reactive wake seam (ISSUE 17): called whenever events are
        # known to be pending delivery — from the transport's watch
        # reader threads (async) and from self-originated writes'
        # _announce (sync) — so the operator's live loop can sleep on
        # an Event instead of polling deliver()
        self._event_pending_hook = None
        # deletion tombstones (kind -> key -> deletion rv), recorded
        # only while shard cursors are divergent: a behind shard's
        # replay of a pre-delete MODIFIED must not resurrect a key a
        # faster shard (or a scoped relist) already deleted. Cleared
        # when a kind's cursors reconverge to a single group.
        self._tombstones: dict[str, dict[str, int]] = {}
        self.sync()
        with self._lock:
            for kind in self._shard_rv:
                rv = self._last_rv.get(kind, 0)
                self._shard_rv[kind] = [rv] * self._shards

    # -- transport funnel --------------------------------------------------

    def _request(self, verb: str, method: str, path: str,
                 body: Optional[dict] = None, body_fn=None,
                 on_conflict=None) -> tuple[int, dict]:
        """EVERY transport request goes through here (statically
        enforced by tests/test_kube_write_sites.py): the env-tuned
        RetryPolicy (kube/retry.py) absorbs 429 storms and apiserver
        5xx hiccups under per-call budgets, and 409s re-enter through
        the caller's targeted re-GET + re-apply hook. `body_fn`
        re-renders the payload per attempt so a conflict hook's
        mutation lands in the retried write."""

        def attempt() -> tuple[int, dict]:
            return self.transport.request(
                method, path, body_fn() if body_fn is not None else body
            )

        return RetryPolicy.current().execute(
            verb, attempt, on_conflict=on_conflict
        )

    # -- informer machinery ----------------------------------------------

    def _from_item(self, kind: str, item: dict):
        """Parse one LIST/watch item. The kind comes from the REQUEST
        context: real API servers omit TypeMeta (kind/apiVersion) on
        the items inside a List response, so dispatching on
        item['kind'] would crash on the very first LIST against a live
        cluster."""
        return FROM_CR[kind](item)

    # kinds whose CRD may legitimately be absent (alpha, feature-gated);
    # a 404 for anything else is a misconfiguration and must fail boot
    OPTIONAL_KINDS = frozenset({"NodeOverlay"})

    def sync(self) -> None:
        """Initial LIST per kind (informer start). A 404 for an
        OPTIONAL kind means its CRD is not installed (e.g. the alpha
        NodeOverlay CRD behind a disabled feature gate): drop the kind
        and keep booting — steady-state _pump tolerates the same
        absence. A 404 for a core kind, or any other error, is a real
        connectivity/configuration problem and fails fast."""
        for kind in list(self.kinds):
            status, body = self._request("list", "GET", _path(kind))
            if status == 404 and kind in self.OPTIONAL_KINDS:
                self.kinds.remove(kind)
                self._mirror.pop(kind, None)
                continue
            if status != 200:
                raise ApiError(status, str(body))
            for item in body.get("items", []):
                obj = self._from_item(kind, item)
                with self._lock:
                    self._mirror[kind][obj.key] = obj
                    self._index_pod(obj)
                    self._last_rv[kind] = max(
                        self._last_rv[kind], obj.metadata.resource_version
                    )

    def _pump(self) -> None:
        """Pull new watch state from the server into the pending queue,
        applying it to the mirror. Two transport styles:

        - event-log (InMemoryApiServer): replay events newer than the
          per-kind high-water resourceVersion;
        - snapshot (HTTPTransport LIST-diff): diff the listed items
          against the mirror, synthesizing DELETED for keys that
          vanished — a real cluster's deletes by OTHER actors must
          reach the mirror even without a streaming watch. Snapshot
          pumps are throttled (snapshot_poll_seconds) because each one
          is an O(cluster) LIST.

        Per-object staleness guard: an item whose rv the mirror already
        reflects is skipped, so a controller's canonical object is
        never replaced by the echo of its own write."""
        if getattr(self.transport, "snapshot_watch", False):
            import time as _time

            interval = getattr(self.transport, "snapshot_poll_seconds", 5.0)
            now = _time.monotonic()
            if now - self._last_pump < interval:
                return
            self._last_pump = now
            for kind in self.kinds:
                # snapshot pump IS a relist per kind (already throttled
                # by snapshot_poll_seconds; not a 410 reaction)
                self._relist(kind, reason="snapshot")
            return
        for kind in self.kinds:
            shard_rv = self._shard_rv.get(kind)
            if shard_rv is None:
                # unsharded (fleet-wide) kind: single logical stream
                try:
                    events = self.transport.watch_events(
                        kind, self._last_rv[kind]
                    )
                except ApiError as err:
                    if err.status == 410:
                        # watch fell off the server's event horizon:
                        # re-LIST and diff (informer relist), then the
                        # next pump restarts the stream at the fresh rv
                        self._relist(kind)
                    continue
                for event, cr, rv in events:
                    with self._lock:
                        self._last_rv[kind] = max(self._last_rv[kind], rv)
                    self._ingest(kind, event, cr, rv)
                continue
            # sharded kind: ONE watch scan per DISTINCT cursor value.
            # Steady state — all shard cursors equal — is a single
            # group covering every shard, i.e. exactly the unsharded
            # scan with zero routing work. After a shard-scoped relist
            # the cursors diverge: each group's pass processes only the
            # events routed to its member shards (every event is owned
            # by exactly one group, so nothing is double-applied), and
            # the groups reconverge as soon as both reach stream head.
            groups: dict[int, list[int]] = {}
            for shard, cursor in enumerate(shard_rv):
                groups.setdefault(cursor, []).append(shard)
            if len(groups) == 1:
                self._tombstones.pop(kind, None)
            gone_shards: set[int] = set()
            for since_rv, members in sorted(groups.items()):
                try:
                    events = self.transport.watch_events(kind, since_rv)
                except ApiError as err:
                    if err.status == 410:
                        gone_shards.update(members)
                    continue
                member_set = (
                    None if len(members) == self._shards else set(members)
                )
                high = since_rv
                for event, cr, rv in events:
                    high = max(high, rv)
                    obj = self._from_item(kind, cr)
                    if member_set is not None and shard_of(
                        route_key(kind, obj), self._shards
                    ) not in member_set:
                        continue  # another group's pass owns this event
                    self._ingest(kind, event, cr, rv, obj=obj,
                                 tombstone=member_set is not None)
                with self._lock:
                    for shard in members:
                        shard_rv[shard] = max(shard_rv[shard], high)
                    self._last_rv[kind] = max(self._last_rv[kind], high)
            if gone_shards:
                # ONE LIST covers every lost shard; a 410 on a subset
                # of shards dirties only that subset's relist epochs
                self._relist(
                    kind,
                    shards=(sorted(gone_shards)
                            if len(gone_shards) < self._shards else None),
                )

    def _ingest(self, kind: str, event: str, cr: dict, rv: int,
                obj=None, tombstone: bool = False) -> None:
        """Apply one watch event to the mirror + pending queue.
        `tombstone` is set by divergent-cursor pump passes: the delete
        is recorded so a behind shard's replay of an older MODIFIED
        cannot resurrect the key (see _tombstones)."""
        if obj is None:
            obj = self._from_item(kind, cr)
        if event == DELETED:
            with self._lock:
                if tombstone:
                    self._tombstones.setdefault(kind, {})[obj.key] = rv
                gone = self._mirror[kind].pop(obj.key, None)
                if gone is not None:
                    # only announce deletes the mirror knew about: our
                    # own deletes were announced at write time, and
                    # never-seen objects have no consumers to notify
                    self._index_pod(gone, removed=True)
                    self._pending_events.append((kind, DELETED, gone))
            return
        self._apply(kind, obj, rv, event)

    def _relist(self, kind: str, reason: str = "watch_gone",
                shards: Optional[list[int]] = None) -> None:
        """Full LIST + mirror diff for one kind (the informer's
        reaction to 410 Gone), synthesizing DELETED for keys that
        vanished while the watch was stale. 410-driven relists are
        BOUNDED (KARPENTER_KUBE_RELIST_MIN_MS, default 500): a
        flapping watch degrades freshness by one bounded interval
        instead of hammering the apiserver with O(cluster) LISTs every
        pump — the 410 stays pending server-side, so a skipped relist
        is retried on the next pump.

        With `shards` given (and the kind sharded), the relist is
        SCOPED: one LIST still hits the server, but only items routed
        to those shards are applied, DELETED is synthesized only for
        mirror keys in those shards, and only those shards' relist
        epochs and cursors advance — every other shard's stream
        continuity (and therefore every other shard's retained rows
        downstream) stays intact."""
        scoped = shards is not None and kind in self._shard_rv
        if reason == "watch_gone":
            import os as _os
            import time as _time

            try:
                min_s = float(_os.environ.get(
                    "KARPENTER_KUBE_RELIST_MIN_MS", "500")) / 1000.0
            except ValueError:
                min_s = 0.5
            now = _time.monotonic()
            if now - self._relist_at.get(kind, float("-inf")) < min_s:
                return
            self._relist_at[kind] = now
            if scoped:
                for shard in shards:
                    STATE_SHARD_RELIST.inc(
                        {"kind": kind, "shard": str(shard)}
                    )
            else:
                KUBE_RELIST.inc({"kind": kind})
        status, body = self._request("list", "GET", _path(kind))
        if status != 200:
            return  # transient; the next pump retries
        if reason == "watch_gone":
            # only 410 relists lose event-stream continuity (snapshot
            # pumps re-LIST every cycle by design); retained-state
            # consumers key "mark everything dirty" off this — scoped
            # to the lost shards when the stream loss was scoped
            with self._lock:
                self._relist_gen[kind] = self._relist_gen.get(kind, 0) + 1
                gens = self._shard_relist_gen.get(kind)
                if gens is not None:
                    for shard in (shards if scoped
                                  else range(self._shards)):
                        gens[shard] += 1
        shard_set = set(shards) if scoped else None
        live_keys = set()
        for item in body.get("items", []):
            rv = int(item["metadata"].get("resourceVersion", "0") or 0)
            obj = self._from_item(kind, item)
            if shard_set is not None and shard_of(
                route_key(kind, obj), self._shards
            ) not in shard_set:
                continue  # other shards' mirror rows stay untouched
            live_keys.add(obj.key)
            self._apply(kind, obj, rv)
        with self._lock:
            list_rv = int(
                body.get("metadata", {}).get("resourceVersion", "0") or 0
            )
            stale = [
                key for key, cur in self._mirror[kind].items()
                if key not in live_keys and (
                    shard_set is None or shard_of(
                        route_key(kind, cur), self._shards
                    ) in shard_set
                )
            ]
            for key in stale:
                gone = self._mirror[kind].pop(key)
                self._index_pod(gone, removed=True)
                self._pending_events.append((kind, DELETED, gone))
                if shard_set is not None:
                    self._tombstones.setdefault(kind, {})[key] = list_rv
            self._last_rv[kind] = max(self._last_rv[kind], list_rv)
            cursors = self._shard_rv.get(kind)
            if cursors is not None:
                for shard in (shard_set if shard_set is not None
                              else range(self._shards)):
                    cursors[shard] = max(cursors[shard], list_rv)

    def close(self) -> None:
        """Tear down transport-side watch machinery (stream threads)."""
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()

    def _apply(self, kind: str, obj, rv: int, event: str = MODIFIED) -> None:
        """Merge one fresh object into the mirror, preserving the
        identity of the canonical instance controllers hold."""
        with self._lock:
            tomb = self._tombstones.get(kind)
            if tomb is not None and rv <= tomb.get(obj.key, -1):
                # a behind shard replaying a pre-delete event must not
                # resurrect a key another shard already deleted
                return
            current = self._mirror[kind].get(obj.key)
            if current is not None and current.metadata.resource_version >= rv:
                return  # self-echo or stale replay
            if current is not None:
                # refresh the CANONICAL instance in place so controller
                # references stay valid (informer cache replace, minus
                # the identity break)
                _refresh_in_place(current, obj)
                obj = current
            else:
                self._mirror[kind][obj.key] = obj
                event = ADDED
            self._index_pod(obj)
            self._pending_events.append((kind, event, obj))

    def set_event_pending_hook(self, hook) -> None:
        """Register a cheap thread-safe callable fired whenever watch
        events are pending delivery (the operator's reactive wake)."""
        self._event_pending_hook = hook
        forward = getattr(self.transport, "set_event_hook", None)
        if forward is not None and hook is not None:
            forward(lambda _kind: hook())

    def watch(self, kind: str, handler: WatchHandler) -> None:
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)
            for obj in self._mirror.get(kind, {}).values():
                handler(ADDED, obj)

    def deliver(self, limit: Optional[int] = None) -> int:
        self._pump()
        with self._lock:
            n = len(self._pending_events) if limit is None else min(
                limit, len(self._pending_events)
            )
            batch = self._pending_events[:n]
            del self._pending_events[:n]
        for kind, event, obj in batch:
            for handler in self._watchers.get(kind, []):
                handler(event, obj)
        return n

    def pending_events(self, kinds: Optional[Iterable[str]] = None) -> int:
        with self._lock:
            if kinds is None:
                return len(self._pending_events)
            wanted = set(kinds)
            return sum(1 for k, _, _ in self._pending_events if k in wanted)

    # -- writes ----------------------------------------------------------

    def _graft(self, obj, fresh_cr: dict) -> None:
        """Adopt the server's fresh state onto the canonical instance
        in place (identity preserved — the same refresh the informer
        _apply does, just ahead of the pump)."""
        _refresh_in_place(obj, self._from_item(obj.kind, fresh_cr))

    @staticmethod
    def _sans_stamps(cr: dict) -> dict:
        """A CR with the server-stamped metadata fields removed, for
        did-my-write-land comparisons."""
        out = json.loads(json.dumps(cr))
        meta = out.get("metadata") or {}
        meta.pop("resourceVersion", None)
        meta.pop("generation", None)
        return out

    def _push(self, method: str, obj, path: str, mutate=None) -> None:
        """Write `obj`; conflict-aware (controller-runtime's
        RetryOnConflict shape). On a 409 the hook re-GETs the server
        copy and decides:

        - server rv == ours: spurious conflict (an injected fault or a
          proxy flake) — the state never moved, re-send as-is;
        - server content == ours modulo stamps AND a prior attempt of
          THIS call lost its response (5xx in the history): OUR write
          landed (write-partial) — adopt the server rv, done. The
          history gate matters: without it, a concurrent writer
          landing IDENTICAL content would be mistaken for our own
          write and a CAS caller would silently lose an update;
        - genuine divergence: re-apply the caller's `mutate` fn on the
          refreshed object and retry (read-modify-write); without a
          mutation fn the conflict is the CALLER's to resolve —
          ConflictError, exactly as before, never last-write-wins.
        """
        get_path = _path(obj.kind, obj.metadata.name, obj.metadata.namespace)
        resolved: dict = {}
        vanished: dict = {}

        def on_conflict(history=()) -> bool:
            st, fresh = self._request("get", "GET", get_path)
            if st == 404:
                # nothing there: a POST's injected conflict (re-send);
                # a PUT's target vanished — that is a NotFound, not a
                # Conflict (a real apiserver would answer the PUT 404),
                # so touch()'s gone-object-is-a-no-op contract holds
                if method == "PUT":
                    vanished["msg"] = fresh.get("message", obj.key)
                return method == "POST"
            if st != 200:
                return False
            fresh_rv = int(
                fresh.get("metadata", {}).get("resourceVersion", "0") or 0
            )
            ours = to_cr(obj)
            if method == "PUT" and fresh_rv == int(
                ours.get("metadata", {}).get("resourceVersion", "0") or 0
            ):
                return True  # spurious: state unmoved, re-send as-is
            if any(s >= 500 for s in history) and (
                self._sans_stamps(fresh) == self._sans_stamps(ours)
            ):
                resolved["rv"] = fresh_rv  # our lost-response write landed
                return False
            if mutate is None or method == "POST":
                # genuine conflict, the CALLER resolves (ConflictError).
                # For PUTs, adopt the server truth onto the canonical
                # object first, so the caller's retry cycle (re-read ->
                # re-apply -> update) works from current state
                # immediately instead of losing a race to the next
                # informer pump — their intended write is already lost
                # either way, that is what the 409 says.
                if method == "PUT":
                    self._graft(obj, fresh)
                return False
            # true read-modify-write: graft the SERVER's fresh state
            # onto the canonical instance, then re-apply the caller's
            # mutation on top — the remote actor's fields survive,
            # ours land
            self._graft(obj, fresh)
            mutate(obj)
            return True

        status, body = self._request(
            "create" if method == "POST" else "update", method, path,
            body_fn=lambda: to_cr(obj), on_conflict=on_conflict,
        )
        if status == 409 and resolved:
            obj.metadata.resource_version = resolved["rv"]
            return
        if status == 409 and vanished:
            raise NotFoundError(vanished["msg"])
        if status == 409:
            raise ConflictError(body.get("message", "conflict"))
        if status == 404:
            raise NotFoundError(body.get("message", obj.key))
        if status == 422:
            raise InvalidError(body.get("message", "invalid"))
        if status >= 400:
            raise ApiError(status, body.get("message", ""))
        new_rv = int(
            body.get("metadata", {}).get("resourceVersion", "0") or 0
        )
        if new_rv:
            # stamp the server-assigned rv on the canonical object (the
            # per-object guard in _apply then dedupes the watch echo).
            # Deliberately do NOT advance the per-kind _last_rv here: a
            # concurrent remote event with a lower rv than our write
            # has not been pumped yet, and skipping past it would drop
            # it forever.
            obj.metadata.resource_version = new_rv

    def _announce(self, kind: str, event: str, obj) -> None:
        """Queue a watch event for a SELF-originated write: the pump
        dedupes the server's echo by resourceVersion, so local handlers
        would otherwise never hear about this process's own mutations
        (the in-memory client announces every write; controllers rely
        on it — DirtyTracker, state informers, the batcher hook)."""
        with self._lock:
            self._pending_events.append((kind, event, obj))
        if self._event_pending_hook is not None:
            try:
                self._event_pending_hook()
            except Exception:
                pass

    def create(self, obj):
        self._push("POST", obj, _path(obj.kind, namespace=obj.metadata.namespace))
        obj.metadata.generation = 1
        if obj.kind not in self._mirror:
            return obj  # write-only kind (Events): push, don't cache
        with self._lock:
            self._mirror[obj.kind][obj.key] = obj
            self._index_pod(obj)
        self._announce(obj.kind, ADDED, obj)
        return obj

    def update(self, obj, mutate=None):
        """Write the object back. `mutate` (optional) is the caller's
        intended mutation as a FUNCTION of the object — applied before
        the first attempt and RE-applied after each conflict re-GET,
        so a racy write converges to read-modify-write instead of
        last-write-wins."""
        if mutate is not None:
            mutate(obj)
        self._push(
            "PUT", obj,
            _path(obj.kind, obj.metadata.name, obj.metadata.namespace),
            mutate=mutate,
        )
        if obj.kind not in self._mirror:
            return obj  # write-only kind (Events): push, don't cache
        with self._lock:
            self._mirror[obj.kind][obj.key] = obj
            self._index_pod(obj)
        self._announce(obj.kind, MODIFIED, obj)
        return obj

    def relist_generation(self, kind: str) -> int:
        """Monotone count of 410-driven relists for one kind — the
        lost-continuity signal DirtyTracker.relisted latches."""
        with self._lock:
            return self._relist_gen.get(kind, 0)

    def relist_generations(self, kind: str) -> dict[int, int]:
        """Per-shard relist generations for one kind (empty for
        unsharded kinds) — the scoped lost-continuity signal
        DirtyTracker.relisted_shards latches. A full-stream relist
        bumps every shard's generation, so shard-aware consumers see
        it as all-shards-dirty (the merged contract's reading)."""
        with self._lock:
            gens = self._shard_relist_gen.get(kind)
            if gens is None:
                return {}
            return {shard: gen for shard, gen in enumerate(gens)}

    def touch(self, obj) -> None:
        """In-place mutations must land on the server: touch IS update
        here (the in-memory client's free local touch has no remote
        analogue). Like the in-memory touch, an object that is already
        gone (deleted between the mutation and the announce) is a
        no-op, not an error."""
        with self._lock:
            if self._mirror.get(obj.kind, {}).get(obj.key) is not obj:
                return
        try:
            self.update(obj)
        except NotFoundError:
            with self._lock:
                self._mirror[obj.kind].pop(obj.key, None)

    def evict(self, pod, now: Optional[float] = None):
        """Drain through the policy/v1 Eviction subresource so the API
        SERVER enforces PDBs (terminator/eviction.go:170-185): 429 maps
        to EvictionBlockedError for the caller's backoff queue; an
        already-gone pod is success."""
        path = _path("Pod", pod.metadata.name, pod.metadata.namespace)
        # eviction is idempotent server-side: a racy/injected 409 is
        # safely re-sent (the PDB-blocked 429 still passes through)
        status, body = self._request("evict", "POST", path + "/eviction", {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": pod.metadata.name,
                         "namespace": pod.metadata.namespace},
        }, on_conflict=lambda *_: True)
        if status == 404:
            with self._lock:
                self._mirror["Pod"].pop(pod.key, None)
                self._index_pod(pod, removed=True)
            # in-process watch subscribers (dirty trackers, cluster
            # state) must see the deletion like the post-eviction gone
            # path below — without the announce they'd only learn of
            # it from a later stream event or relist
            self._announce("Pod", DELETED, pod)
            return None
        if status == 429:
            causes = (body.get("details") or {}).get("causes") or [{}]
            raise EvictionBlockedError(causes[0].get("message", ""))
        if status >= 400:
            raise ApiError(status, body.get("message", ""))
        # A REAL apiserver answers eviction with a Status object, not
        # the pod; the in-memory one returns the pod CR. When the body
        # carries no deletionTimestamp, GET the pod to learn whether it
        # is terminating (grace period / finalizers) or already gone.
        if not (body and body.get("metadata", {}).get("deletionTimestamp")):
            st, got = self._request("get", "GET", path)
            body = got if st == 200 else {}
        # mirror bookkeeping identical to delete(): either the pod is
        # wedged terminating behind a finalizer or it is gone
        if body and body.get("metadata", {}).get("deletionTimestamp"):
            from karpenter_tpu.kube.serialize import ts_from_rfc3339

            pod.metadata.deletion_timestamp = (
                now if now is not None else ts_from_rfc3339(
                    body["metadata"]["deletionTimestamp"]
                )
            )
            pod.metadata.resource_version = int(
                body["metadata"].get("resourceVersion", "0") or 0
            )
            self._announce("Pod", MODIFIED, pod)
            return pod
        with self._lock:
            self._mirror["Pod"].pop(pod.key, None)
            self._index_pod(pod, removed=True)
        self._announce("Pod", DELETED, pod)
        return None

    def delete(self, obj_or_kind, key: Optional[str] = None,
               now: Optional[float] = None):
        if isinstance(obj_or_kind, str):
            obj = self.get(obj_or_kind, key)
        else:
            obj = self.get(obj_or_kind.kind, obj_or_kind.key)
        if obj is None:
            return None
        # deletes carry no resourceVersion precondition here: a
        # racy/injected 409 is safely re-sent (idempotent)
        status, body = self._request(
            "delete", "DELETE",
            _path(obj.kind, obj.metadata.name, obj.metadata.namespace),
            on_conflict=lambda *_: True,
        )
        if status == 404:
            # already gone server-side (another actor, or OUR earlier
            # delete whose response was lost and the wrapper retried):
            # in-process subscribers must still hear the deletion —
            # the server's DELETED echo skips keys the mirror already
            # dropped, so without this announce they never would
            with self._lock:
                self._mirror[obj.kind].pop(obj.key, None)
                self._index_pod(obj, removed=True)
            self._announce(obj.kind, DELETED, obj)
            return None
        if status >= 400:
            raise ApiError(status, body.get("message", ""))
        if body and body.get("metadata", {}).get("deletionTimestamp"):
            from karpenter_tpu.kube.serialize import ts_from_rfc3339

            obj.metadata.deletion_timestamp = (
                now if now is not None else ts_from_rfc3339(
                    body["metadata"]["deletionTimestamp"]
                )
            )
            obj.metadata.resource_version = int(
                body["metadata"].get("resourceVersion", "0") or 0
            )
            self._announce(obj.kind, MODIFIED, obj)
            return obj
        with self._lock:
            self._mirror[obj.kind].pop(obj.key, None)
            self._index_pod(obj, removed=True)
        self._announce(obj.kind, DELETED, obj)
        return None

    def remove_finalizer(self, obj, finalizer: str) -> None:
        if finalizer in obj.metadata.finalizers:
            obj.metadata.finalizers.remove(finalizer)
        try:
            self.update(obj)
        except NotFoundError:
            # already finalized server-side (another actor removed the
            # last finalizer first) — the in-memory client's
            # remove_finalizer never raises here either, and controllers
            # rely on that tolerance
            pass
        if obj.metadata.deletion_timestamp is not None and not (
            obj.metadata.finalizers
        ):
            with self._lock:
                self._mirror[obj.kind].pop(obj.key, None)
                self._index_pod(obj, removed=True)
            self._announce(obj.kind, DELETED, obj)

    def bind_pod(self, pod, node_name: str) -> None:
        # bindings are idempotent toward the same target: a
        # racy/injected 409 is safely re-sent
        status, body = self._request(
            "bind", "POST",
            _path("Pod", pod.metadata.name, pod.metadata.namespace)
            + "/binding",
            {"target": {"kind": "Node", "name": node_name}},
            on_conflict=lambda *_: True,
        )
        if status >= 400:
            raise ApiError(status, body.get("message", ""))
        pod.spec.node_name = node_name
        with self._lock:
            self._index_pod(pod)
        self._announce("Pod", MODIFIED, pod)

    # -- reads (mirror) ---------------------------------------------------

    def get(self, kind: str, key: str):
        with self._lock:
            return self._mirror.get(kind, {}).get(key)

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[LabelSelector] = None) -> list:
        if kind in UNMAPPED_KINDS:
            return []
        with self._lock:
            out = []
            for obj in self._mirror.get(kind, {}).values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if selector is not None and not selector.matches(
                    obj.metadata.labels
                ):
                    continue
                out.append(obj)
            return out

    def _index_pod(self, obj, removed: bool = False) -> None:
        if obj.kind != "Pod":
            return
        old = self._pod_node.get(obj.key)
        new = "" if removed else obj.spec.node_name
        if old == new:
            return
        if old:
            self._pods_by_node.get(old, set()).discard(obj.key)
        if new:
            self._pods_by_node.setdefault(new, set()).add(obj.key)
            self._pod_node[obj.key] = new
        else:
            self._pod_node.pop(obj.key, None)

    def pods_on_node(self, node_name: str) -> list:
        with self._lock:
            keys = self._pods_by_node.get(node_name)
            if not keys:
                return []
            bucket = self._mirror.get("Pod", {})
            return [bucket[k] for k in keys if k in bucket]

    # -- typed sugar (KubeClient parity) ----------------------------------

    def pods(self, namespace=None, selector=None):
        return self.list("Pod", namespace, selector)

    def nodes(self):
        return self.list("Node")

    def node_claims(self):
        return self.list("NodeClaim")

    def node_pools(self):
        return self.list("NodePool")

    def daemon_sets(self):
        return self.list("DaemonSet")

    def pdbs(self):
        return self.list("PodDisruptionBudget")

    def csi_nodes(self):
        return self.list("CSINode")

    def get_pod(self, namespace: str, name: str):
        return self.get("Pod", f"{namespace}/{name}")

    def get_node(self, name: str):
        return self.get("Node", name)

    def get_node_claim(self, name: str):
        return self.get("NodeClaim", name)

    def get_node_pool(self, name: str):
        return self.get("NodePool", name)

    def get_pvc(self, namespace: str, name: str):
        return self.get("PersistentVolumeClaim", f"{namespace}/{name}")

    def get_storage_class(self, name: str):
        return self.get("StorageClass", name)

    def get_pv(self, name: str):
        return self.get("PersistentVolume", name)

    def get_csi_node(self, name: str):
        return self.get("CSINode", name)
