"""Watch-driven dirty tracking for incremental controller reconciles.

The reference is watch-driven end to end (controllers.go:85-106): a
controller touches an object only when an informer event names it. The
tick-driven runtime here gets the same property via this tracker: each
controller owns one, subscribes it to the kinds it cares about, and
each tick drains only the keys that changed since the last drain —
O(changes) instead of O(cluster) per tick. `KubeClient.watch` replays
current state on subscribe (the informer initial LIST), so the first
drain after startup is a full pass.

In-place mutations bypass the API server analogue and therefore emit
no watch events; controllers that mutate objects in place call
`KubeClient.touch` so every tracker sees the change (the reference has
no such path — every write goes through the API server — which is
exactly the property touch() restores).

Two extensions serve retained-state consumers (the provisioner's
incremental live tick):

- `watch(kind, key=fn)` maps each event to DERIVED keys (e.g. a Pod
  event dirties the NODE the pod is bound to), so a consumer keyed by
  one kind can be fed from events of another.
- `relisted(kind)` latches 410-driven relists: a watch_gone re-LIST
  means the watch stream fell off the server's event horizon, so the
  diff-based relist events CANNOT be trusted to name every change the
  stale window hid (the mirror's rv guard suppresses echoes, and a
  change-then-change-back is invisible to a diff). A retained-state
  consumer must treat such a relist as "everything dirty" and rebuild —
  correctness over incrementality, exactly once per relist. Snapshot
  transports re-LIST every pump BY DESIGN — their diff events are the
  primary event stream, not a recovery path — so they never advance
  the generation (marking everything dirty every pump would erase
  incrementality entirely).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from karpenter_tpu.kube.client import KubeClient

# key-mapping hook: (event, obj) -> derived dirty keys
KeyFn = Callable[[str, object], Iterable[str]]


class DirtyTracker:
    def __init__(self, kube: KubeClient):
        self.kube = kube
        self._sets: dict[str, set[str]] = {}
        self._watched: set[str] = set()
        # dirty-wake hooks (ISSUE 17): cheap callables fired when a
        # watched kind gains a dirty key, so an event-driven loop can
        # sleep on an Event instead of polling peek()
        self._hooks: list[Callable[[], None]] = []
        # last relist generation observed per kind (clients that never
        # relist — the in-memory substrate — simply never advance it)
        self._relist_gen: dict[str, int] = {}
        # last PER-SHARD relist generation observed, (kind, shard) ->
        # gen — the sharded state plane's scoped continuity latch
        self._shard_gen: dict[tuple[str, int], int] = {}

    def watch(self, *kinds: str, key: Optional[KeyFn] = None) -> "DirtyTracker":
        for kind in kinds:
            if kind in self._watched:
                continue
            self._watched.add(kind)
            self._sets.setdefault(kind, set())

            def handler(event: str, obj, _k: str = kind,
                        _key: Optional[KeyFn] = key) -> None:
                if _key is None:
                    self._sets[_k].add(obj.key)
                else:
                    self._sets[_k].update(_key(event, obj))
                for hook in self._hooks:
                    hook()

            self.kube.watch(kind, handler)
        return self

    def on_dirty(self, hook: Callable[[], None]) -> "DirtyTracker":
        """Register a cheap, exception-free callable (e.g.
        threading.Event.set) fired on every event a watched kind
        receives — the reactive wake seam for consumers that sleep
        between ticks and only want to run when O(dirty) work exists."""
        self._hooks.append(hook)
        return self

    def mark(self, kind: str, key: str) -> None:
        self._sets.setdefault(kind, set()).add(key)

    def drain(self, kind: str) -> set[str]:
        out = self._sets.get(kind, set())
        self._sets[kind] = set()
        return out

    def peek(self, kind: str) -> set[str]:
        return set(self._sets.get(kind, set()))

    def relisted(self, *kinds: str) -> bool:
        """True once per 410-driven relist of any of `kinds` since the
        last call — the signal that the event stream lost continuity
        and a retained-state consumer must mark EVERYTHING dirty.
        Reads the client's per-kind relist generation (RealKubeClient
        increments it only on watch_gone re-LISTs; snapshot pumps
        re-LIST every cycle by design and never advance it); clients
        without one never relist."""
        gen_of = getattr(self.kube, "relist_generation", None)
        if gen_of is None:
            return False
        hit = False
        for kind in kinds:
            gen = gen_of(kind)
            if gen != self._relist_gen.get(kind, 0):
                self._relist_gen[kind] = gen
                hit = True
        return hit

    def relisted_shards(self, *kinds: str) -> Optional[set[int]]:
        """Shard-scoped continuity latch (ISSUE 16): the set of shard
        ids whose relist epoch advanced for any of `kinds` since the
        last call — each named shard's retained keys must be treated as
        dirty, while every OTHER shard's rows stay warm. Returns None
        when a relist happened but the client cannot scope it (no
        per-shard epochs — the merged contract's conservative reading:
        everything dirty). Returns an empty set when nothing relisted.

        Latches the merged per-kind generation alongside the shard
        generations, so mixing `relisted_shards` and `relisted` over
        the same kinds never double-fires for one relist."""
        gens_of = getattr(self.kube, "relist_generations", None)
        if gens_of is None:
            return None if self.relisted(*kinds) else set()
        out: set[int] = set()
        for kind in kinds:
            for shard, gen in gens_of(kind).items():
                if gen != self._shard_gen.get((kind, shard), 0):
                    self._shard_gen[(kind, shard)] = gen
                    out.add(shard)
        gen_of = getattr(self.kube, "relist_generation", None)
        if gen_of is not None:
            for kind in kinds:
                self._relist_gen[kind] = gen_of(kind)
        return out

    def clear(self) -> None:
        """Drop all pending dirt without reporting it (used after a
        consumer rebuilt its state from scratch — a relist or a full
        cache bust — so stale keys don't force a second rebuild)."""
        for kind in self._sets:
            self._sets[kind] = set()
