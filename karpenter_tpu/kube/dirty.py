"""Watch-driven dirty tracking for incremental controller reconciles.

The reference is watch-driven end to end (controllers.go:85-106): a
controller touches an object only when an informer event names it. The
tick-driven runtime here gets the same property via this tracker: each
controller owns one, subscribes it to the kinds it cares about, and
each tick drains only the keys that changed since the last drain —
O(changes) instead of O(cluster) per tick. `KubeClient.watch` replays
current state on subscribe (the informer initial LIST), so the first
drain after startup is a full pass.

In-place mutations bypass the API server analogue and therefore emit
no watch events; controllers that mutate objects in place call
`KubeClient.touch` so every tracker sees the change (the reference has
no such path — every write goes through the API server — which is
exactly the property touch() restores).
"""

from __future__ import annotations

from karpenter_tpu.kube.client import KubeClient


class DirtyTracker:
    def __init__(self, kube: KubeClient):
        self.kube = kube
        self._sets: dict[str, set[str]] = {}
        self._watched: set[str] = set()

    def watch(self, *kinds: str) -> "DirtyTracker":
        for kind in kinds:
            if kind in self._watched:
                continue
            self._watched.add(kind)
            self._sets.setdefault(kind, set())

            def handler(event: str, obj, _k: str = kind) -> None:
                self._sets[_k].add(obj.key)

            self.kube.watch(kind, handler)
        return self

    def mark(self, kind: str, key: str) -> None:
        self._sets.setdefault(kind, set()).add(key)

    def drain(self, kind: str) -> set[str]:
        out = self._sets.get(kind, set())
        self._sets[kind] = set()
        return out

    def peek(self, kind: str) -> set[str]:
        return set(self._sets.get(kind, set()))

    def clear(self) -> None:
        """Drop all pending dirt without reporting it (used after a
        consumer rebuilt its state from scratch — a relist or a full
        cache bust — so stale keys don't force a second rebuild)."""
        for kind in self._sets:
            self._sets[kind] = set()
