"""In-memory Kubernetes-style API server.

The reference's fabric is the real API server (watches + optimistic
concurrency). This build substitutes a single-process store with the
same semantics the controllers rely on:

- create/get/list/update/delete by (kind, key)
- resource versions bumped on write; stale updates rejected
- finalizers: delete sets deletion_timestamp while finalizers remain;
  the object disappears when the last finalizer is removed
- watch: subscribers receive (event, obj) synchronously on mutation —
  the analogue of informer event handlers feeding state.Cluster
- async delivery mode: watch events queue instead of firing inline,
  modelling the informer-cache lag behind the real API server
  (cluster.go:118-213 exists because of exactly this); the operator
  pumps `deliver()` once per tick, and `Cluster.synced()` reports
  False while events are in flight
- immutable NodeClaim spec (the reference enforces via CEL)

Controllers are written against this client; swapping in a real
apiserver adapter later only replaces this module.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Callable, Iterable, Optional

from karpenter_tpu.apis.v1.nodeclaim import NodeClaim
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.kube.objects import (
    CSINode,
    DaemonSet,
    LabelSelector,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    PriorityClass,
    StorageClass,
)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

WatchHandler = Callable[[str, object], None]


class ConflictError(Exception):
    pass


class NotFoundError(Exception):
    pass


class InvalidError(Exception):
    pass


class EvictionBlockedError(Exception):
    """The API substrate's 429: a PodDisruptionBudget blocks the
    eviction right now (terminator/eviction.go:170-185 retries these
    with backoff rather than falling through to delete)."""

    def __init__(self, pdb: str = ""):
        self.pdb = pdb
        super().__init__(
            "Cannot evict pod as it would violate the pod's disruption "
            f"budget: {pdb}"
        )


class KubeClient:
    # This store IS the simulated cluster: there is no ReplicaSet
    # controller or kube-scheduler behind it, so controllers that
    # emulate workload-owner behavior (eviction successor pods) are
    # entitled to do so. Real-cluster adapters set this False — there
    # the actual controllers own that behavior.
    simulates_workload_controllers = True

    def __init__(self, async_delivery: bool = False) -> None:
        self._lock = threading.RLock()
        self._store: dict[str, dict[str, object]] = {}
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._rv = 0
        self.async_delivery = async_delivery
        self._pending_events: list[tuple[str, str, object]] = []
        # field-indexer analogue (operator.go:251-294 indexes
        # pod.spec.nodeName): node name -> pod keys, kept in lockstep
        # with writes so pods_on_node is O(pods-on-node) not O(pods)
        self._pods_by_node: dict[str, set[str]] = {}
        self._pod_node: dict[str, str] = {}
        # serializes deliver() so concurrent pumps can't interleave
        # event order; re-entrant pumps (a handler calling deliver)
        # no-op instead of delivering newer events ahead of the
        # in-flight batch
        self._deliver_lock = threading.RLock()
        self._delivering = False

    # -- core CRUD ------------------------------------------------------------

    def _bucket(self, kind: str) -> dict[str, object]:
        return self._store.setdefault(kind, {})

    def _admit(self, obj, old=None) -> None:
        """Admission-time validation — the CEL analogue the real API
        server runs before any write lands (apis/v1/validation.py)."""
        from karpenter_tpu.apis.v1.validation import (
            ValidationError,
            validate_node_claim,
            validate_node_pool,
        )

        try:
            if isinstance(obj, NodePool):
                validate_node_pool(obj, old=old)
            elif isinstance(obj, NodeClaim) and old is None:
                validate_node_claim(obj)
        except ValidationError as err:
            raise InvalidError(str(err)) from None

    def _index_pod(self, obj, removed: bool = False) -> None:
        if not isinstance(obj, Pod):
            return
        old = self._pod_node.get(obj.key)
        new = "" if removed else obj.spec.node_name
        if old == new:
            return
        if old:
            self._pods_by_node.get(old, set()).discard(obj.key)
        if new:
            self._pods_by_node.setdefault(new, set()).add(obj.key)
            self._pod_node[obj.key] = new
        else:
            self._pod_node.pop(obj.key, None)

    def create(self, obj) -> object:
        with self._lock:
            self._admit(obj)
            bucket = self._bucket(obj.kind)
            if obj.key in bucket:
                raise ConflictError(f"{obj.kind} {obj.key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            obj.metadata.generation = 1
            bucket[obj.key] = obj
            self._index_pod(obj)
            self._notify(obj.kind, ADDED, obj)
            return obj

    def get(self, kind: str, key: str):
        with self._lock:
            obj = self._bucket(kind).get(key)
            return obj

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[LabelSelector] = None) -> list:
        with self._lock:
            out = []
            for obj in self._bucket(kind).values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if selector is not None and not selector.matches(obj.metadata.labels):
                    continue
                out.append(obj)
            return out

    def update(self, obj, mutate=None) -> object:
        """Write an object back; bumps resource version.

        Optimistic concurrency (the API server's resourceVersion
        precondition): writing a DIFFERENT object instance whose
        resource version is older than the stored one is a conflict —
        the caller read stale state and must re-read and retry.
        In-place mutations of the canonical object (the common
        single-process controller pattern here) are never stale.
        NodeClaim specs are immutable (nodeclaim.go:145 CEL rule).

        `mutate` (optional) states the write as a FUNCTION of the
        object — the conflict-safe form mirrored by RealKubeClient's
        retry wrapper: applied before the write, and on a would-be
        conflict re-applied onto the CANONICAL stored object instead
        of failing (read-modify-write, never last-write-wins).
        """
        with self._lock:
            if mutate is not None:
                mutate(obj)
            bucket = self._bucket(obj.kind)
            existing = bucket.get(obj.key)
            if existing is None:
                raise NotFoundError(f"{obj.kind} {obj.key}")
            if (
                mutate is not None
                and existing is not obj
                and obj.metadata.resource_version
                < existing.metadata.resource_version
            ):
                mutate(existing)
                obj = existing
            if existing is not obj and (
                obj.metadata.resource_version < existing.metadata.resource_version
            ):
                raise ConflictError(
                    f"{obj.kind} {obj.key}: stale resourceVersion "
                    f"{obj.metadata.resource_version} < "
                    f"{existing.metadata.resource_version}"
                )
            if isinstance(obj, NodeClaim) and existing is not obj:
                if repr(existing.spec) != repr(obj.spec):
                    raise InvalidError("NodeClaim spec is immutable")
            self._admit(obj, old=existing)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            bucket[obj.key] = obj
            self._index_pod(obj)
            self._notify(obj.kind, MODIFIED, obj)
            return obj

    def delete(self, obj_or_kind, key: Optional[str] = None, now: Optional[float] = None):
        """Delete with finalizer semantics."""
        with self._lock:
            if isinstance(obj_or_kind, str):
                obj = self._bucket(obj_or_kind).get(key)
            else:
                obj = self._bucket(obj_or_kind.kind).get(obj_or_kind.key)
            if obj is None:
                return None
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = now if now is not None else time.time()
                    self._rv += 1
                    obj.metadata.resource_version = self._rv
                    self._notify(obj.kind, MODIFIED, obj)
                return obj
            del self._bucket(obj.kind)[obj.key]
            self._index_pod(obj, removed=True)
            self._notify(obj.kind, DELETED, obj)
            return None

    def evict(self, pod: Pod, now: Optional[float] = None) -> None:
        """policy/v1 Eviction analogue: the store (playing the API
        server) enforces PDBs SERVER-side and answers the eviction.go
        429 with EvictionBlockedError; an allowed eviction proceeds as
        a graceful delete (finalizer semantics included). Drains must
        call this, never delete() — on a real cluster only the
        eviction subresource consults PDBs."""
        from karpenter_tpu.utils.pdb import PdbLimits

        # check + delete under one lock (RLock: the nested reads and
        # the delete re-enter safely) — the real API server evaluates
        # the budget atomically per eviction, so two racing evictions
        # can never both pass a disruptions_allowed=1 budget
        with self._lock:
            blocking = PdbLimits(self).can_evict(pod, server_side=True)
            if blocking is not None:
                raise EvictionBlockedError(blocking)
            self.delete(pod, now=now)

    def touch(self, obj) -> None:
        """Publish a MODIFIED event for an object mutated in place.

        Controllers that edit objects directly (conditions, timestamps,
        annotations) bypass update() and would otherwise be invisible
        to watch-driven consumers; touch restores the every-write-is-
        an-event property the reference gets from the API server."""
        with self._lock:
            if self._bucket(obj.kind).get(obj.key) is not obj:
                return  # deleted or replaced; nothing to announce
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._notify(obj.kind, MODIFIED, obj)

    def remove_finalizer(self, obj, finalizer: str) -> None:
        with self._lock:
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                bucket = self._bucket(obj.kind)
                if obj.key in bucket:
                    del bucket[obj.key]
                    self._index_pod(obj, removed=True)
                    self._notify(obj.kind, DELETED, obj)
            else:
                self.update(obj)

    # -- checkpoint / resume ---------------------------------------------------
    #
    # The reference's durable state IS the API server (SURVEY §5.4:
    # conditions, labels, finalizers, taints — the in-memory caches are
    # rebuilt from watches on restart). This store is that API server,
    # so persistence = serializing the store; a fresh operator attaches
    # informers, replays the LIST, and resumes exactly where the old
    # process stopped.

    def save(self, path: str) -> None:
        import pickle

        with self._lock:
            with open(path, "wb") as fh:
                pickle.dump(self._store, fh)

    @classmethod
    def load(cls, path: str, async_delivery: bool = False) -> "KubeClient":
        import pickle

        client = cls(async_delivery=async_delivery)
        with open(path, "rb") as fh:
            client._store = pickle.load(fh)
        client._rv = max(
            (
                obj.metadata.resource_version
                for bucket in client._store.values()
                for obj in bucket.values()
            ),
            default=0,
        )
        for pod in client._bucket("Pod").values():
            client._index_pod(pod)
        return client

    # -- watch ----------------------------------------------------------------

    def watch(self, kind: str, handler: WatchHandler) -> None:
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)
            # replay current state (informer initial LIST)
            for obj in self._bucket(kind).values():
                handler(ADDED, obj)

    def _notify(self, kind: str, event: str, obj) -> None:
        if not self._watchers.get(kind):
            return
        if self.async_delivery:
            self._pending_events.append((kind, event, obj))
            return
        self._dispatch(kind, event, obj)

    def _dispatch(self, kind: str, event: str, obj) -> None:
        for handler in self._watchers.get(kind, []):
            handler(event, obj)

    def deliver(self, limit: Optional[int] = None,
                shard: Optional[int] = None) -> int:
        """Drain queued watch events to their handlers (the informer
        stream catching up with the API server). Returns the number
        delivered. `limit` delivers only the oldest N, letting tests
        hold the cache arbitrarily stale. `shard` delivers only the
        events routed to one state-plane shard (state/shards.py),
        leaving the rest queued — the per-shard logical stream the
        cross-shard ordering tests replay in both orders."""
        with self._deliver_lock:
            if self._delivering:
                return 0
            self._delivering = True
            try:
                with self._lock:
                    if shard is None:
                        n = len(self._pending_events) if limit is None \
                            else min(limit, len(self._pending_events))
                        batch = self._pending_events[:n]
                        del self._pending_events[:n]
                    else:
                        from karpenter_tpu.state.shards import shard_of_event

                        batch, kept = [], []
                        for item in self._pending_events:
                            kind, _, obj = item
                            if shard_of_event(kind, obj) == shard and (
                                limit is None or len(batch) < limit
                            ):
                                batch.append(item)
                            else:
                                kept.append(item)
                        self._pending_events = kept
                for kind, event, obj in batch:
                    self._dispatch(kind, event, obj)
                return len(batch)
            finally:
                self._delivering = False

    def pending_events(self, kinds: Optional[Iterable[str]] = None) -> int:
        """Undelivered watch events, optionally filtered by kind."""
        with self._lock:
            if kinds is None:
                return len(self._pending_events)
            wanted = set(kinds)
            return sum(1 for k, _, _ in self._pending_events if k in wanted)

    # -- typed sugar ----------------------------------------------------------

    def pods(self, namespace: Optional[str] = None,
             selector: Optional[LabelSelector] = None) -> list[Pod]:
        return self.list("Pod", namespace, selector)

    def nodes(self) -> list[Node]:
        return self.list("Node")

    def node_claims(self) -> list[NodeClaim]:
        return self.list("NodeClaim")

    def node_pools(self) -> list[NodePool]:
        return self.list("NodePool")

    def daemon_sets(self) -> list[DaemonSet]:
        return self.list("DaemonSet")

    def pdbs(self) -> list[PodDisruptionBudget]:
        return self.list("PodDisruptionBudget")

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self.get("Pod", f"{namespace}/{name}")

    def get_node(self, name: str) -> Optional[Node]:
        return self.get("Node", name)

    def get_node_claim(self, name: str) -> Optional[NodeClaim]:
        return self.get("NodeClaim", name)

    def get_node_pool(self, name: str) -> Optional[NodePool]:
        return self.get("NodePool", name)

    def get_pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self.get("PersistentVolumeClaim", f"{namespace}/{name}")

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        return self.get("StorageClass", name)

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        return self.get("PersistentVolume", name)

    def get_csi_node(self, name: str) -> Optional[CSINode]:
        return self.get("CSINode", name)

    def csi_nodes(self) -> list[CSINode]:
        return self.list("CSINode")

    def priority_classes(self) -> list[PriorityClass]:
        return self.list("PriorityClass")

    def get_priority_class(self, name: str) -> Optional[PriorityClass]:
        return self.get("PriorityClass", name)

    def bind_pod(self, pod: Pod, node_name: str) -> None:
        """The scheduler binding: sets spec.node_name."""
        with self._lock:
            pod.spec.node_name = node_name
            self.update(pod)

    def pods_on_node(self, node_name: str) -> list[Pod]:
        with self._lock:
            keys = self._pods_by_node.get(node_name)
            if not keys:
                return []
            bucket = self._bucket("Pod")
            return [bucket[k] for k in keys if k in bucket]
