"""Deployable manifests — the kwok/charts analogue.

The reference ships a Helm chart (kwok/charts: deployment, RBAC,
service, PDB, CRDs) so a user can install the controller on a real
cluster. This module renders the equivalent static manifests for the
TPU-native operator binary (`python -m karpenter_tpu`), generated from
the SAME sources the runtime enforces:

- `deploy/crds.yaml` — full CustomResourceDefinition objects whose
  openAPIV3Schema is the generated admission-rule corpus
  (apis/crds.py; drift from validation.py is a test failure),
- `deploy/karpenter.yaml` — namespace, service account, RBAC scoped
  to exactly the kinds the real client speaks (kube/real.py
  RESOURCES), the operator Deployment with /healthz//readyz probes
  and the Prometheus port, a Service, and a PodDisruptionBudget.

Regenerate with `python -m karpenter_tpu.deploy`; tests assert the
checked-in files match the generator (the `make verify` codegen
pattern).
"""

from __future__ import annotations

import os

import yaml

from karpenter_tpu.apis.crds import nodeclaim_schema, nodepool_schema

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY_DIR = os.path.join(REPO_ROOT, "deploy")

NAMESPACE = "karpenter"
APP = "karpenter-tpu"


def _crd(group: str, plural: str, kind: str, schema: dict,
         version: str = "v1", served: bool = True) -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {
                "plural": plural,
                "singular": kind.lower(),
                "kind": kind,
                "categories": ["karpenter"],
            },
            "scope": "Cluster",
            "versions": [{
                "name": version,
                "served": served,
                "storage": True,
                # no status subresource: the real client writes status
                # through the main resource (kube/real.py PUT); with the
                # subresource enabled a real API server would silently
                # strip status from those writes
                "schema": {"openAPIV3Schema": schema["openAPIV3Schema"]},
            }],
        },
    }


def _overlay_schema() -> dict:
    """NodeOverlay v1alpha1 schema from the runtime-validation rules
    (apis/v1alpha1/nodeoverlay.py runtime_validate)."""
    from karpenter_tpu.apis.v1alpha1.nodeoverlay import _VALID_OPERATORS

    return {
        "group": "karpenter.sh",
        "kind": "NodeOverlay",
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "spec": {
                    "type": "object",
                    "properties": {
                        "requirements": {
                            "type": "array",
                            "items": {
                                "type": "object",
                                "required": ["key", "operator"],
                                "properties": {
                                    "key": {"type": "string"},
                                    "operator": {
                                        "type": "string",
                                        "enum": sorted(_VALID_OPERATORS),
                                    },
                                    "values": {
                                        "type": "array",
                                        "items": {"type": "string"},
                                    },
                                },
                            },
                        },
                        "priceAdjustment": {
                            "type": "string",
                            "pattern": r"^[+-]?\d+(\.\d+)?%?$",
                        },
                        "price": {
                            "type": "string",
                            "pattern": r"^\d+(\.\d+)?$",
                        },
                        "capacity": {
                            "type": "object",
                            "additionalProperties": {
                                "anyOf": [{"type": "integer"},
                                          {"type": "string"}],
                            },
                        },
                        "weight": {
                            "type": "integer", "minimum": 0, "maximum": 100,
                        },
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "conditions": {"type": "array",
                                       "items": {"type": "object",
                                                 "x-kubernetes-preserve-unknown-fields": True}},
                    },
                },
            },
        },
    }


def crds() -> list[dict]:
    return [
        _crd("karpenter.sh", "nodepools", "NodePool", nodepool_schema()),
        _crd("karpenter.sh", "nodeclaims", "NodeClaim", nodeclaim_schema()),
        _crd("karpenter.sh", "nodeoverlays", "NodeOverlay",
             _overlay_schema(), version="v1alpha1"),
    ]


def _rbac_rules() -> list[dict]:
    """Scoped to the kinds the real client speaks (kube/real.py
    RESOURCES) — read everywhere, write where the controllers write."""
    return [
        {"apiGroups": ["karpenter.sh"],
         # no */status entries: the generated CRDs deliberately omit
         # the status subresource (see _crd comment above), so those
         # RBAC resources would name nothing
         "resources": ["nodepools", "nodeclaims", "nodeoverlays"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": [""],
         "resources": ["nodes", "pods", "persistentvolumeclaims",
                       "persistentvolumes"],
         # create: kwok-style providers register Node objects and the
         # eviction queue recreates successor pods
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": [""],
         "resources": ["pods/binding", "pods/eviction"],
         "verbs": ["create"]},
        # update: the recorder bumps count/lastTimestamp on deduped
        # Events via PUT (the reference's record.EventRecorder patches)
        {"apiGroups": [""], "resources": ["events"],
         "verbs": ["create", "patch", "update"]},
        {"apiGroups": ["apps"], "resources": ["daemonsets"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["policy"], "resources": ["poddisruptionbudgets"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["storage.k8s.io"],
         "resources": ["storageclasses", "csinodes"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["coordination.k8s.io"], "resources": ["leases"],
         "verbs": ["get", "list", "watch", "create", "update", "patch"]},
    ]


def operator_manifests(image: str = "karpenter-tpu:latest") -> list[dict]:
    labels = {"app.kubernetes.io/name": APP}
    return [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": NAMESPACE}},
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": APP, "namespace": NAMESPACE}},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
         "metadata": {"name": APP}, "rules": _rbac_rules()},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": APP},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole", "name": APP},
         "subjects": [{"kind": "ServiceAccount", "name": APP,
                       "namespace": NAMESPACE}]},
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": APP, "namespace": NAMESPACE,
                      "labels": labels},
         "spec": {
             "replicas": 2,  # active/passive via leader election
             "selector": {"matchLabels": labels},
             "template": {
                 "metadata": {"labels": labels},
                 "spec": {
                     "serviceAccountName": APP,
                     "containers": [{
                         "name": "controller",
                         "image": image,
                         "args": [
                             "--api-server",
                             "https://kubernetes.default.svc",
                             "--api-token-file",
                             "/var/run/secrets/kubernetes.io/"
                             "serviceaccount/token",  # re-read on expiry
                             "--api-ca-file",
                             "/var/run/secrets/kubernetes.io/"
                             "serviceaccount/ca.crt",
                             "--leader-elect",
                             "--metrics-port", "8080",
                         ],
                         "ports": [{"name": "http-metrics",
                                    "containerPort": 8080}],
                         "livenessProbe": {
                             "httpGet": {"path": "/healthz", "port": 8080},
                             "initialDelaySeconds": 10,
                         },
                         "readinessProbe": {
                             "httpGet": {"path": "/readyz", "port": 8080},
                         },
                         "env": [{
                             "name": "HOSTNAME",
                             "valueFrom": {"fieldRef": {
                                 "fieldPath": "metadata.name"}},
                         }],
                         "resources": {
                             "requests": {"cpu": "1", "memory": "1Gi"},
                         },
                     }],
                 },
             },
         }},
        {"apiVersion": "v1", "kind": "Service",
         "metadata": {"name": APP, "namespace": NAMESPACE,
                      "labels": labels},
         "spec": {"selector": labels,
                  "ports": [{"name": "http-metrics", "port": 8080,
                             "targetPort": 8080}]}},
        {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
         "metadata": {"name": APP, "namespace": NAMESPACE},
         "spec": {"maxUnavailable": 1,
                  "selector": {"matchLabels": labels}}},
    ]


def render() -> dict[str, str]:
    return {
        "crds.yaml": yaml.safe_dump_all(crds(), sort_keys=True),
        "karpenter.yaml": yaml.safe_dump_all(
            operator_manifests(), sort_keys=True
        ),
    }


def write(directory: str = DEPLOY_DIR) -> None:
    os.makedirs(directory, exist_ok=True)
    for name, content in render().items():
        with open(os.path.join(directory, name), "w") as fh:
            fh.write(content)


if __name__ == "__main__":  # pragma: no cover
    write()
    print(f"wrote deploy manifests to {DEPLOY_DIR}")
