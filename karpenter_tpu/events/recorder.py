"""Deduplicated event recorder.

Counterpart of pkg/events/recorder.go:47-120: events identical in
(kind, object, reason, message) within a 10s TTL are dropped; a simple
per-reason token bucket guards against floods.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class Event:
    kind: str          # object kind
    name: str          # object name
    type: str          # Normal | Warning
    reason: str
    message: str


@dataclass
class RecordedEvent:
    event: Event
    timestamp: float
    count: int = 1


class EventRecorder:
    DEDUPE_TTL = 10.0
    RATE_LIMIT_PER_REASON = 10  # events per TTL window
    MAX_EVENTS = 1000           # ring buffer: long-running loops must not leak

    def __init__(self) -> None:
        from collections import deque

        self.events: "deque[RecordedEvent]" = deque(maxlen=self.MAX_EVENTS)
        self._last_seen: dict[Event, float] = {}
        self._reason_counts: dict[str, list[float]] = {}

    def publish(self, event: Event, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        # prune the dedupe cache so distinct one-off events can't grow
        # it without bound
        if len(self._last_seen) > 4 * self.MAX_EVENTS:
            self._last_seen = {
                e: t for e, t in self._last_seen.items()
                if now - t < self.DEDUPE_TTL
            }
        last = self._last_seen.get(event)
        if last is not None and now - last < self.DEDUPE_TTL:
            for rec in reversed(self.events):
                if rec.event == event:
                    rec.count += 1
                    break
            return False
        window = [t for t in self._reason_counts.get(event.reason, []) if now - t < self.DEDUPE_TTL]
        if len(window) >= self.RATE_LIMIT_PER_REASON:
            self._reason_counts[event.reason] = window
            return False
        window.append(now)
        self._reason_counts[event.reason] = window
        self._last_seen[event] = now
        self.events.append(RecordedEvent(event=event, timestamp=now))
        return True

    def for_reason(self, reason: str) -> list[RecordedEvent]:
        return [r for r in self.events if r.event.reason == reason]
