"""Deduplicated event recorder, flushed to the API substrate.

Counterpart of pkg/events/recorder.go:47-120: events identical in
(kind, object, reason, message) within a 10s TTL are dropped; a simple
per-reason token bucket guards against floods. With a `kube` sink the
recorder also publishes real corev1 Event objects (recorder.go:52-72
goes through record.EventRecorder to the API server — that is what
`kubectl describe` shows an operator debugging a live cluster):
fresh events are created, deduped repeats bump the existing Event's
count/lastTimestamp, rate-limited floods never reach the server.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional

_seq = itertools.count(1)


@dataclass(frozen=True)
class Event:
    kind: str          # object kind
    name: str          # object name
    type: str          # Normal | Warning
    reason: str
    message: str
    namespace: str = ""  # empty for cluster-scoped objects


@dataclass
class RecordedEvent:
    event: Event
    timestamp: float
    count: int = 1
    # flight-recorder provenance: the tick trace open when the event
    # was first published ("" outside any trace). Lives here — NOT on
    # the frozen Event — so dedupe identity ignores it: the same event
    # republished from a later tick still dedupes.
    trace_id: str = ""


class EventRecorder:
    DEDUPE_TTL = 10.0
    RATE_LIMIT_PER_REASON = 10  # events per TTL window
    MAX_EVENTS = 1000           # ring buffer: long-running loops must not leak

    def __init__(self, kube=None) -> None:
        from collections import deque

        self.kube = kube  # optional API sink for corev1 Events
        self.events: "deque[RecordedEvent]" = deque(maxlen=self.MAX_EVENTS)
        self._last_seen: dict[Event, float] = {}
        self._reason_counts: dict[str, list[float]] = {}
        self._posted: dict[Event, object] = {}  # event -> KubeEvent CR
        self._last_flush: dict[Event, float] = {}  # bump-PUT throttle
        # sink-side retention for the SIMULATION store only: a real
        # apiserver expires Events (~1h TTL); the in-memory store has
        # no TTL, so the recorder deletes its oldest posts beyond
        # MAX_EVENTS to keep long sims from leaking
        self._sink_fifo: "deque" = deque()

    def publish(self, event: Event, now: Optional[float] = None,
                sticky: bool = False) -> bool:
        """`sticky` makes the frozen-key dedupe window SLIDING: a
        duplicate republished within the TTL refreshes the window, so
        a condition that persists tick after tick (an unschedulable
        pod) bumps the one posted Event's count forever instead of
        reposting an identical message every DEDUPE_TTL — persistence
        stays visible through counters, not apiserver spam."""
        now = time.time() if now is None else now
        # prune the dedupe cache so distinct one-off events can't grow
        # it without bound
        if len(self._last_seen) > 4 * self.MAX_EVENTS:
            self._last_seen = {
                e: t for e, t in self._last_seen.items()
                if now - t < self.DEDUPE_TTL
            }
            self._posted = {
                e: o for e, o in self._posted.items() if e in self._last_seen
            }
        last = self._last_seen.get(event)
        if last is not None and now - last < self.DEDUPE_TTL:
            if sticky:
                self._last_seen[event] = now
            for rec in reversed(self.events):
                if rec.event == event:
                    rec.count += 1
                    break
            self._bump_posted(event, now)
            return False
        window = [t for t in self._reason_counts.get(event.reason, []) if now - t < self.DEDUPE_TTL]
        if len(window) >= self.RATE_LIMIT_PER_REASON:
            self._reason_counts[event.reason] = window
            return False
        window.append(now)
        self._reason_counts[event.reason] = window
        self._last_seen[event] = now
        from karpenter_tpu import tracing

        self.events.append(RecordedEvent(
            event=event, timestamp=now,
            trace_id=tracing.current_trace_id(),
        ))
        self._post(event, now)
        return True

    # -- corev1 Event sink ----------------------------------------------

    def _post(self, event: Event, now: float) -> None:
        if self.kube is None:
            return
        from karpenter_tpu import tracing
        from karpenter_tpu.kube.objects import KubeEvent, ObjectMeta

        # corev1 Events carry the provenance annotation too: kubectl
        # describe on a disrupted node leads straight to the tick trace
        trace_id = tracing.current_trace_id()
        obj = KubeEvent(
            metadata=ObjectMeta(
                # the real recorder's unique-name convention:
                # <object>.<time-based suffix> (UnixNano upstream) —
                # time-seeded so a restarted operator never regenerates
                # a name that still exists server-side (Events live ~1h;
                # a collision 409s and the event would be lost). _seq
                # disambiguates same-microsecond publishes in sims.
                name=f"{event.name}.{int(now * 1e6):x}{next(_seq):04x}",
                namespace=event.namespace or "default",
                annotations=(
                    {tracing.PROVENANCE_ANNOTATION: trace_id}
                    if trace_id else {}
                ),
            ),
            involved_kind=event.kind,
            involved_name=event.name,
            involved_namespace=event.namespace,
            type=event.type,
            reason=event.reason,
            message=event.message,
            count=1,
            first_timestamp=now,
            last_timestamp=now,
        )
        try:
            self.kube.create(obj)
        except Exception:
            return  # event loss is tolerable; controllers never block on it
        self._posted[event] = obj
        self._last_flush[event] = now
        if getattr(self.kube, "simulates_workload_controllers", False):
            self._sink_fifo.append(obj)
            while len(self._sink_fifo) > self.MAX_EVENTS:
                old = self._sink_fifo.popleft()
                try:
                    self.kube.delete(old)
                except Exception:
                    pass

    def _bump_posted(self, event: Event, now: float) -> None:
        obj = self._posted.get(event)
        if obj is None or self.kube is None:
            return
        obj.count += 1
        obj.last_timestamp = now
        # throttle the write: a pod stuck behind a PDB republishes every
        # reconcile, and a synchronous PUT per tick per stuck object
        # would put apiserver round-trips on the hot path (the reference
        # posts through an async broadcaster). The local count keeps
        # accumulating; at most one flush per second carries it up.
        if now - self._last_flush.get(event, 0.0) < 1.0:
            return
        self._last_flush[event] = now
        try:
            self.kube.update(obj)
        except Exception:
            self._posted.pop(event, None)  # deleted/expired server-side

    def for_reason(self, reason: str) -> list[RecordedEvent]:
        return [r for r in self.events if r.event.reason == reason]
