"""Elimination funnel: which constraint killed which instance types.

An unschedulable pod's flat `NO_CAPACITY_ERROR` hides a staged story
the encoder already told in masks: the catalog shrank through
requirements, then taints, then resource axes, then offering budgets —
and whatever survived was eliminated by the kernel (existing capacity
committed, pool limits, placement conflicts). This module replays that
attrition as explicit stages with surviving-type counts:

    948/1000 types survived requirements -> 12 survived taints
        -> 0 fit memory

The funnel is computed LAZILY, only for pods the solve actually failed
(never on the healthy path), from the same primitives the encode uses
(`encode.requirement_compat` — the G x C vocab-mask compat the solver
ships to the device — plus the taint/fit checks), so the explanation
can never drift from what the solver saw. Counts are over distinct
instance-type names (what an operator recognizes), not raw config
columns.
"""

from __future__ import annotations

from typing import Optional, Sequence

from karpenter_tpu.utils import resources as resutil

# stage names, in funnel order; `kernel` is the terminal stage for
# pods every host-side filter admitted but the solve still rejected
STAGE_CATALOG = "catalog"
STAGE_REQUIREMENTS = "requirements"
STAGE_TAINTS = "taints"
STAGE_RESOURCES = "resources"
STAGE_BUDGETS = "offering-budgets"
STAGE_KERNEL = "kernel"


def _type_count(configs) -> int:
    return len({c.instance_type.name for c in configs})


def compute(
    pod,
    pools_with_types,
    existing_inputs: Sequence = (),
    daemon_overhead: Optional[dict] = None,
    reserved_in_use: Optional[dict[str, int]] = None,
) -> dict:
    """The elimination funnel for one pod against one catalog. Pure
    function of its inputs (deterministic under fault replay); called
    only for solve failures, so its O(catalog) scans are off the
    healthy path."""
    from karpenter_tpu.scheduling.requirements import Requirements
    from karpenter_tpu.scheduling.taints import tolerates
    from karpenter_tpu.solver.encode import (
        group_pods,
        launch_configs,
        requirement_compat,
    )

    overhead = daemon_overhead or {}
    in_use = reserved_in_use or {}
    configs = launch_configs(pools_with_types)
    group = group_pods([pod])[0]
    stages: list[dict] = [
        {"stage": STAGE_CATALOG, "survivors": _type_count(configs)}
    ]
    funnel = {"types_total": _type_count(configs), "stages": stages}

    def _push(stage: str, survivors, eliminated_by: Optional[str]) -> bool:
        """Append one stage; returns False (stop) when the funnel hit
        zero — `eliminated_by` names the constraint that emptied it."""
        entry: dict = {"stage": stage, "survivors": _type_count(survivors)}
        if not survivors and eliminated_by:
            entry["eliminated_by"] = eliminated_by
        stages.append(entry)
        return bool(survivors)

    # requirements: the SAME vocab-mask compat the encode ships
    compat = requirement_compat([group], configs)
    req_surv = [c for ci, c in enumerate(configs) if compat[0, ci]]
    if not req_surv:
        # name the keys no config can satisfy alone (each checked via
        # the same compat machinery, one single-key pseudo-group each;
        # _compat_matrix reads only group.requirements, so one reused
        # group with the field swapped per key suffices)
        from dataclasses import replace as _replace

        blocking = []
        for key in sorted(group.requirements.keys()):
            single = Requirements([group.requirements.get(key).copy()])
            row = requirement_compat(
                [_replace(group, requirements=single)], configs
            )
            if not row.any():
                blocking.append(key)
        _push(
            STAGE_REQUIREMENTS, req_surv,
            "requirement " + ", ".join(blocking) if blocking
            else "pod requirements",
        )
        return funnel
    _push(STAGE_REQUIREMENTS, req_surv, None)

    # taints / tolerations
    taint_surv, offenders = [], {}
    for cfg in req_surv:
        err = tolerates(cfg.taints, list(group.tolerations))
        if err is None:
            taint_surv.append(cfg)
        else:
            offenders[err] = offenders.get(err, 0) + 1
    if not _push(
        STAGE_TAINTS, taint_surv,
        max(sorted(offenders), key=lambda k: offenders[k])
        if offenders else "taints",
    ):
        return funnel

    # resource axes: requests + the pool's daemon overhead must fit
    # the type's allocatable; the axis failing on the most survivors
    # names the bottleneck ("0 fit memory")
    fit_surv, axis_fails = [], {}
    for cfg in taint_surv:
        need = resutil.merge(
            group.resources, overhead.get(cfg.pool.metadata.name, {})
        )
        alloc = cfg.instance_type.allocatable
        bad = [k for k, v in need.items() if v > alloc.get(k, 0.0)]
        if bad:
            for k in bad:
                axis_fails[k] = axis_fails.get(k, 0) + 1
        else:
            fit_surv.append(cfg)
    if not _push(
        STAGE_RESOURCES, fit_surv,
        max(sorted(axis_fails), key=lambda k: axis_fails[k])
        if axis_fails else "resources",
    ):
        return funnel

    # offering budgets: a reserved offering only launches while its
    # reservation has instances left (spot-stripped pools never reach
    # here — their spot columns were removed before the catalog)
    budget_surv = [
        cfg for cfg in fit_surv
        if not cfg.offering.is_reserved()
        or cfg.offering.reservation_capacity
        - in_use.get(cfg.offering.reservation_id, 0) > 0
    ]
    if not _push(
        STAGE_BUDGETS, budget_surv, "reservation budget exhausted"
    ):
        return funnel

    # whatever survived every host-side filter was eliminated by the
    # kernel itself: capacity already committed this round, pool
    # limits, topology/placement conflicts, or existing-node quotas
    stages.append({
        "stage": STAGE_KERNEL, "survivors": 0,
        "eliminated_by": "kernel no-capacity (capacity committed, "
                         "pool limits, or placement conflicts)",
    })
    funnel["existing_compatible"] = _existing_compatible(
        group, existing_inputs
    )
    return funnel


def _existing_compatible(group, existing_inputs: Sequence) -> int:
    """How many existing/in-flight nodes could host the pod on
    requirements+taints+remaining room — context for the kernel stage
    ('12 existing nodes were compatible but full' reads differently
    from '0 were')."""
    from karpenter_tpu.apis.v1.labels import WELL_KNOWN_LABELS
    from karpenter_tpu.scheduling.taints import tolerates

    n = 0
    for inp in existing_inputs:
        if tolerates(inp.taints, list(group.tolerations)) is not None:
            continue
        if not inp.requirements.is_compatible(
            group.requirements, allow_undefined=WELL_KNOWN_LABELS
        ):
            continue
        if resutil.fits(group.resources, inp.available):
            n += 1
    return n


def top_exclusions(pod_record: Optional[dict], k: int = 3) -> list[str]:
    """The top-k exclusion reasons for one pod record, largest
    type-drop first — the strings folded into the unschedulable-pod
    corev1 Event message."""
    if not pod_record:
        return []
    funnel = pod_record.get("funnel")
    if not funnel:
        code = pod_record.get("code")
        return [code] if code else []
    stages = funnel.get("stages", [])
    drops = []
    prev = None
    for entry in stages:
        survivors = entry["survivors"]
        if prev is not None and survivors < prev["survivors"]:
            label = f"{entry['stage']} eliminated " \
                    f"{prev['survivors'] - survivors}/{prev['survivors']} types"
            by = entry.get("eliminated_by")
            if by:
                label += f" ({by})"
            drops.append((prev["survivors"] - survivors, label))
        prev = entry
    drops.sort(key=lambda t: -t[0])
    return [label for _, label in drops[:k]]


def render(pod_record: dict) -> str:
    """One pod's funnel as the human-readable arrow chain the README
    documents: '948/1000 types survived requirements -> ...'."""
    funnel = pod_record.get("funnel")
    lines = []
    if funnel:
        total = funnel.get("types_total", 0)
        parts = []
        for entry in funnel.get("stages", []):
            if entry["stage"] == STAGE_CATALOG:
                continue
            label = f"{entry['survivors']}/{total} survived {entry['stage']}"
            by = entry.get("eliminated_by")
            if by:
                label += f" [{by}]"
            parts.append(label)
        lines.append(" -> ".join(parts))
        if "existing_compatible" in funnel:
            lines.append(
                f"existing nodes compatible but unavailable: "
                f"{funnel['existing_compatible']}"
            )
    for step in pod_record.get("relaxed", []):
        lines.append(f"relaxed: {step}")
    if pod_record.get("error"):
        lines.append(f"error: {pod_record['error']}")
    return "\n".join(lines) if lines else "(no funnel recorded)"
