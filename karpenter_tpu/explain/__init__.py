"""Decision explainability plane: structured "why" for every verdict.

The flight recorder (karpenter_tpu/tracing) answers *when* and the
telemetry plane (metrics/slo, metrics/sentinel) answers *how fast*;
this plane answers *why*: why a pod stayed unschedulable (the
elimination funnel over the instance-type catalog, the relaxation
steps burned, the admission cutoff that shed it), why a disruption
candidate was kept (`kept:<reason>` — same-type guard, budgets, PDBs,
the priority veto, the LP weak-duality certificate with its numbers),
and what the device LP's duals said about the tick (top-k binding
groups, reservation cap duals — the dual as an economic explanation).

Design rules, inherited from the flight recorder:

- **Decisions are never changed, only accounted.** Every note sits
  behind the existing seams; the recording sites read state the
  decision path already computed (the encoder's masks, the pruner's
  certificate, the validator's verdicts).
- **Determinism**: a record carries only decision provenance —
  counts, reasons, prices, dual values — that replays identically
  under the same KARPENTER_FAULTS schedule. `structure()` strips the
  (run-random) trace id, so chaos suites assert byte-identical
  explain payloads across replays — the `tracing.structure()`
  contract extended to explanations.
- **Healthy-path cost**: with no record open (or KARPENTER_EXPLAIN=0)
  every note is one global read and a return; the operator opens one
  record per tick, keyed by the tick's trace id so explanations join
  the flight recorder.
- **Bounded**: a ring of KARPENTER_EXPLAIN_RING finished tick records
  (default 64), with per-tick entry caps (KARPENTER_EXPLAIN_MAX_PODS
  / _MAX_NODES) so a million-pod outage cannot eat the heap; drops
  are counted, never silent.

Surfaces: `/debug/explain?pod=<key>|node=<name>|tick=<trace_id>` on
the observability server, `readyz()["explain"]`, the top-3 exclusion
reasons folded into unschedulable-pod corev1 Events, per-arm bench
`explain_summary` blocks, and `tools/explain.py`.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import Optional

ENV_ENABLED = "KARPENTER_EXPLAIN"
ENV_RING = "KARPENTER_EXPLAIN_RING"
ENV_MAX_PODS = "KARPENTER_EXPLAIN_MAX_PODS"
ENV_MAX_NODES = "KARPENTER_EXPLAIN_MAX_NODES"
DEFAULT_RING = 64
DEFAULT_MAX_ENTRIES = 4096
# per-tick cap on LP dual summaries (probe ladders can stage many)
MAX_LP_SUMMARIES = 32

# -- verdict taxonomy ---------------------------------------------------------
#
# Disruption verdicts: `consolidated` / `interrupted` for candidates a
# command acted on, `kept:<reason>` for everything scanned and left
# alone. Every `kept:` code below must have a row in README's verdict
# taxonomy table (tests/test_explain_docs.py, the test_fault_docs
# pattern).

VERDICT_CONSOLIDATED = "consolidated"
VERDICT_INTERRUPTED = "interrupted"

KEPT_NOT_CONSOLIDATABLE = "kept:not-consolidatable"
KEPT_DO_NOT_DISRUPT = "kept:do-not-disrupt"
KEPT_PDB_BLOCKED = "kept:pdb-blocked"
KEPT_NOMINATED = "kept:nominated"
KEPT_INTERRUPTED = "kept:interrupted"
KEPT_UNPRICED = "kept:unpriced"
KEPT_BUDGET = "kept:budget"
KEPT_SAME_TYPE = "kept:same-type-guard"
KEPT_PRIORITY_VETO = "kept:priority-veto"
KEPT_LP_PRUNE = "kept:lp-prune"
KEPT_NOT_CHEAPER = "kept:not-cheaper"
KEPT_SPOT_GATED = "kept:spot-to-spot-gated"
KEPT_NEEDS_MULTIPLE = "kept:needs-multiple-nodes"
KEPT_SIMULATION = "kept:simulation-failed"
KEPT_VALIDATION = "kept:validation-failed"


def enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") != "0"


def _env_int(key: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(key, str(default))))
    except ValueError:
        return default


def ring_size() -> int:
    return _env_int(ENV_RING, DEFAULT_RING)


class TickRecord:
    """One tick's decision provenance: per-pod scheduling verdicts,
    per-node disruption verdicts, per-solve LP dual summaries."""

    __slots__ = ("trace_id", "pods", "nodes", "lp", "truncated",
                 "_max_pods", "_max_nodes")

    def __init__(self, trace_id: str = ""):
        self.trace_id = trace_id
        self.pods: dict[str, dict] = {}
        self.nodes: dict[str, dict] = {}
        self.lp: list[dict] = []
        self.truncated = {"pods": 0, "nodes": 0, "lp": 0}
        self._max_pods = _env_int(ENV_MAX_PODS, DEFAULT_MAX_ENTRIES)
        self._max_nodes = _env_int(ENV_MAX_NODES, DEFAULT_MAX_ENTRIES)

    def _pod(self, key: str) -> Optional[dict]:
        rec = self.pods.get(key)
        if rec is None:
            if len(self.pods) >= self._max_pods:
                self.truncated["pods"] += 1
                return None
            rec = self.pods[key] = {}
        return rec

    def finish(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "pods": self.pods,
            "nodes": self.nodes,
            "lp": self.lp,
            "truncated": dict(self.truncated),
        }


# -- module state -------------------------------------------------------------

_lock = threading.Lock()
_ring: "deque[dict]" = deque(maxlen=DEFAULT_RING)
_active: Optional[TickRecord] = None


def _resize_ring() -> None:
    global _ring
    size = ring_size()
    if _ring.maxlen != size:
        with _lock:
            if _ring.maxlen != size:
                _ring = deque(_ring, maxlen=size)


def active() -> Optional[TickRecord]:
    """The open tick record, or None (kill switch off / outside a
    tick) — THE fast-path check every recording site makes first."""
    return _active


@contextmanager
def tick(trace_id: str = ""):
    """Open one tick's record (the operator's per-tick call). On exit
    the finished record lands in the ring and its verdicts tally into
    karpenter_explain_verdicts_total. No-op when KARPENTER_EXPLAIN=0;
    a nested open (a bench harness around an operator) degrades to the
    already-open record so the tick keeps one ring entry."""
    global _active
    if not enabled():
        yield None
        return
    if _active is not None:
        yield _active
        return
    record = TickRecord(trace_id)
    _active = record
    try:
        yield record
    finally:
        if _active is record:
            _active = None
        _finish(record)


def _finish(record: TickRecord) -> None:
    from karpenter_tpu.metrics.store import (
        EXPLAIN_TRUNCATED,
        EXPLAIN_VERDICTS,
    )

    for rec in record.nodes.values():
        verdict = rec.get("verdict")
        if verdict:
            EXPLAIN_VERDICTS.inc({"verdict": verdict})
    dropped = sum(record.truncated.values())
    if dropped:
        EXPLAIN_TRUNCATED.inc(value=float(dropped))
    _resize_ring()
    with _lock:
        _ring.append(record.finish())


# -- recording ----------------------------------------------------------------


def note_pod(key: str, **fields) -> None:
    """Merge provenance fields into one pod's verdict (error, reason
    code, shed cutoff, preemption victims, ...)."""
    record = _active
    if record is None:
        return
    rec = record._pod(key)
    if rec is not None:
        rec.update(fields)


def note_funnel(key: str, funnel: dict) -> None:
    """Attach the elimination funnel (explain/funnel.py) to a pod."""
    record = _active
    if record is None:
        return
    rec = record._pod(key)
    if rec is not None:
        rec["funnel"] = funnel


def note_relax(key: str, step: str) -> None:
    """One relaxation-ladder rung tried for a pod, in order."""
    record = _active
    if record is None:
        return
    rec = record._pod(key)
    if rec is not None:
        rec.setdefault("relaxed", []).append(step)


def note_candidate(name: str, verdict: str, weak: bool = False,
                   **fields) -> None:
    """One disruption candidate's verdict. `weak` notes never
    overwrite an existing verdict (a generic `kept:simulation-failed`
    must not stomp the specific priority-veto recorded moments
    earlier); strong notes do — a candidate probed and kept several
    times this tick ends on the LAST (most decisive) verdict, and a
    decided command's `consolidated` wins over any earlier keep."""
    record = _active
    if record is None:
        return
    existing = record.nodes.get(name)
    if existing is None:
        if len(record.nodes) >= record._max_nodes:
            record.truncated["nodes"] += 1
            return
    elif weak and existing.get("verdict"):
        return
    record.nodes[name] = {"verdict": verdict, **fields}


def note_lp(summary: dict) -> None:
    """One device-LP dual summary (lp_device.dual_summary)."""
    record = _active
    if record is None:
        return
    if len(record.lp) >= MAX_LP_SUMMARIES:
        record.truncated["lp"] += 1
        return
    record.lp.append(summary)


# -- queries ------------------------------------------------------------------


def records() -> list[dict]:
    """Finished tick records, oldest first, plus a snapshot of the
    open record (newest) so /debug/explain sees the current tick."""
    with _lock:
        out = list(_ring)
    record = _active
    if record is not None:
        out.append(record.finish())
    return out


def find_tick(trace_id: str) -> Optional[dict]:
    for rec in reversed(records()):
        if rec["trace_id"] == trace_id:
            return rec
    return None


def find_pod(key: str) -> Optional[dict]:
    """Newest explanation recorded for one pod, wrapped with the tick
    trace id it belongs to."""
    for rec in reversed(records()):
        hit = rec["pods"].get(key)
        if hit is not None:
            return {"trace_id": rec["trace_id"], "pod": key, **hit}
    return None


def find_node(name: str) -> Optional[dict]:
    """Newest disruption verdict recorded for one node."""
    for rec in reversed(records()):
        hit = rec["nodes"].get(name)
        if hit is not None:
            return {"trace_id": rec["trace_id"], "node": name, **hit}
    return None


def clear() -> None:
    with _lock:
        _ring.clear()


# -- digests ------------------------------------------------------------------


def digest() -> dict:
    """The readyz()["explain"] block: the last finished record's entry
    counts and verdict histogram."""
    with _lock:
        last = _ring[-1] if _ring else None
    if last is None:
        return {"ticks": 0}
    verdicts: dict[str, int] = {}
    for rec in last["nodes"].values():
        v = rec.get("verdict", "")
        if v:
            verdicts[v] = verdicts.get(v, 0) + 1
    with _lock:
        ticks = len(_ring)
    return {
        "ticks": ticks,
        "trace_id": last["trace_id"],
        "pods": len(last["pods"]),
        "nodes": len(last["nodes"]),
        "lp_solves": len(last["lp"]),
        "verdicts": dict(sorted(verdicts.items())),
        "truncated": dict(last["truncated"]),
    }


def summarize_ring() -> dict:
    """The per-arm bench `explain_summary` block: verdict histogram
    (node verdicts + pod reason codes) and funnel depth p50 over every
    record currently in the ring. Always well-formed — an arm that
    recorded nothing reports zeros and a null p50."""
    recs = records()
    verdicts: dict[str, int] = {}
    pod_codes: dict[str, int] = {}
    depths: list[int] = []
    pods = nodes = 0
    for rec in recs:
        pods += len(rec["pods"])
        nodes += len(rec["nodes"])
        for p in rec["pods"].values():
            code = p.get("code", "")
            if code:
                pod_codes[code] = pod_codes.get(code, 0) + 1
            funnel = p.get("funnel")
            if funnel:
                depths.append(len(funnel.get("stages", [])))
        for n in rec["nodes"].values():
            v = n.get("verdict", "")
            if v:
                verdicts[v] = verdicts.get(v, 0) + 1
    depths.sort()
    return {
        "ticks": len(recs),
        "pods_recorded": pods,
        "nodes_recorded": nodes,
        "verdicts": dict(sorted(verdicts.items())),
        "pod_codes": dict(sorted(pod_codes.items())),
        "funnel_depth_p50": (
            depths[len(depths) // 2] if depths else None
        ),
    }


def verdict_distance(observed: dict, expected: dict) -> float:
    """Normalized L1 (total-variation) distance between two verdict
    histograms, on [0, 1]: 0.0 means identical SHARES (counts may
    scale — a 2x-longer soak with the same decision mix is distance
    0), 1.0 means disjoint support. The soak judge (ISSUE 18) scores
    a run's summarize_ring() histogram against the scenario's
    declared expectation envelope with this — unexplained-verdict
    DRIFT gates on shape, never on raw volume."""
    tot_obs = float(sum(observed.values())) if observed else 0.0
    tot_exp = float(sum(expected.values())) if expected else 0.0
    if tot_obs <= 0.0 and tot_exp <= 0.0:
        return 0.0
    if tot_obs <= 0.0 or tot_exp <= 0.0:
        return 1.0
    keys = set(observed) | set(expected)
    return round(0.5 * sum(
        abs(observed.get(k, 0) / tot_obs - expected.get(k, 0) / tot_exp)
        for k in keys
    ), 6)


def structure(record: dict) -> str:
    """The deterministic skeleton of one record: everything but the
    run-random trace id, as canonical JSON — what chaos suites compare
    byte-for-byte across byte-identical fault replays (the
    tracing.structure() contract; every recorded field is decision
    provenance, so nothing else needs stripping)."""
    body = {k: v for k, v in record.items() if k != "trace_id"}
    return json.dumps(body, sort_keys=True)


def render_json(pod: str = "", node: str = "", trace_id: str = "") -> tuple[int, str]:
    """The /debug/explain body: (HTTP status, JSON). One selector at a
    time; no selector returns the digest plus the ring's tick ids."""
    if pod:
        found = find_pod(pod)
        if found is None:
            return 404, json.dumps({"error": f"no explanation for pod {pod!r}"})
        return 200, json.dumps(found)
    if node:
        found = find_node(node)
        if found is None:
            return 404, json.dumps(
                {"error": f"no explanation for node {node!r}"}
            )
        return 200, json.dumps(found)
    if trace_id:
        found = find_tick(trace_id)
        if found is None:
            return 404, json.dumps({"error": f"no record for tick {trace_id!r}"})
        return 200, json.dumps(found)
    return 200, json.dumps({
        "digest": digest(),
        "ticks": [r["trace_id"] for r in records()],
    })
