"""Reactive placement plane (ISSUE 17): the debounce window between
the watch stream and the micro-solve.

The operator's periodic reconcile loop makes arrival→bind latency a
function of the tick cadence: a pod created right after a tick waits a
full interval before the solver even sees it. This plane turns the
per-shard watch pump into the scheduling trigger. Pod-arrival events
(and capacity-freeing deletes) land here via `note_arrival` /
`note_capacity_freed`; a debounced batch (idle `KARPENTER_MICRO_DEBOUNCE_MS`,
bounded by `KARPENTER_MICRO_MAX_WAIT_MS` and `KARPENTER_MICRO_BATCH_MAX`)
fires `Operator.micro_step` into the incremental tick's O(dirty) path.

Determinism contract (the chaos suite's debounce-determinism test):
every decision here is a pure function of the operator-supplied clock
(`observe_now`) and the event sequence — no wall-clock reads, so batch
boundaries replay identically under the injectable clock. The
`threading.Event` wake exists only so the live `run()` loop can sleep
between events instead of polling; it carries no state the batch logic
depends on.

The plane also owns the arrival-stamp ledger that makes
`pod_to_bind_latency` an honest arrival→bind SLI: `_stamps` remembers
when each pending pod was first seen (preferring a numeric
`metadata.creation_timestamp` when the creator set one), the binding
queue subtracts it at bind time, and a TTL prune on full ticks bounds
the ledger by the pending backlog, never the fleet.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

PodKey = str  # "namespace/name" — the kube objects' own `.key` shape

ENV_ENABLE = "KARPENTER_REACTIVE"
ENV_DEBOUNCE_MS = "KARPENTER_MICRO_DEBOUNCE_MS"
ENV_MAX_WAIT_MS = "KARPENTER_MICRO_MAX_WAIT_MS"
ENV_BATCH_MAX = "KARPENTER_MICRO_BATCH_MAX"
ENV_STAMP_TTL_S = "KARPENTER_MICRO_STAMP_TTL_S"
# seconds between full audit/repack ticks when the reactive plane owns
# the loop; unset/0 keeps the legacy every-tick cadence
ENV_FULL_TICK_EVERY = "KARPENTER_FULL_TICK_EVERY"


def reactive_enabled() -> bool:
    """KARPENTER_REACTIVE gate, default ON (like the incremental tick:
    the reactive plane is the default path, the knob is the kill
    switch). Read per call so tests/bench can flip it live."""
    return os.environ.get(ENV_ENABLE, "1").lower() not in (
        "0", "false", "off"
    )


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ReactivePlane:
    """Debounced arrival batching with an injectable clock."""

    def __init__(self) -> None:
        # pending micro batch: insertion-ordered key -> RECEIPT time on
        # the plane clock (when the watch event reached us — the
        # debounce window's timeline; the arrival-stamp LEDGER below
        # keeps the creation-time stamps the latency SLI is measured
        # from, which may lie arbitrarily far in the past and must
        # never drive the window, or every batch would fire instantly)
        self._arrivals: dict[PodKey, float] = {}
        # persistent arrival ledger for arrival→bind measurement;
        # consumed at plan-enqueue time, TTL-pruned on full ticks
        self._stamps: dict[PodKey, float] = {}
        self._window_start: Optional[float] = None
        self._last_event: Optional[float] = None
        self._capacity_freed = False
        self._now: Optional[float] = None
        # live-loop wake: set on any event and on bind-plan enqueue so
        # run() drains immediately instead of sleeping the interval out
        self.wake = threading.Event()

    # -- knobs (re-read per call; satellite-1 discipline) --------------

    def debounce_s(self) -> float:
        return max(0.0, _env_float(ENV_DEBOUNCE_MS, 50.0)) / 1000.0

    def max_wait_s(self) -> float:
        return max(0.0, _env_float(ENV_MAX_WAIT_MS, 500.0)) / 1000.0

    def batch_max(self) -> int:
        return max(1, int(_env_float(ENV_BATCH_MAX, 256.0)))

    def stamp_ttl_s(self) -> float:
        return max(0.0, _env_float(ENV_STAMP_TTL_S, 900.0))

    # -- clock ---------------------------------------------------------

    def observe_now(self, now: float) -> None:
        """Advance the plane's clock (monotone; the operator calls this
        at the top of every step/micro_step with its injectable now)."""
        if self._now is None or now > self._now:
            self._now = now

    def clamp_stamp(self, ts) -> Optional[float]:
        """Arrival stamp for an event whose object carries a creation
        timestamp: prefer it when it lives on the same timeline as the
        plane clock (honest queue-time before the operator even saw
        the pod), fall back to `now` when it is absent, in the future,
        or from a different time domain entirely (a wall-clock stamp
        under a simulated clock would poison the latency SLI)."""
        now = self._now
        if now is None:
            return None
        if isinstance(ts, (int, float)) and (
            0.0 <= now - float(ts) <= self.stamp_ttl_s()
        ):
            return float(ts)
        return now

    # -- event intake --------------------------------------------------

    def note_arrival(self, key: PodKey, stamp: Optional[float] = None) -> bool:
        """An unbound pod appeared on the watch stream. Returns True if
        the pending batch changed. Before the first observe_now there
        is no timeline to stamp against (startup replay) — the arrival
        is ignored and the periodic path owns the pod."""
        if stamp is None:
            stamp = self._now
        if stamp is None:
            return False
        # earliest sighting wins: a MODIFIED after ADDED must not reset
        # the arrival stamp the bind latency is measured from
        if key not in self._stamps or stamp < self._stamps[key]:
            self._stamps[key] = stamp
        if not reactive_enabled():
            return False
        # the debounce window runs on RECEIPT time: a pod created long
        # before the operator saw it (startup backlog, relist replay)
        # still gets a full idle window to coalesce with its neighbors
        seen = self._now if self._now is not None else stamp
        if key not in self._arrivals:
            self._arrivals[key] = seen
        if self._window_start is None:
            self._window_start = seen
        self._last_event = seen
        self.wake.set()
        return True

    def note_capacity_freed(self, now: Optional[float] = None) -> None:
        """A bound pod vanished / a claim registered: capacity changed.
        Wakes the live loop and flags the operator to re-arm the full
        batcher so deferred demand retries against the freed room."""
        if now is not None:
            self.observe_now(now)
        if not reactive_enabled():
            return
        self._capacity_freed = True
        self.wake.set()

    def take_capacity_freed(self) -> bool:
        freed, self._capacity_freed = self._capacity_freed, False
        return freed

    # -- batch boundary ------------------------------------------------

    def pending(self) -> int:
        return len(self._arrivals)

    def ready(self, now: float) -> bool:
        """Deterministic batch boundary: fire on debounce-idle, on the
        max-wait bound, or when the batch hits the size cap."""
        if not self._arrivals:
            return False
        if len(self._arrivals) >= self.batch_max():
            return True
        # boundary tests MUST be the exact expressions next_deadline
        # hands back (`anchor + knob`, never `now - anchor >= knob`):
        # float rounding can make anchor+knob == now while now-anchor
        # < knob, and a loop sleeping until next_deadline would then
        # wake to a not-ready plane forever
        if self._last_event is not None and (
            now >= self._last_event + self.debounce_s()
        ):
            return True
        return self._window_start is not None and (
            now >= self._window_start + self.max_wait_s()
        )

    def next_deadline(self, now: float) -> Optional[float]:
        """Earliest future time `ready` could flip true — the live
        loop's sleep bound. None when nothing is pending."""
        if not self._arrivals:
            return None
        if self.ready(now):
            return now
        candidates = []
        if self._last_event is not None:
            candidates.append(self._last_event + self.debounce_s())
        if self._window_start is not None:
            candidates.append(self._window_start + self.max_wait_s())
        return min(candidates) if candidates else None

    def take_batch(self, now: float) -> dict:
        """Pop up to batch_max arrivals (FIFO). Leftovers keep their
        window so an oversized burst drains in consecutive firings.
        `debounce_latency` is the window wait — now minus the oldest
        RECEIPT in the batch, pure plane-clock (the chaos suite
        replays it byte-identically); arrival->bind latency is the
        stamp ledger's job, not this one's."""
        cap = self.batch_max()
        keys = list(self._arrivals.keys())[:cap]
        batch = {k: self._arrivals.pop(k) for k in keys}
        if self._arrivals:
            # re-anchor the window on the oldest leftover's receipt:
            # the next firing is due immediately (max-wait math, not a
            # reset)
            self._window_start = min(self._arrivals.values())
        else:
            self._window_start = None
            self._last_event = None
        latency = 0.0
        if batch:
            latency = max(0.0, now - min(batch.values()))
        return {"keys": keys, "stamps": batch, "debounce_latency": latency}

    def discard(self, key: PodKey) -> None:
        """A pending arrival became moot (bound/deleted before firing)."""
        self._arrivals.pop(key, None)
        if not self._arrivals:
            self._window_start = None
            self._last_event = None

    # -- arrival-stamp ledger ------------------------------------------

    def consume_stamps(self, keys) -> dict[PodKey, float]:
        """Pop arrival stamps for pods a bind plan now covers; the
        binding queue measures arrival→bind from these."""
        out = {}
        for key in keys:
            stamp = self._stamps.pop(key, None)
            if stamp is not None:
                out[key] = stamp
        return out

    def forget(self, key: PodKey) -> None:
        self._stamps.pop(key, None)
        self.discard(key)

    def status(self) -> dict:
        """readyz()["reactive"] digest."""
        return {
            "enabled": reactive_enabled(),
            "pending_batch": len(self._arrivals),
            "stamps": len(self._stamps),
            "capacity_freed": self._capacity_freed,
        }

    def prune(self, now: float) -> int:
        """Drop stamps older than the TTL (pods that shed and never
        bound). Called from full ticks: O(pending backlog)."""
        ttl = self.stamp_ttl_s()
        if ttl <= 0:
            return 0
        stale = [k for k, s in self._stamps.items() if now - s > ttl]
        for key in stale:
            self._stamps.pop(key, None)
            self.discard(key)
        return len(stale)
