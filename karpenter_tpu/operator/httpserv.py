"""Observability over HTTP: /metrics, /healthz, /readyz,
/debug/profile, /debug/traces, /debug/slo, /debug/explain.

Counterpart of the ports the reference mounts on its manager
(pkg/operator/operator.go:183-222: metrics server, healthz/readyz
probes, pprof handlers behind --enable-profiling). One threaded stdlib
server carries all routes — the split metrics/health ports of the
reference collapse onto one listener per process here, with the port
taken from Options.metrics_port (0 picks an ephemeral port, exposed as
`.port` for tests).

/debug/traces serves the flight recorder's tick-trace ring
(karpenter_tpu/tracing): plain JSON by default, Chrome-trace/Perfetto
with ?format=perfetto (load into ui.perfetto.dev), one trace's
segments with ?trace_id=<id> — the id a NodeClaim's
karpenter.sh/provenance annotation carries.

/debug/explain serves the decision explainability ring
(karpenter_tpu/explain): ?pod=<ns/name> the pod's elimination funnel
and verdict, ?node=<name> the node's disruption verdict,
?tick=<trace_id> one tick's whole record — the same id the flight
recorder keys on, so "why" joins "when".
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

log = logging.getLogger("karpenter.operator.http")


class ObservabilityServer:
    """Serves Prometheus text metrics and health probes for an
    operator. Probe callables return {"ok": bool, "checks": {...}};
    not-ok maps to HTTP 503 the way controller-runtime's checkers do."""

    def __init__(
        self,
        healthz: Callable[[], dict],
        readyz: Callable[[], dict],
        port: int = 8080,
        host: str = "127.0.0.1",
        profile_report: Optional[Callable[[], dict]] = None,
        slo_report: Optional[Callable[[], dict]] = None,
    ):
        self._healthz = healthz
        self._readyz = readyz
        self._profile_report = profile_report
        self._slo_report = slo_report
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (stdlib API)
                try:
                    outer._route(self)
                except BrokenPipeError:  # client went away mid-write
                    pass

            def log_message(self, fmt: str, *args) -> None:
                log.debug("http: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="observability-http",
            daemon=True,
        )
        self._thread.start()
        log.info("observability server on :%d (/metrics /healthz /readyz)",
                 self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- routing -----------------------------------------------------------

    @staticmethod
    def _query(handler: BaseHTTPRequestHandler) -> dict:
        from urllib.parse import parse_qsl

        _, _, query = handler.path.partition("?")
        return dict(parse_qsl(query))

    def _route(self, handler: BaseHTTPRequestHandler) -> None:
        path = handler.path.split("?", 1)[0]
        if path == "/metrics":
            from karpenter_tpu.metrics.exposition import render

            body = render().encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif path in ("/healthz", "/readyz"):
            probe = self._healthz if path == "/healthz" else self._readyz
            try:
                result = probe()
            except Exception as err:  # a probe must never crash the server
                result = {"ok": False, "checks": {"error": str(err)}}
            body = json.dumps(result).encode()
            handler.send_response(200 if result.get("ok") else 503)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif path == "/debug/profile" and self._profile_report is not None:
            body = json.dumps(self._profile_report()).encode()
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif path == "/debug/slo" and self._slo_report is not None:
            # the SLO engine's full report (metrics/slo.py): per-SLI
            # burn windows, verdicts, alert counts, objectives. A
            # report crash must not take the server down — same
            # contract as the probes.
            try:
                body = json.dumps(self._slo_report()).encode()
                status = 200
            except Exception as err:
                body = json.dumps({"error": str(err)}).encode()
                status = 500
            handler.send_response(status)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif path == "/debug/explain":
            # the decision explainability plane (karpenter_tpu/explain):
            # one pod's elimination funnel, one node's disruption
            # verdict, or one tick's whole record. Unknown keys 404;
            # a crash inside the plane 500s — it must never hang or
            # kill the server (the /debug/slo contract).
            from karpenter_tpu import explain

            params = self._query(handler)
            try:
                status, text = explain.render_json(
                    pod=params.get("pod", ""),
                    node=params.get("node", ""),
                    trace_id=params.get("tick", ""),
                )
                body = text.encode()
            except Exception as err:
                status = 500
                body = json.dumps({"error": str(err)}).encode()
            handler.send_response(status)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif path == "/debug/traces":
            from karpenter_tpu import tracing

            params = self._query(handler)
            trace_id = params.get("trace_id", "")
            if params.get("format") in ("perfetto", "chrome"):
                selected = (
                    tracing.find(trace_id) if trace_id
                    else tracing.traces()
                )
                body = json.dumps(tracing.to_chrome(selected)).encode()
            else:
                # one source of truth for the response shape
                body = tracing.render_json(trace_id).encode()
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        else:
            handler.send_response(404)
            handler.send_header("Content-Length", "0")
            handler.end_headers()
