"""Leader election over a store-backed Lease.

Counterpart of the reference's lease-based leader election
(operator.go:141-165: a coordination.k8s.io Lease named
"karpenter-leader-election", renewed by the active replica; standbys
take over when the lease expires). The lease lives in the same store
as everything else, so HA semantics — exactly one active operator,
failover on silence — are testable with two Operator instances sharing
one client.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.kube.objects import ObjectMeta

LEASE_NAME = "karpenter-leader-election"
LEASE_DURATION_SECONDS = 15.0  # controller-runtime default
RENEW_DEADLINE_SECONDS = 10.0


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, trimmed to what election needs."""

    kind = "Lease"
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name=LEASE_NAME))
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = LEASE_DURATION_SECONDS

    @property
    def key(self) -> str:
        return self.metadata.name

    def expired(self, now: float) -> bool:
        return now - self.renew_time > self.lease_duration


class LeaderElector:
    def __init__(self, kube, identity: str,
                 lease_duration: float = LEASE_DURATION_SECONDS):
        self.kube = kube
        self.identity = identity
        self.lease_duration = lease_duration

    def try_acquire_or_renew(self, now: Optional[float] = None) -> bool:
        """One election tick: returns True while this identity holds
        the lease. Acquires a missing/expired lease, renews an owned
        one, and defers to a live foreign holder."""
        now = time.time() if now is None else now
        lease = self.kube.get("Lease", LEASE_NAME)
        if lease is None:
            lease = Lease(holder=self.identity, renew_time=now,
                          lease_duration=self.lease_duration)
            try:
                self.kube.create(lease)
            except Exception:
                lease = self.kube.get("Lease", LEASE_NAME)
                return lease is not None and lease.holder == self.identity
            return True
        if lease.holder == self.identity or lease.expired(now):
            # write a fresh object (not an in-place mutation of the
            # shared stored one) and re-read after the update: when two
            # replicas race an expired lease, last-writer-wins on the
            # store and the re-read confirms exactly one winner
            claimed = Lease(
                metadata=lease.metadata, holder=self.identity,
                renew_time=now, lease_duration=self.lease_duration,
            )
            self.kube.update(claimed)
            final = self.kube.get("Lease", LEASE_NAME)
            return final is not None and final.holder == self.identity
        return False

    def is_leader(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        lease = self.kube.get("Lease", LEASE_NAME)
        return (
            lease is not None
            and lease.holder == self.identity
            and not lease.expired(now)
        )
