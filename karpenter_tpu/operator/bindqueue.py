"""Sharded pending-bind ledger: drain work is O(pending), never O(fleet).

The operator owns pod binding (the kube-scheduler's job in a real
cluster): every solve/command produces SchedulerResults whose pods must
be bound once their target node materializes. The old implementation
kept a flat list of results and re-walked every pod of every held plan
each drain — including pods long since bound — and probed node
existence with a full `kube.nodes()` scan per unresolved claim name.
At 100k pods a handful of held command plans made every tick pay a
fleet-sized walk.

This queue keeps the exact hold/drop semantics of the flat list (same
branch structure, same deadline contract, same batcher requeues) but:

- each enqueued results carries a `done` set of pod keys whose binding
  reached a TERMINAL outcome (bound by us, or requeued through the
  batcher after the target claim died). Subsequent drains skip them, so
  a plan held for ONE slow pod re-examines one pod, not the plan.
- node existence is answered by the mirror's O(1) `get_node`, not a
  fleet scan.
- every successful bind records arrival->bind latency (enqueue stamp to
  bind), drained by the operator into the `pod_to_bind_latency` SLO.
- held pods are tallied per state-plane shard (shard of the target
  node/claim name) into karpenter_state_shard_queue_pending{queue=bind}
  so a wedged shard is visible as a shard, not an anonymous backlog.

The queue is list-compatible where tests and the operator relied on
list behavior: `append(results)` enqueues under the results' own
`bind_deadline` stamp, and truthiness/len reflect held items.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from karpenter_tpu.metrics.store import STATE_SHARD_QUEUE_PENDING
from karpenter_tpu.state.shards import shard_count, shard_of


class _Item:
    __slots__ = ("results", "enqueued_at", "done", "arrivals")

    def __init__(self, results, enqueued_at: float, arrivals=None):
        self.results = results
        self.enqueued_at = enqueued_at
        # pod keys whose binding reached a terminal outcome; never
        # re-examined on later drains
        self.done: set[str] = set()
        # pod key -> watch-stream arrival stamp (ISSUE 17): when the
        # reactive plane saw the pod first. Bind latency is measured
        # from here so the SLI covers the wait-for-solve, not just the
        # queue residency; pods without a stamp (command plans, pods
        # predating the plane's clock) fall back to enqueued_at. For
        # held replace-then-drain plans, drain() re-stamps pods still
        # bound to the node being drained — a migrating pod is serving,
        # not pending, until its rebirth.
        self.arrivals: dict[str, float] = arrivals or {}

    def latency_start(self, pod_key: str) -> float:
        return self.arrivals.get(pod_key, self.enqueued_at)

    @property
    def deadline(self) -> float:
        # the stamp lives on the results (crash recovery and tests
        # read/write it there); the item defers to it
        return getattr(self.results, "bind_deadline", float("inf"))


class BindingQueue:
    """Holds scheduling results whose pods await binding; drains in
    time proportional to the pods still pending."""

    def __init__(
        self,
        kube,
        cluster,
        bind_one: Callable[[object, str], bool],
        requeue: Callable[[float], None],
        on_enqueue: Optional[Callable[[], None]] = None,
    ):
        self.kube = kube
        self.cluster = cluster
        self._bind_one = bind_one
        self._requeue = requeue
        # wake-on-enqueue (ISSUE 17): the live loop drains a fresh plan
        # immediately instead of sleeping the tick interval out
        self._on_enqueue = on_enqueue
        self._shards = shard_count()
        self._items: list[_Item] = []
        # arrival->bind walls of binds since the last take_latencies()
        self._latencies: list[float] = []
        # full-run latency ledger (bench p50/p99; bounded)
        self.history: deque[float] = deque(maxlen=200_000)

    # -- list compatibility (operator internals + tests) ---------------

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def append(self, results) -> None:
        """Enqueue under the results' own bind_deadline stamp (set one
        via `enqueue` for the TTL contract)."""
        self._items.append(_Item(results, time.time()))

    # -- queue API -----------------------------------------------------

    def enqueue(self, results, now: float, ttl: float, arrivals=None) -> None:
        results.bind_deadline = now + ttl
        self._items.append(_Item(results, now, arrivals))
        if self._on_enqueue is not None:
            self._on_enqueue()

    def take_latencies(self) -> list[float]:
        out, self._latencies = self._latencies, []
        return out

    def planned_pod_keys(self) -> set[str]:
        """Pod keys a held plan already covers (O(pending)): the
        micro-solve path filters these so an arrival never gets two
        competing placements while its plan is materializing."""
        keys: set[str] = set()
        for item in self._items:
            results = item.results
            for plan in results.new_node_plans:
                for pod in plan.pods:
                    if pod.key not in item.done:
                        keys.add(pod.key)
            for pods in results.existing_assignments.values():
                for pod in pods:
                    if pod.key not in item.done:
                        keys.add(pod.key)
        return keys

    def _record_latency(self, now: float, item: _Item, pod_key: str) -> None:
        latency = max(0.0, now - item.latency_start(pod_key))
        self._latencies.append(latency)
        self.history.append(latency)

    def drain(self, now: float) -> tuple[int, int]:
        """One binding pass. Returns (bound, held_plans). Results are
        dropped once fully bound or once every pod found a different
        home; a plan whose pods are still materializing is HELD under
        its deadline."""
        bound = 0
        remaining: list[_Item] = []
        held_by_shard: dict[int, int] = {}

        def hold(target: str, n: int = 1) -> None:
            s = shard_of(target, self._shards) if target else 0
            held_by_shard[s] = held_by_shard.get(s, 0) + n

        for item in self._items:
            if now > item.deadline:
                continue  # stale plan: its pods re-solve via the batcher
            results = item.results
            done = item.done
            unbound = False
            for plan in results.new_node_plans:
                pods = [p for p in plan.pods if p.key not in done]
                if not pods:
                    continue
                claim = (
                    self.kube.get_node_claim(plan.claim_name)
                    if plan.claim_name else None
                )
                node_name = claim.status.node_name if claim is not None else ""
                claim_gone = claim is None or (
                    claim.metadata.deletion_timestamp is not None
                )
                target = node_name or plan.claim_name or ""
                for pod in pods:
                    live = self.kube.get_pod(
                        pod.metadata.namespace, pod.metadata.name
                    )
                    if live is None or (
                        live.spec.node_name
                        and node_name
                        and live.spec.node_name != node_name
                    ):
                        # awaiting rebirth, or still bound to the node
                        # the command is draining: HOLD the plan until
                        # the pod comes free (deadline-bounded) — a
                        # plan dropped while its pods are still bound
                        # never fires at all (seed-11 oscillation).
                        # The pod is not pending here — it is still
                        # serving on the old node (or mid-rebirth), so
                        # the bind-latency clock must not run: advance
                        # its stamp to this sighting
                        item.arrivals[pod.key] = now
                        unbound = True
                        hold(target)
                        continue
                    if live.spec.node_name:
                        if not node_name and not claim_gone:
                            # still bound to the node being drained
                            # while the replacement claim has no
                            # status.node_name yet (created this tick,
                            # registers in a later lifecycle phase):
                            # HOLD the plan like the
                            # existing-assignments branch below —
                            # treating this as "already home" silently
                            # dropped pure-replace command plans before
                            # their claims ever registered (ADVICE r5).
                            # Still bound = not pending: keep the
                            # latency clock parked at this sighting
                            item.arrivals[pod.key] = now
                            unbound = True
                            hold(target)
                        continue  # already home (or nothing to wait on)
                    if node_name and not claim_gone:
                        if self._bind_one(live, node_name):
                            bound += 1
                            done.add(pod.key)
                            self._record_latency(now, item, pod.key)
                        else:
                            unbound = True
                            hold(target)
                    elif claim_gone:
                        # binding target never materializes (ICE /
                        # liveness timeout deleted the claim): re-queue
                        # the still-pending pod through the batcher —
                        # the controller analogue of the reference's
                        # pod-event-driven re-provisioning; simulated
                        # clock threaded through so batcher windows
                        # never mix wall and sim time
                        self._requeue(now)
                        done.add(pod.key)
                    else:
                        unbound = True  # node still materializing
                        hold(target)
            for node_name, pods in results.existing_assignments.items():
                pods = [p for p in pods if p.key not in done]
                if not pods:
                    continue
                # an in-flight assignment is keyed by CLAIM name; bind
                # only once the claim's node materialized — a bind to
                # the raw key would pin pods to a node that will never
                # exist under that name
                target = node_name
                if self.cluster.node_for_name(node_name) is None:
                    claim = self.kube.get_node_claim(node_name)
                    if claim is not None and (
                        claim.metadata.deletion_timestamp is None
                    ):
                        target = claim.status.node_name
                        if not target:
                            unbound = True
                            hold(node_name, len(pods))
                            continue
                    elif self.kube.get_node(node_name) is None:
                        # the claim died (ICE/liveness) before its node
                        # existed, or the node vanished: never bind to
                        # a name that will not materialize — re-queue
                        # the pods through the batcher instead
                        self._requeue(now)
                        done.update(p.key for p in pods)
                        continue
                for pod in pods:
                    live = self.kube.get_pod(
                        pod.metadata.namespace, pod.metadata.name
                    )
                    if live is not None and not live.spec.node_name:
                        if self._bind_one(live, target):
                            bound += 1
                            done.add(pod.key)
                            self._record_latency(now, item, pod.key)
                        else:
                            unbound = True
                            hold(target)
                    elif live is None or live.spec.node_name != target:
                        # awaiting rebirth from the drain, or still
                        # bound to the node being drained: HOLD the
                        # plan (deadline-bounded) so the pod lands on
                        # the planned capacity, not a fresh solve.
                        # Not pending — park the latency clock here
                        item.arrivals[pod.key] = now
                        unbound = True
                        hold(target)
            if unbound:
                remaining.append(item)
        self._items = remaining
        for s in range(self._shards):
            STATE_SHARD_QUEUE_PENDING.set(
                float(held_by_shard.get(s, 0)),
                {"queue": "bind", "shard": str(s)},
            )
        return bound, len(remaining)
