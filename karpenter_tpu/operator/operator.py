"""Operator runtime: controller registry + run loop.

Counterpart of pkg/operator/operator.go:117-249 and
pkg/controllers/controllers.go:66-148: builds the full controller set
over one kube client / state / provider, and drives them. The
reference runs controller-runtime watch-driven workers under leader
election; this runtime is tick-driven — `step(now)` advances every
controller once in dependency order, and `run()` loops it on wall
clock. Tests call `step` directly for determinism (the envtest
ExpectReconciled pattern).
"""

from __future__ import annotations

import gc
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.apis.v1alpha1.nodeoverlay import OverlayCloudProvider
from karpenter_tpu.disruption.conditions import (
    DisruptionConditionsController,
    ExpirationController,
    PodEventsController,
)
from karpenter_tpu.disruption.engine import DisruptionEngine
from karpenter_tpu.events.recorder import EventRecorder
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.lifecycle.garbagecollection import (
    GC_INTERVAL_SECONDS,
    GarbageCollectionController,
    NodeHealthController,
)
from karpenter_tpu.lifecycle.hygiene import (
    ConsistencyController,
    HydrationController,
    NodePoolStatusController,
)
from karpenter_tpu.lifecycle.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.lifecycle.termination import TerminationController
from karpenter_tpu.metrics.controllers import (
    NodeMetricsController,
    NodePoolMetricsController,
    PodMetricsController,
    StatusConditionMetricsController,
)
from karpenter_tpu import tracing
from karpenter_tpu.metrics.store import (
    BINDING_RETRY,
    OPERATOR_LAST_TICK,
    OPERATOR_RECOVERY,
    OPERATOR_TICK_DURATION,
)
from karpenter_tpu.operator.options import Options
from karpenter_tpu.provisioning.provisioner import Provisioner
from karpenter_tpu.provisioning.static import StaticCapacityController
from karpenter_tpu.solver import faults as _faults
from karpenter_tpu.state.cluster import Cluster, attach_informers
from karpenter_tpu.state.nodepoolhealth import HealthTracker

log = logging.getLogger("karpenter.operator")

# how long a scheduling result's placements stay bindable: pods evicted
# by a disruption command rebirth over several drain ticks and must
# land on the command's planned capacity, not a fresh solve; pods that
# never come back (deleted meanwhile) age the plan out. Command plans
# live longer: draining may not even START until the command's
# replacements initialize (bounded by the queue's 10-min retry
# deadline), so their TTL covers that window plus the drain itself.
BIND_RESULTS_TTL_SECONDS = 120.0
COMMAND_BIND_TTL_SECONDS = 720.0


@dataclass
class Operator:
    kube: KubeClient
    cloud_provider: CloudProvider
    options: Options = field(default_factory=Options)
    # HA: with leader_election on, step() is a no-op (beyond the
    # informer pump) unless this instance holds the lease — the
    # reference's active/passive replica model (operator.go:141-165)
    identity: str = "operator-1"
    leader_election: bool = False

    def __post_init__(self) -> None:
        from karpenter_tpu.operator.leader import LeaderElector
        from karpenter_tpu.utils.profiling import Profiler

        self.elector = LeaderElector(self.kube, self.identity)
        # per-phase wall-clock histograms (the pprof analogue,
        # operator.go:183-199); read via self.profiler.report()
        self.profiler = Profiler(enabled=self.options.enable_profiling)
        # decorators (kwok/main.go:37, controllers.go wiring)
        provider = MetricsCloudProvider(self.cloud_provider)
        self.overlay_controller = None
        if self.options.feature_gates.node_overlay:
            from karpenter_tpu.apis.v1alpha1.nodeoverlay import (
                NodeOverlayController,
            )

            provider = OverlayCloudProvider(provider, self.kube)
            self.overlay_controller = NodeOverlayController(self.kube, provider)
        self.provider = provider

        self.cluster = Cluster(self.kube)
        attach_informers(self.kube, self.cluster)
        # the recorder flushes corev1 Events through the API substrate
        # (events/recorder.go:52-72) — kubectl-describe visibility
        self.recorder = EventRecorder(kube=self.kube)
        self.health = HealthTracker()
        if self.overlay_controller is not None:
            # conflict events + consolidation invalidation need the
            # recorder/cluster built just above
            self.overlay_controller.recorder = self.recorder
            self.overlay_controller.cluster = self.cluster

        self.provisioner = Provisioner(
            self.kube, self.cluster, provider, options=self.options,
            recorder=self.recorder,
        )
        from karpenter_tpu.provisioning.preemption import (
            PreemptionController,
        )

        # priority preemption: pending higher-priority pods the solve
        # could not place nominate lower-priority victims (PDB-aware,
        # never equal/higher); landings ride the binding queue
        self.preemption = PreemptionController(
            self.kube, self.cluster, self.provisioner,
            recorder=self.recorder,
        )
        self.lifecycle = NodeClaimLifecycle(self.kube, provider, health=self.health)
        self.termination = TerminationController(
            self.kube, self.cluster, recorder=self.recorder
        )
        self.conditions = DisruptionConditionsController(
            self.kube, self.cluster, provider
        )
        self.pod_events = PodEventsController(self.kube, self.cluster)
        self.expiration = ExpirationController(self.kube)
        self.disruption = DisruptionEngine(
            self.kube, self.cluster, provider, self.provisioner,
            options=self.options, recorder=self.recorder,
        )
        from karpenter_tpu.disruption.interruption import (
            InterruptionController,
        )

        # spot interruption notices: poll the provider (through the
        # decorators — they forward the hook), replace before draining
        self.interruption = InterruptionController(
            self.kube, self.cluster, provider, self.disruption,
            recorder=self.recorder,
        )
        self.gc = GarbageCollectionController(self.kube, provider)
        self.node_health = NodeHealthController(self.kube, provider, self.options)
        self.consistency = ConsistencyController(self.kube, self.recorder)
        self.hydration = HydrationController(self.kube)
        self.nodepool_status = NodePoolStatusController(
            self.kube, self.cluster, health=self.health
        )
        self.static = StaticCapacityController(self.kube, self.cluster, self.options)
        self.pod_metrics = PodMetricsController(self.kube, self.cluster)
        self.node_metrics = NodeMetricsController(self.kube, self.cluster)
        self.nodepool_metrics = NodePoolMetricsController(self.kube, self.cluster)
        self.status_condition_metrics = StatusConditionMetricsController(self.kube)

        self._last_disruption = 0.0
        self._last_gc = 0.0
        self._last_metrics = 0.0
        self._last_resync = 0.0
        self._last_pending_scan = 0.0
        self._gc_frozen = False
        # AOT compile warm pool: background-compile the packing
        # kernels' shape buckets (and enable the persistent compile
        # cache) so the first tick's solve never waits on XLA — gated
        # (tests and embedders must not grow compile threads as a side
        # effect); KARPENTER_WARM_POOL=1 force-enables for deploys that
        # can't thread Options through
        self._warm_pool_thread = None
        import os as _os

        if self.options.solver_warm_pool or _os.environ.get(
            "KARPENTER_WARM_POOL"
        ) == "1":
            from karpenter_tpu.solver import warm_pool

            self._warm_pool_thread = warm_pool.start_background()
        # resilience knobs: Options export into the env the solver
        # layer reads per call (already-set env vars win — a deploy's
        # explicit environment outranks embedder defaults). The
        # resilience ladder itself is always on; these only tune it.
        for value, env_key in (
            (self.options.solve_deadline_ms, "KARPENTER_SOLVE_DEADLINE_MS"),
            (self.options.compile_deadline_ms,
             "KARPENTER_COMPILE_DEADLINE_MS"),
            (self.options.solve_hedge_ms, "KARPENTER_SOLVE_HEDGE_MS"),
            (self.options.solver_faults, "KARPENTER_FAULTS"),
        ):
            if value and env_key not in _os.environ:
                _os.environ[env_key] = str(value)
        # reactive placement plane (ISSUE 17): debounces watch-stream
        # pod arrivals into micro-solve batches and owns the
        # arrival-stamp ledger behind the arrival->bind SLI
        from karpenter_tpu.operator.reactive import ReactivePlane

        self.reactive = ReactivePlane()
        # plans whose pods await binding (the kube-scheduler's job in a
        # real cluster; this runtime owns the whole substrate, so it
        # binds pods to the nodes the solver placed them on). Sharded
        # queue: drain cost tracks pods still pending, never fleet size
        from karpenter_tpu.operator.bindqueue import BindingQueue

        self._pending_bindings = BindingQueue(
            self.kube, self.cluster, self._bind_one,
            lambda t: self.provisioner.batcher.trigger(now=t),
            on_enqueue=self.reactive.wake.set,
        )
        # crash/restart convergence: the first tick rebuilds in-flight
        # intent from the API alone (see _recover)
        self._recovered = False
        self._recovery: dict = {}

        # pod/node watch events drive the provisioning batcher
        # (provisioning/controller.go PodController/NodeController)
        # and the reactive plane: an unbound arrival opens/extends the
        # micro-solve debounce window; a bound pod vanishing frees
        # capacity that deferred demand should retry against
        def on_pod_event(event: str, pod) -> None:
            if event in ("ADDED", "MODIFIED") and not pod.spec.node_name:
                self.provisioner.batcher.trigger()
                self.reactive.note_arrival(
                    pod.key,
                    stamp=self.reactive.clamp_stamp(
                        pod.metadata.creation_timestamp
                    ),
                )
            elif event == "MODIFIED" and pod.spec.node_name:
                # bound (by us or anyone): a pending arrival is moot
                self.reactive.discard(pod.key)
            elif event == "DELETED":
                if pod.spec.node_name:
                    self.reactive.note_capacity_freed()
                self.reactive.forget(pod.key)

        self.kube.watch("Pod", on_pod_event)

        # claim registration is a capacity-freeing event too: planned
        # capacity materializing should wake the bind drain, and any
        # demand the envelope deferred can retry against it
        def on_claim_event(event: str, claim) -> None:
            if event == "MODIFIED" and claim.status.node_name:
                self.reactive.note_capacity_freed()

        self.kube.watch("NodeClaim", on_claim_event)
        # async transports (RealKubeClient's watch pump) expose a
        # queued-event hook: wake the live loop so deliver() runs now
        # instead of after the sleep; synchronous stores don't need it
        hook = getattr(self.kube, "set_event_pending_hook", None)
        if hook is not None:
            hook(self.reactive.wake.set)

        # Incremental disruption gate: the engine's candidate scan +
        # probe ladder is O(fleet) even when it decides nothing. When
        # the previous round came back empty-handed and NOTHING the
        # scan reads has changed since — no Node/NodeClaim/Pod/
        # NodePool/PDB watch traffic, same catalog fingerprint, and no
        # cron-scheduled budget that could open a window silently — the
        # same scan returns the same nothing, so skip it. A periodic
        # forced scan (KARPENTER_INCR_DISRUPTION_FORCE_SECONDS) bounds
        # staleness against anything the gate mis-models.
        from karpenter_tpu.kube.dirty import DirtyTracker

        self._disruption_dirty = DirtyTracker(self.kube).watch(
            "Node", "NodeClaim", "Pod", "NodePool", "PodDisruptionBudget"
        ).on_dirty(self.reactive.wake.set)
        self._disruption_idle = False    # last round found nothing
        self._disruption_catalog_fp = None
        self._last_forced_disruption = 0.0
        # tick liveness (wedge detection): wall clock of the last
        # COMPLETED tick, compared by healthz() against the tick
        # interval x KARPENTER_TICK_STALL_MULTIPLE. The interval is
        # only known once run() owns the loop; embedders driving
        # step() on their own clock get no staleness check (None).
        self._last_tick_wall: Optional[float] = None
        self._tick_interval: Optional[float] = None
        # the flight recorder's last tick trace id for THIS operator
        # (the process ring can interleave several operators in tests)
        self._last_trace_id = ""
        # SLO burn-rate engine (ISSUE 13): declarative SLIs over this
        # operator's tick signals, evaluated per completed tick under
        # the engine's injectable clock (replace self.slo before the
        # first step to pin determinism in chaos replays). Counter
        # deltas are tracked per operator — the metrics are
        # process-global and tests run several operators
        from karpenter_tpu.metrics.slo import SLOEngine
        from karpenter_tpu.metrics.store import (
            INCREMENTAL_DIVERGENCE,
            PRIORITY_SHED,
        )

        self.slo = SLOEngine()
        self._slo_divergences0 = INCREMENTAL_DIVERGENCE.total()
        self._slo_shed0 = PRIORITY_SHED.total()

    # -- one tick --------------------------------------------------------------

    def step(self, now: Optional[float] = None) -> None:
        """Advance every controller once, dependency-ordered: status
        controllers -> provisioning -> lifecycle -> disruption (on its
        poll period) -> orchestration -> termination -> hygiene.

        Every tick runs under a flight-recorder root span ("tick"):
        the per-phase children land in the trace ring served from
        /debug/traces, and the completed tick stamps the liveness
        gauge + duration histogram. A crashed tick (injected
        operator_crash, real exception) records its partial trace but
        never the liveness stamp — a wedged loop must look wedged."""
        now = time.time() if now is None else now
        wall0 = time.perf_counter()
        slo_wall0 = self.slo.clock()
        # anything noted BEFORE this tick opened (a solve run outside
        # any operator — bench, tools — in the same process) is not
        # this tick's signal: discard it so the optimality SLI only
        # ever scores the tick's own solves
        from karpenter_tpu.metrics import slo as _slo_mod

        _slo_mod.take_noted()
        with tracing.trace("tick") as root:
            self._last_trace_id = getattr(root, "trace_id", "")
            # the explain record shares the tick's trace id so
            # explanations join the flight recorder: a NodeClaim's
            # provenance annotation resolves to BOTH the span tree
            # (/debug/traces) and the decision record (/debug/explain)
            from karpenter_tpu import explain

            with explain.tick(self._last_trace_id):
                self._step(now)
        wall = time.perf_counter() - wall0
        OPERATOR_TICK_DURATION.observe(wall)
        # telemetry plane (ISSUE 13): the sentinel baselines the tick
        # wall, the SLO engine evaluates the tick's signals — both only
        # for COMPLETED ticks (a crashed tick must neither replenish an
        # error budget nor poison a baseline), like the liveness stamp
        from karpenter_tpu.metrics import sentinel as _sentinel

        _sentinel.observe("tick_wall", wall)
        self._observe_slo(self.slo.clock() - slo_wall0)
        self._last_tick_wall = time.time()
        OPERATOR_LAST_TICK.set(self._last_tick_wall)

    def _observe_slo(self, wall_s: float) -> None:
        """One SLO evaluation per completed tick. Signals come from
        the metrics the tick already maintained (counter deltas scoped
        to this operator) plus whatever the solver noted mid-tick
        (slo.note — gap_vs_lp); the engine itself is a pure function
        of this dict, which is what the chaos determinism contract
        asserts on."""
        from karpenter_tpu.metrics import slo as _slo
        from karpenter_tpu.metrics.store import (
            INCREMENTAL_DIVERGENCE,
            PRIORITY_SHED,
            SCHEDULER_UNSCHEDULABLE_PODS,
        )

        divergences = INCREMENTAL_DIVERGENCE.total()
        shed = PRIORITY_SHED.total()
        signals = {
            "tick_wall_s": wall_s,
            # the LIVE provisioning series only: disruption
            # simulations publish controller="disruption" counts whose
            # "unschedulable" verdict just means a probe kept its node
            # — scoring those would page schedulability on a healthy
            # fleet (both live paths — full Scheduler and incremental
            # tick — publish under controller="provisioner"). Read via
            # series() so an ABSENT series is None, not 0.0: a crashed
            # solve deliberately deletes its series, and scoring that
            # tick "good" would keep karpenter_slo_ok green through a
            # total solver outage — absent data is a data-free tick
            "unschedulable_pods": SCHEDULER_UNSCHEDULABLE_PODS.series()
            .get((("controller", "provisioner"),)),
            "oracle_divergences": divergences - self._slo_divergences0,
            "priority_shed": shed - self._slo_shed0,
        }
        self._slo_divergences0 = divergences
        self._slo_shed0 = shed
        # arrival->bind walls the binding queue collected this tick; an
        # absent signal is a data-free tick (no binds), not a zero
        lats = sorted(self._pending_bindings.take_latencies())
        if lats:
            signals["pod_to_bind_p99_s"] = lats[
                min(len(lats) - 1, int(0.99 * len(lats)))
            ]
            signals["pod_to_bind_p50_s"] = lats[
                min(len(lats) - 1, int(0.50 * len(lats)))
            ]
        signals.update(_slo.take_noted())
        self.slo.observe_tick(signals)

    def _step(self, now: float) -> None:
        # the reactive plane's clock advances before the informer pump
        # so arrivals delivered this tick stamp against a live now
        self.reactive.observe_now(now)
        # informer pump: under async delivery, queued watch events land
        # at tick start, so every controller in the tick reads one
        # consistent (possibly one-tick-stale) mirror — the informer
        # cache model the reference's Synced() barrier exists for
        self.kube.deliver()
        _faults.fire("crash_tick")
        if self.leader_election and not self.elector.try_acquire_or_renew(now):
            return  # standby replica: keep the mirror warm, do nothing
        if not self._recovered:
            self._recover(now)
        if self.reactive.take_capacity_freed():
            # freed/registered capacity: demand the envelope deferred
            # (or a solve shed) retries without waiting for the
            # periodic pending-scan backstop
            self.provisioner.batcher.trigger(now=now)
        if self.overlay_controller is not None:
            # overlay snapshot before anything consumes instance types
            self.overlay_controller.reconcile(now=now)
        # watch-driven controllers run O(changes) per tick; the
        # periodic full resync is the informer-resync analogue
        # backstopping in-place mutations that escaped the event fabric
        full = now - self._last_resync >= self.options.full_resync_seconds
        if full:
            self._last_resync = now
            if self._gc_frozen:
                # Resync-boundary GC hygiene: freeze() after the first
                # tick permanently exempts everything alive then from
                # cycle collection, so first-tick scratch objects that
                # were since replaced (relist swaps, first-solve
                # structures) would leak forever if they sit in cycles.
                # Unfreeze -> collect -> re-freeze here reclaims them
                # at resync cadence while keeping the steady-state
                # ticks free of full gen-2 scans (ADVICE r5).
                gc.unfreeze()
                gc.collect()
                gc.freeze()
            self.hydration.reconcile_all()
            self.nodepool_status.reconcile_all(now=now)
            # arrival-stamp ledger hygiene: stamps for pods that shed
            # and never bound age out at resync cadence (O(backlog))
            self.reactive.prune(now)
        else:
            self.hydration.reconcile_dirty()
            self.nodepool_status.reconcile_dirty(now=now)
        self.static.reconcile_all(now=now)

        # Planned placements bind BEFORE any fresh solve: pods evicted
        # by an in-flight disruption command rebirth pending at the end
        # of the previous tick, and re-solving them from scratch (the
        # batcher fires on their create events) can buy a NEW node for
        # pods the command already placed on existing capacity —
        # consolidation then finds the new node underutilized and the
        # fleet oscillates one command per poll, forever (seed-11
        # soak). Binding first consumes them.
        self._bind_pending(now=now)

        # Periodic re-solve backstop: the reference's provisioner is a
        # singleton controller that reconciles on a steady requeue, so
        # a pod left unschedulable by one solve is retried even with
        # no further watch traffic (provisioner.go:116). The batcher
        # here fires on events; without this, a pod that missed its
        # window (capacity blip, PDB-held drain, ICE) wedges Pending
        # forever once the event stream goes quiet.
        if (
            not self.provisioner.batcher._pending
            and now - self._last_pending_scan
            >= self.options.batch_max_duration
        ):
            self._last_pending_scan = now
            # the provisioner's own intake filter decides what counts
            # as provisionable — a pod it deliberately ignores
            # (foreign scheduler, rejected PVC) must not re-arm the
            # backstop forever
            if self.provisioner.get_pending_pods():
                self.provisioner.batcher.trigger(now=now)

        if self.provisioner.batcher.ready(now=now):
            with self.profiler.span("provisioning"), \
                    tracing.span("provision"):
                results = self.provisioner.reconcile(now=now)
            # crash window: NodeClaims written, binding plan not yet
            # queued — restart must re-derive the plan from the API
            _faults.fire("crash_provision")
            self._enqueue_bindings(results, now, BIND_RESULTS_TTL_SECONDS)
            # preemption acts on the round's capacity failures: a
            # pending higher-priority pod that fit nothing nominates
            # lower-priority victims; its landing plan rides the same
            # binding queue (nominate-then-evict — the pod-level
            # drain-after-replace ordering)
            with tracing.span("preemption") as sp:
                bindings = self.preemption.reconcile(results, now=now)
                sp.annotate(nominations=len(bindings))
            for binding in bindings:
                self._enqueue_bindings(
                    binding, now, BIND_RESULTS_TTL_SECONDS
                )

        with self.profiler.span("lifecycle"), tracing.span("lifecycle"):
            if full:
                self.lifecycle.reconcile_all(now=now)
            else:
                self.lifecycle.reconcile_dirty(now=now)
            tick = getattr(self.cloud_provider, "tick", None)
            if tick is not None:
                tick(now=now)
            if full:
                self.lifecycle.reconcile_all(now=now)
            else:
                self.lifecycle.reconcile_dirty(now=now)

        self._bind_pending(now=now)

        if full:
            self.pod_events.reconcile_all(now=now)
            self.conditions.reconcile_all(now=now)
            self.expiration.reconcile_all(now=now)
        else:
            self.pod_events.reconcile_dirty(now=now)
            self.conditions.reconcile_dirty(now=now)
            self.expiration.reconcile_dirty(now=now)

        # interruption notices run EVERY tick (a notice is a countdown,
        # not a policy choice — waiting a disruption poll period risks
        # the forced reclaim beating the replacement); each started
        # command's placements ride the binding queue like a disruption
        # command's, so displaced pods land on the pre-provisioned
        # claims instead of a fresh solve
        with self.profiler.span("interruption"), \
                tracing.span("interruption"):
            for command in self.interruption.reconcile(now=now):
                if command.results is not None:
                    self._enqueue_bindings(
                        command.results, now, COMMAND_BIND_TTL_SECONDS
                    )

        if now - self._last_disruption >= self.options.disruption_poll_seconds:
            # a skipped scan consumes its poll slot too — otherwise the
            # skip-gate's own checks (node_pools, catalog fingerprint)
            # re-run every step in exactly the idle clusters the gate
            # exists to make cheap
            self._last_disruption = now
            if not self._skip_disruption_scan(now):
                with self.profiler.span("disruption"), \
                        tracing.span("disruption"):
                    command = self.disruption.reconcile(now=now)
                    self._disruption_idle = (
                        command is None and not self.disruption.queue.active
                    )
                    if command is not None:
                        # crash window: command started (candidates
                        # tainted, replacements created) but its binding
                        # plan and the queue's in-memory command state
                        # die with us
                        _faults.fire("crash_disruption_started")
                    if command is not None and command.results is not None:
                        # the command's placements ARE the plan for the
                        # candidates' pods: route them through the
                        # binding queue so evicted pods land on the
                        # planned capacity instead of re-solving from
                        # scratch (the reference nominates pods onto the
                        # planned nodes and the provisioner skips
                        # nominated pods — without this, a fresh solve
                        # can buy a NEW node for the displaced pods and
                        # consolidation oscillates: found by the round-5
                        # seed-11 soak)
                        self._enqueue_bindings(
                            command.results, now, COMMAND_BIND_TTL_SECONDS
                        )
        self.disruption.queue.reconcile(now=now)

        with self.profiler.span("termination"), tracing.span("termination"):
            if full:
                self.termination.reconcile_all(now=now)
            else:
                self.termination.reconcile_dirty(now=now)
        self.node_health.reconcile(now=now)
        if now - self._last_gc >= GC_INTERVAL_SECONDS:
            self._last_gc = now
            self.gc.reconcile(now=now)
        if full:
            self.consistency.reconcile_all(now=now)
        else:
            self.consistency.reconcile_dirty(now=now)
        if now - self._last_metrics >= self.options.metrics_interval_seconds:
            self._last_metrics = now
            self.pod_metrics.reconcile_all(now=now)
            self.node_metrics.reconcile_all(now=now)
            self.nodepool_metrics.reconcile_all(now=now)
            self.status_condition_metrics.reconcile_all(now=now)

    # -- reactive micro-solve (ISSUE 17) ---------------------------------------

    def micro_step(self, now: Optional[float] = None) -> Optional[dict]:
        """Sub-tick arrival->bind round: deliver queued watch events,
        and when the reactive plane's debounce window closed, route the
        batch through the incremental tick's O(dirty) micro path and
        straight into the binding queue. Anything the envelope defers
        (cold cache, churn, ineligible shape, quarantine, priority
        pressure) re-arms the batcher for the next full tick — the
        micro path NEVER runs the full solver.

        Returns a small digest dict when a batch fired (the chaos
        suite's debounce-determinism test replays these), else None.
        Deterministic under the injectable `now`; crash faults
        propagate exactly like step()'s (the restart harness catches
        OperatorCrashError mid-micro-solve)."""
        from karpenter_tpu.metrics.store import (
            MICRO_BATCH_SIZE,
            MICRO_DEBOUNCE_LATENCY,
            MICRO_SOLVE,
        )
        from karpenter_tpu.operator.reactive import reactive_enabled

        now = time.time() if now is None else now
        self.reactive.observe_now(now)
        self.kube.deliver()
        if not reactive_enabled():
            return None
        if self.leader_election and not self.elector.is_leader():
            return None  # standby: the lease is renewed on full ticks
        if not self._recovered:
            return None  # the first FULL tick owns crash recovery
        if self.reactive.take_capacity_freed():
            self.provisioner.batcher.trigger(now=now)
        # drain plans whose nodes materialized since the last round:
        # wake-on-enqueue lands here long before the next full tick
        self._bind_pending(now=now)
        if not self.reactive.ready(now):
            return None
        batch = self.reactive.take_batch(now)
        planned = self._pending_bindings.planned_pod_keys()
        pods = []
        for key in batch["keys"]:
            ns, _, name = key.partition("/")
            live = self.kube.get_pod(ns, name)
            if live is None:
                self.reactive.forget(key)  # gone before the window shut
                continue
            if live.spec.node_name or key in planned:
                continue  # already home, or a held plan covers it
            pods.append(live)
        MICRO_BATCH_SIZE.observe(float(len(batch["keys"])))
        MICRO_DEBOUNCE_LATENCY.observe(batch["debounce_latency"])
        digest = {
            "batch": list(batch["keys"]),
            "solved": len(pods),
            "debounce_latency": batch["debounce_latency"],
            "outcome": "empty",
        }
        if not pods:
            MICRO_SOLVE.inc({"outcome": "empty"})
            return digest
        with tracing.trace("micro"), \
                tracing.span("solve.micro", batch=len(pods)):
            results = self.provisioner.micro_solve(pods, now=now)
        if results is None:
            # deferred: the periodic path owns the batch — stamps stay
            # in the ledger so the full tick's plan still measures
            # arrival->bind from the original sighting
            MICRO_SOLVE.inc({"outcome": "deferred"})
            self.provisioner.batcher.trigger(now=now)
            digest["outcome"] = "deferred"
            return digest
        MICRO_SOLVE.inc({"outcome": "served"})
        self._enqueue_bindings(results, now, BIND_RESULTS_TTL_SECONDS)
        self._bind_pending(now=now)
        digest["outcome"] = "served"
        return digest

    def _skip_disruption_scan(self, now: float) -> bool:
        """True when this poll's disruption scan provably repeats the
        last empty-handed one (see the gate's construction in
        __post_init__). Conservative: any dirt, any catalog movement,
        any cron-scheduled budget, an active command queue, or an
        expired force interval runs the scan."""
        from karpenter_tpu.provisioning.incremental_tick import (
            _env_float,
            incremental_enabled,
        )

        if not incremental_enabled() or not self._disruption_idle:
            self._disruption_dirty.clear()
            return False
        force_s = _env_float("KARPENTER_INCR_DISRUPTION_FORCE_SECONDS", 60.0)
        if now - self._last_forced_disruption >= force_s:
            self._last_forced_disruption = now
            self._disruption_dirty.clear()
            return False
        if self.disruption.queue.active:
            return False
        dirty = False
        for kind in ("Node", "NodeClaim", "Pod", "NodePool",
                     "PodDisruptionBudget"):
            # drain ALL kinds so one dirty kind doesn't leave the
            # others' stale keys to mis-trigger a later poll
            if self._disruption_dirty.drain(kind):
                dirty = True
        if self._disruption_dirty.relisted(
            "Node", "NodeClaim", "Pod", "NodePool", "PodDisruptionBudget"
        ):
            dirty = True
        if dirty:
            return False
        # a cron-scheduled budget can open a disruption window with no
        # watch traffic at all; never skip while one exists
        for pool in self.kube.node_pools():
            for budget in pool.spec.disruption.budgets:
                if budget.schedule is not None or budget.duration is not None:
                    return False
        # catalog movement (spot reprice, overlay, ICE) changes
        # consolidation economics without kube events
        try:
            from karpenter_tpu.solver.incremental import catalog_fingerprint

            fp = catalog_fingerprint(self.provisioner.ready_pools_with_types())
        except Exception:
            return False
        if fp != self._disruption_catalog_fp:
            self._disruption_catalog_fp = fp
            return False
        from karpenter_tpu.metrics.store import DISRUPTION_SCAN_SKIPPED

        DISRUPTION_SCAN_SKIPPED.inc()
        return True

    def _recover(self, now: float) -> None:
        """Crash/restart convergence: the first tick rebuilds in-flight
        intent from the API alone. A predecessor's memory — its
        `_pending_bindings` plans, lifecycle active set, launch
        backoffs, disruption queue — is gone; everything it had already
        WRITTEN (claims, taints, deletionTimestamps) survives on the
        API server and is the only truth.

        - claims still progressing (or deleting) re-enter the lifecycle
          active set so they advance without waiting for fresh events;
        - lost binding plans are re-derived by re-solving the pending
          pods against the surviving in-flight capacity (the scheduler
          routes them onto existing unregistered claims, so no capacity
          is bought twice);
        - a GC pass reaps launches that were decided but never
          acknowledged (cloud instances no claim records — the
          double-launch window) before any solve can bind onto them.
        """
        self._recovered = True
        # a crash between ticks must not resurrect a pre-crash
        # retained-state cache: rebuild the incremental tick's inputs
        # from the API mirror and force an oracle audit on its first
        # incremental serve (cheap insurance — this process is fresh,
        # but recovery may also run after leadership churn where the
        # informer stream, and thus the dirty sets, had gaps)
        self.provisioner.incremental.on_recover()
        OPERATOR_RECOVERY.inc({"action": "incremental_rebuild"})
        readopted = self.lifecycle.adopt_in_flight()
        deleting = sum(
            1 for c in self.kube.node_claims()
            if c.metadata.deletion_timestamp is not None
        )
        requeued = 0
        if readopted or deleting:
            pending = self.provisioner.get_pending_pods()
            requeued = len(pending)
            if pending:
                # nominated-but-unbound pods lost their plan with the
                # old process: re-solve them (deadline-free — the
                # in-flight claims they were headed to still count as
                # capacity, so the fresh solve re-derives the bindings)
                self.provisioner.batcher.trigger(now=now)
            self.gc.reconcile(now=now)
            self._last_gc = now
        if readopted:
            OPERATOR_RECOVERY.inc({"action": "readopted_claim"},
                                  value=float(readopted))
        if requeued:
            OPERATOR_RECOVERY.inc({"action": "requeued_pod"},
                                  value=float(requeued))
        self._recovery = {
            "readopted_claims": readopted,
            "requeued_pods": requeued,
            "deleting_claims": deleting,
        }

    def _bind_one(self, pod, node_name: str) -> bool:
        """Bind one pod; on a RETRYABLE failure (409/429/5xx — an
        apiserver conflict or throttle that outlived the transport's
        own retry budget) the plan is held and re-tried next tick
        under its remaining TTL instead of being dropped. Returns
        False when the binding must be re-enqueued."""
        from karpenter_tpu.kube.client import ConflictError
        from karpenter_tpu.kube.real import ApiError

        _faults.fire("crash_bind")
        try:
            self.kube.bind_pod(pod, node_name)
            return True
        except ConflictError:
            status = 409
        except ApiError as err:
            if err.status not in (409, 429) and not 500 <= err.status < 600:
                raise
            status = err.status
        BINDING_RETRY.inc({"status": str(status)})
        log.warning("binding %s -> %s failed with retryable HTTP %s; "
                    "re-enqueued", pod.key, node_name, status)
        return False

    def _enqueue_bindings(self, results, now: float, ttl: float,
                          arrivals: Optional[dict] = None) -> None:
        """Queue a plan for binding. Arrival stamps for the covered
        pods are consumed from the reactive plane (O(plan pods)) so
        `pod_to_bind_latency` measures from watch-stream arrival on
        BOTH paths — micro-solve and periodic — not from enqueue."""
        if arrivals is None:
            keys = [
                p.key
                for plan in results.new_node_plans
                for p in plan.pods
            ]
            keys += [
                p.key
                for pods in results.existing_assignments.values()
                for p in pods
            ]
            arrivals = self.reactive.consume_stamps(keys)
        self._pending_bindings.enqueue(results, now, ttl, arrivals=arrivals)

    def _bind_pending(self, now: Optional[float] = None) -> None:
        """Bind pods from completed scheduling results to their target
        nodes once those nodes exist (and immediately for placements on
        live nodes). Results are dropped once fully bound or once every
        pod found a different home. The queue's drain is O(pods still
        pending): terminally-handled pods are never re-walked."""
        now = time.time() if now is None else now
        if not self._pending_bindings:
            return
        with tracing.span("bind", plans=len(self._pending_bindings)) as sp:
            bound, held = self._pending_bindings.drain(now)
            sp.annotate(bound=bound, held=held)

    def healthz(self) -> dict:
        """Liveness: the process and its store are responsive, and the
        tick loop is actually ticking (operator.go:205-222 mounts
        healthz/readyz probes). Wedge detection: once a tick has
        completed, the last tick's age must stay under
        KARPENTER_TICK_STALL_MULTIPLE (default 10) x the tick interval
        — a reconcile loop stuck inside one tick (hung solve, wedged
        write) goes unhealthy instead of serving green forever."""
        try:
            self.kube.node_pools()
            store_ok = True
        except Exception:
            store_ok = False
        tick_fresh = True
        if self._last_tick_wall is not None and self._tick_interval:
            import os as _os

            try:
                multiple = float(
                    _os.environ.get("KARPENTER_TICK_STALL_MULTIPLE", "10")
                )
            except ValueError:
                multiple = 10.0
            age = time.time() - self._last_tick_wall
            tick_fresh = age <= multiple * max(self._tick_interval, 1e-3)
        return {
            "ok": store_ok and tick_fresh,
            "checks": {"store": store_ok, "tick_fresh": tick_fresh},
        }

    def readyz(self) -> dict:
        """Readiness: the mirror has caught up with the store (the
        reference additionally probes CRD presence; here the typed
        store is always 'installed')."""
        synced = self.cluster.synced()
        leader = (
            self.elector.is_leader() if self.leader_election else True
        )
        return {
            "ok": synced,
            "checks": {"informers_synced": synced, "leader": leader},
            # crash-recovery status: what the first tick rebuilt from
            # the API ({} until the first tick has run)
            "recovery": dict(self._recovery),
            # incremental live tick: last oracle-audit verdict,
            # retained-state fingerprint + age, quarantine state,
            # per-reason full-path fallback rollup
            "incremental": self.provisioner.incremental.status(),
            # reactive placement plane (ISSUE 17): debounce-window
            # backlog + arrival-stamp ledger size; micro-solve
            # serve/defer counts live under "incremental"."micro"
            "reactive": self.reactive.status(),
            # retained disruption snapshots (ISSUE 15): row reuse hit
            # rate + identity-audit verdicts for the fleet seam every
            # candidate scan and simulation consumes
            "disruption_snapshot": self.disruption.fleet_seam.status(),
            # per-pool launch/registration health (state/nodepoolhealth
            # ring buffers): a pool failing most recent registrations
            # is visible here and in
            # karpenter_nodepool_registration_healthy, not just in the
            # NodeRegistrationHealthy condition
            "nodepool_health": self.health.snapshot(),
            # malformed KARPENTER_FAULTS entries dropped at parse time:
            # a typo'd chaos knob must be visible here (and in
            # karpenter_faults_rejected_total), never silent
            "rejected_fault_specs": _faults.rejected_specs(),
            # solver mesh resolution (ISSUE 11 satellite): the
            # configured shard count vs what the last device solve
            # actually ran with — a fleet-wide KARPENTER_SOLVER_SHARDS
            # silently falling back to unsharded on a device-poor host
            # is visible here (and in karpenter_solver_shards), not
            # just in a log line
            "solver": self._solver_status(),
            # flight recorder: digest of THIS operator's last tick
            # trace (full tree at /debug/traces?trace_id=...). The id
            # can match several ring segments — an in-process solver
            # service adopts it for its remote hop — so pick the tick
            # segment explicitly
            "last_tick_trace": tracing.summarize(next(
                (t for t in tracing.find(self._last_trace_id)
                 if t["name"] == "tick"),
                None,
            )),
            # SLO engine digest (ISSUE 13): the multiwindow burn-rate
            # verdict per SLI, deterministic under the injectable clock
            # (full report at /debug/slo)
            "slo": self.slo.digest(),
            # regression-sentinel baselines (ISSUE 18 satellite): the
            # per-signal EWMA/MAD checkpoint view — a phase-boundary
            # reset_baselines() re-enters warmup, visible here as
            # warmed=false until the warmup count refills
            "sentinel": self._sentinel_snapshot(),
            # decision explainability (ISSUE 14): the last tick's
            # verdict counts (full records at /debug/explain)
            "explain": self._explain_digest(),
        }

    @staticmethod
    def _explain_digest() -> dict:
        from karpenter_tpu import explain

        return explain.digest()

    @staticmethod
    def _sentinel_snapshot() -> dict:
        from karpenter_tpu.metrics import sentinel

        return sentinel.snapshot()

    @staticmethod
    def _solver_status() -> dict:
        """readyz()["solver"]: configured vs observed shard counts.
        `shards_effective`/`devices_visible` are 0 until a device
        solve has dispatched — deliberately read from the solve path's
        own record rather than probing jax here, so a wedged backend
        can never hang the readiness probe."""
        from karpenter_tpu.solver.pack import (
            default_shards,
            last_resolved_shards,
        )

        observed = last_resolved_shards()
        return {
            "shards_configured": default_shards(),
            "shards_effective": observed["effective"],
            "devices_visible": observed["devices"],
        }

    def serve_observability(self, port: Optional[int] = None):
        """Mount /metrics (Prometheus text), /healthz, /readyz and —
        with profiling enabled — /debug/profile on an HTTP port
        (operator.go:183-222). Returns the running server; idempotent,
        but an explicit `port` conflicting with the running server is
        an error (a silent wrong-port server would scrape nothing)."""
        from karpenter_tpu.operator.httpserv import ObservabilityServer

        running = getattr(self, "_observability", None)
        if running is not None:
            if port is not None and port != 0 and port != running.port:
                raise ValueError(
                    f"observability server already on :{running.port}; "
                    f"requested :{port}"
                )
            return running
        self._observability = ObservabilityServer(
            healthz=self.healthz,
            readyz=self.readyz,
            port=self.options.metrics_port if port is None else port,
            host=self.options.metrics_bind_host,
            profile_report=(
                self.profiler.report if self.options.enable_profiling else None
            ),
            slo_report=self.slo.report,
        )
        self._observability.start()
        return self._observability

    def stop_observability(self) -> None:
        server = getattr(self, "_observability", None)
        if server is not None:
            server.stop()
            self._observability = None

    def _full_tick_every(self, tick_seconds: float) -> float:
        """Seconds between FULL ticks. Legacy cadence (every
        `tick_seconds`) unless the reactive plane owns the loop and
        KARPENTER_FULL_TICK_EVERY demotes full ticks to a background
        audit/repack cadence. Re-read per loop iteration (satellite-1
        discipline: cadence knobs are live, never construction-frozen)."""
        from karpenter_tpu.operator.reactive import (
            ENV_FULL_TICK_EVERY,
            _env_float,
            reactive_enabled,
        )

        if not reactive_enabled():
            return tick_seconds
        every = _env_float(ENV_FULL_TICK_EVERY, 0.0)
        return every if every > 0 else tick_seconds

    def run(self, stop_after: Optional[float] = None, tick_seconds: float = 1.0,
            serve: bool = False, should_stop=None) -> None:
        """Wall-clock loop (operator.Start). With the reactive plane
        enabled the loop is EVENT-DRIVEN: between full ticks it sleeps
        on the plane's wake event and runs `micro_step` when watch
        traffic (or a bind-plan enqueue) arrives, so arrival->bind is
        bounded by the debounce window, not the tick interval. Full
        `step()` ticks keep running every `tick_seconds` (or every
        KARPENTER_FULL_TICK_EVERY seconds when set) as the background
        audit/repack/disruption cadence and shadow-oracle safety net.

        `stop_after` bounds the run for embedding in tests/sims;
        `serve=True` mounts the observability endpoints for the
        duration of the loop (opt-in: embedders must not grow a
        listening port as a side effect — the binary serves
        explicitly); `should_stop` is polled each iteration (signal
        handlers)."""
        if serve:
            self.serve_observability()
        self._tick_interval = tick_seconds
        try:
            deadline = None if stop_after is None else time.time() + stop_after
            first_tick = True
            next_full = time.time()
            while deadline is None or time.time() < deadline:
                if should_stop is not None and should_stop():
                    break
                now = time.time()
                if now >= next_full:
                    self.step()
                    next_full = time.time() + self._full_tick_every(
                        tick_seconds
                    )
                    if first_tick:
                        first_tick = False
                        # Long-lived-service GC hygiene, AFTER the
                        # first tick so the synced cluster mirror and
                        # the first solve's jitted kernels exist: move
                        # them to the permanent generation so CPython's
                        # stop-the-world gen-2 scans stop re-walking
                        # ~1M mirror objects on every threshold
                        # crossing (the Go reference's GC is
                        # concurrent, so it never pays this).
                        # Per-reconcile garbage is still collected, and
                        # full-resync ticks unfreeze+collect+refreeze
                        # so replaced first-tick objects in cycles are
                        # reclaimed at resync cadence (see step()).
                        gc.collect()
                        gc.freeze()
                        self._gc_frozen = True
                else:
                    self.micro_step(now)
                # sleep until whichever comes first: the next full
                # tick, the plane's next debounce deadline, or the run
                # deadline — interruptible by the wake event so a
                # watch burst or bind-plan enqueue is handled NOW
                now = time.time()
                wake_at = next_full
                micro_deadline = self.reactive.next_deadline(now)
                if micro_deadline is not None:
                    wake_at = min(wake_at, micro_deadline)
                if deadline is not None:
                    wake_at = min(wake_at, deadline)
                timeout = max(0.0, min(wake_at - now, tick_seconds))
                if timeout <= 0:
                    # floor: a batch that is ready but unconsumable
                    # (standby replica, disabled plane) must not spin
                    timeout = min(tick_seconds, 0.005)
                self.reactive.wake.wait(timeout)
                self.reactive.wake.clear()
        finally:
            if serve:
                self.stop_observability()
