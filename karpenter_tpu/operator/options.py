"""Operator options and feature gates.

Counterpart of pkg/operator/options/options.go:67-203: one flat config
struct (flags/env in the reference; kwargs here) plus feature gates
parsed from a comma string ("SpotToSpotConsolidation=true,...").
Defaults mirror the reference's.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    node_repair: bool = False
    reserved_capacity: bool = True
    spot_to_spot_consolidation: bool = False
    node_overlay: bool = False
    static_capacity: bool = False

    @classmethod
    def parse(cls, gates: str) -> "FeatureGates":
        out = cls()
        mapping = {
            "NodeRepair": "node_repair",
            "ReservedCapacity": "reserved_capacity",
            "SpotToSpotConsolidation": "spot_to_spot_consolidation",
            "NodeOverlay": "node_overlay",
            "StaticCapacity": "static_capacity",
        }
        for part in gates.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, value = part.partition("=")
            attr = mapping.get(name.strip())
            if attr is not None:
                setattr(out, attr, value.strip().lower() in ("true", "1", ""))
        return out


@dataclass
class Options:
    batch_idle_duration: float = 1.0       # options.go:126
    batch_max_duration: float = 10.0       # options.go:127
    preference_policy: str = "Respect"     # Respect | Ignore
    min_values_policy: str = "Strict"      # Strict | BestEffort
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    metrics_port: int = 8080
    health_probe_port: int = 8081
    # bind-all default so external scrapers / kubelet probes reach the
    # endpoints in a pod (the reference's metrics server behavior);
    # tests override to loopback or pass port=0
    metrics_bind_host: str = "0.0.0.0"
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    log_level: str = "info"
    cluster_name: str = ""
    disruption_poll_seconds: float = 10.0  # disruption/controller.go:69
    metrics_interval_seconds: float = 10.0  # object-gauge republish cadence
    # watch-driven controllers run O(changes) per tick; a periodic full
    # resync (the informer-resync analogue) backstops any in-place
    # mutation that escaped the event fabric
    full_resync_seconds: float = 30.0
    enable_profiling: bool = False         # operator.go:183-199 pprof gate
    # Pods consuming DRA ResourceClaims are rejected with a permanent
    # scheduling error while set (options.go:130 ignore-dra-requests;
    # default true upstream until formal DRA support lands)
    ignore_dra_requests: bool = True
    # AOT compile warm pool at operator startup: background-compile
    # the packing kernels' shape buckets and enable the persistent
    # compile cache (solver/warm_pool.py). Off by default so tests and
    # embedders don't grow compile threads; KARPENTER_WARM_POOL=1 in
    # the environment force-enables it too.
    solver_warm_pool: bool = False
    # Solver resilience layer (solver/resilience.py). The env knobs
    # (KARPENTER_SOLVE_DEADLINE_MS etc.) stay authoritative — these
    # options export into the environment at operator startup when the
    # env doesn't already set them, so embedders configure resilience
    # the same way they configure everything else. 0 disables.
    solve_deadline_ms: int = 0      # hard per-solve wall budget
    compile_deadline_ms: int = 0    # separate budget for the XLA compile
    solve_hedge_ms: int = 0         # fire the host FFD hedge after this
    solver_faults: str = ""         # KARPENTER_FAULTS spec (chaos/bench)


DEFAULT_OPTIONS = Options()
