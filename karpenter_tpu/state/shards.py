"""State-plane sharding: the hash partition every sharded seam shares.

ISSUE 16 shards the state plane — the watch/list pump's logical
streams, the retained-state invalidation domains, and the bind/evict
queues — by ONE consistent hash of the node/claim key, so that a
continuity loss (a 410 on one shard's stream) or a queue drain touches
only the keys that hash to the affected shard. Everything here is a
pure function of the key string: shard routing must be stable across
processes and restarts (retained epochs survive neither, but the
regression suite replays event orders across shard counts and the
routes must agree).

Routing is BY NODE KEY wherever a kind's events affect a node-keyed
retained row: a Pod event routes by the node the pod is bound to (its
usage lands on that node's row), a NodeClaim by its materialized node
name (falling back to the claim name while in flight — exactly the
state key `_state_node_key` answers to in that window). Unbound pods
route by their own key: they touch no retained row, and any stable
route keeps their stream partition consistent. Kinds with fleet-wide
effect (DaemonSet, PodDisruptionBudget, NodePool) are not sharded —
consumers treat their relists as whole-cache events.
"""

from __future__ import annotations

import os
import zlib
from typing import Optional

ENV_SHARDS = "KARPENTER_STATE_SHARDS"
DEFAULT_SHARDS = 8

# kinds whose events are routed by node/claim key; everything else has
# fleet-wide effect and stays on the unsharded (whole-cache) contract
SHARDED_KINDS = frozenset({"Node", "NodeClaim", "Pod"})


def shard_count() -> int:
    """The configured shard count (KARPENTER_STATE_SHARDS, default 8,
    floor 1). Read per call so tests can vary it; long-lived holders
    (clients, queues) capture it at construction."""
    raw = os.environ.get(ENV_SHARDS, "")
    try:
        n = int(raw) if raw else DEFAULT_SHARDS
    except ValueError:
        n = DEFAULT_SHARDS
    return max(1, n)


def shard_of(key: str, shards: Optional[int] = None) -> int:
    """Stable shard for one state key. crc32, not hash(): Python's
    string hash is salted per process, and shard routes must agree
    between the operator that wrote a retained row and the test (or
    restarted operator) replaying the event order."""
    n = shard_count() if shards is None else shards
    if n <= 1:
        return 0
    return zlib.crc32(key.encode()) % n


def route_key(kind: str, obj) -> str:
    """The key an event routes by — the node/claim key whose retained
    row the event can touch (module doc)."""
    if kind == "Pod":
        node = obj.spec.node_name
        return node if node else obj.key
    if kind == "NodeClaim":
        node = obj.status.node_name
        return node if node else obj.metadata.name
    return obj.key


def shard_of_event(kind: str, obj, shards: Optional[int] = None) -> int:
    return shard_of(route_key(kind, obj), shards)
