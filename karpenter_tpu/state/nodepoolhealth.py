"""Per-NodePool launch/registration health tracking.

Counterpart of pkg/state/nodepoolhealth (ring buffer capacity 10):
recent registration outcomes decide Healthy/Degraded for the
NodeRegistrationHealthy condition.
"""

from __future__ import annotations

from collections import deque

CAPACITY = 10
UNHEALTHY_THRESHOLD = 0.5  # more than half failures -> degraded


class HealthTracker:
    def __init__(self) -> None:
        self._buffers: dict[str, deque[bool]] = {}

    def record(self, pool_name: str, success: bool) -> None:
        if not pool_name:
            return
        self._buffers.setdefault(pool_name, deque(maxlen=CAPACITY)).append(success)

    def healthy(self, pool_name: str) -> bool:
        buf = self._buffers.get(pool_name)
        if not buf:
            return True
        failures = sum(1 for ok in buf if not ok)
        return failures / len(buf) <= UNHEALTHY_THRESHOLD or len(buf) < 3

    def reset(self, pool_name: str) -> None:
        self._buffers.pop(pool_name, None)
