"""Per-NodePool launch/registration health tracking.

Counterpart of pkg/state/nodepoolhealth (ring buffer capacity 10):
recent registration outcomes decide Healthy/Degraded for the
NodeRegistrationHealthy condition. Every record publishes the
`karpenter_nodepool_registration_healthy` gauge and the tracker
snapshots into `Operator.readyz()["nodepool_health"]` — the state was
previously invisible outside the condition writer.
"""

from __future__ import annotations

from collections import deque

CAPACITY = 10
UNHEALTHY_THRESHOLD = 0.5  # more than half failures -> degraded


class HealthTracker:
    def __init__(self) -> None:
        self._buffers: dict[str, deque[bool]] = {}

    def record(self, pool_name: str, success: bool) -> None:
        if not pool_name:
            return
        self._buffers.setdefault(pool_name, deque(maxlen=CAPACITY)).append(success)
        self._publish(pool_name)

    def healthy(self, pool_name: str) -> bool:
        buf = self._buffers.get(pool_name)
        if not buf:
            return True
        failures = sum(1 for ok in buf if not ok)
        return failures / len(buf) <= UNHEALTHY_THRESHOLD or len(buf) < 3

    def reset(self, pool_name: str) -> None:
        self._buffers.pop(pool_name, None)
        from karpenter_tpu.metrics.store import (
            NODEPOOL_REGISTRATION_HEALTHY,
        )

        # the pool's history is gone (pool deleted or hash-reset):
        # drop the series rather than freeze a stale verdict
        NODEPOOL_REGISTRATION_HEALTHY.delete({"nodepool": pool_name})

    def _publish(self, pool_name: str) -> None:
        from karpenter_tpu.metrics.store import (
            NODEPOOL_REGISTRATION_HEALTHY,
        )

        NODEPOOL_REGISTRATION_HEALTHY.set(
            1.0 if self.healthy(pool_name) else 0.0,
            {"nodepool": pool_name},
        )

    def snapshot(self) -> dict:
        """Operator-facing view (readyz): which tracked pools are
        degraded right now, with their recent failure counts."""
        degraded = {}
        for pool_name, buf in self._buffers.items():
            if not self.healthy(pool_name):
                degraded[pool_name] = {
                    "recent_failures": sum(1 for ok in buf if not ok),
                    "window": len(buf),
                }
        return {
            "tracked_pools": len(self._buffers),
            "degraded": degraded,
        }
