"""In-memory cluster state mirror.

Counterpart of pkg/controllers/state (cluster.go, statenode.go):
a thread-safe mirror of nodes + nodeclaims keyed by provider id,
pod -> node bindings with per-node resource usage, daemonset tracking,
nomination windows, consolidation timestamps and per-NodePool tallies.
Fed by watch events (see `informers.attach`), consumed by the
provisioner (snapshot via `deep_copy_nodes`) and the disruption engine.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.apis.v1.labels import (
    DO_NOT_DISRUPT_ANNOTATION,
    NODEPOOL_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_INITIALIZED,
    COND_REGISTERED,
    NodeClaim,
)
from karpenter_tpu.kube.objects import DaemonSet, Node, Pod, Taint
from karpenter_tpu.kube.client import ADDED, DELETED, KubeClient, MODIFIED
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.scheduling.taints import filter_ephemeral
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.resources import ResourceList

NOMINATION_WINDOW_SECONDS = 20.0

# the kinds whose watch streams feed the mirror — attach_informers
# registers handlers for exactly these, and synced() refuses while any
# of their events are undelivered; one constant so the two can't drift
INFORMER_KINDS = ("Node", "NodeClaim", "Pod", "DaemonSet")


class StateNode:
    """A Node + NodeClaim pair (statenode.go:119)."""

    def __init__(self, node: Optional[Node] = None, node_claim: Optional[NodeClaim] = None):
        self.node = node
        self.node_claim = node_claim
        self.marked_for_deletion = False
        self.nominated_until = 0.0
        self.pod_keys: set[str] = set()
        self.pod_usage: ResourceList = {}
        self.daemon_usage: ResourceList = {}

    # -- identity -------------------------------------------------------------

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.spec.provider_id:
            return self.node.spec.provider_id
        if self.node_claim is not None:
            return self.node_claim.status.provider_id
        return ""

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.metadata.name
        if self.node_claim is not None and self.node_claim.status.node_name:
            return self.node_claim.status.node_name
        return ""

    def managed(self) -> bool:
        return self.node_claim is not None or (
            self.node is not None and NODEPOOL_LABEL in self.node.metadata.labels
        )

    def nodepool_name(self) -> str:
        return self.labels().get(NODEPOOL_LABEL, "")

    # -- lifecycle ------------------------------------------------------------

    def registered(self) -> bool:
        return self.node_claim is not None and self.node_claim.status_conditions.is_true(
            COND_REGISTERED
        )

    def initialized(self) -> bool:
        if self.node_claim is None:
            return self.node is not None  # unmanaged nodes count as initialized
        return self.node_claim.status_conditions.is_true(COND_INITIALIZED)

    def deleting(self) -> bool:
        if self.marked_for_deletion:
            return True
        for obj in (self.node, self.node_claim):
            if obj is not None and obj.metadata.deletion_timestamp is not None:
                return True
        return False

    # -- shape ----------------------------------------------------------------

    def labels(self) -> dict[str, str]:
        out: dict[str, str] = {}
        if self.node_claim is not None:
            out.update(self.node_claim.metadata.labels)
        if self.node is not None:
            out.update(self.node.metadata.labels)
        return out

    def annotations(self) -> dict[str, str]:
        out: dict[str, str] = {}
        if self.node_claim is not None:
            out.update(self.node_claim.metadata.annotations)
        if self.node is not None:
            out.update(self.node.metadata.annotations)
        return out

    def taints(self) -> list[Taint]:
        """Node taints; while a managed node initializes, known
        ephemeral taints AND the claim's own startupTaints are ignored
        — both clear before pods run (statenode.go:315-328)."""
        source = self.node.spec.taints if self.node is not None else (
            list(self.node_claim.spec.taints) + list(self.node_claim.spec.startup_taints)
            if self.node_claim is not None
            else []
        )
        if not self.initialized() and self.managed():
            startup = (
                self.node_claim.spec.startup_taints
                if self.node_claim is not None
                else ()
            )
            return [
                t for t in filter_ephemeral(source)
                if not any(
                    t.key == s.key and t.effect == s.effect for s in startup
                )
            ]
        return list(source)

    def capacity(self) -> ResourceList:
        if self.node is not None and self.node.status.capacity:
            return self.node.status.capacity
        if self.node_claim is not None:
            return self.node_claim.status.capacity
        return {}

    def allocatable(self) -> ResourceList:
        if self.registered() or self.node_claim is None:
            if self.node is not None and self.node.status.allocatable:
                return self.node.status.allocatable
        if self.node_claim is not None:
            return self.node_claim.status.allocatable
        return {}

    def used(self) -> ResourceList:
        return resutil.merge(self.pod_usage, self.daemon_usage)

    def available(self) -> ResourceList:
        return resutil.subtract(self.allocatable(), self.used())

    def requirements(self) -> Requirements:
        return Requirements.from_labels(self.labels())

    # -- scheduling hooks -----------------------------------------------------

    def nominate(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self.nominated_until = now + NOMINATION_WINDOW_SECONDS

    def nominated(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        return self.nominated_until > now

    # -- disruption validation (statenode.go:202-280) -------------------------

    def validate_node_disruptable(self) -> Optional[str]:
        if self.node is None or self.node_claim is None:
            return "node is not managed or not yet paired"
        if self.annotations().get(DO_NOT_DISRUPT_ANNOTATION) == "true":
            return "disruption is blocked through the do-not-disrupt annotation"
        if NODEPOOL_LABEL not in self.labels():
            return "node does not have the nodepool label"
        if not self.initialized():
            return "node is not initialized"
        return None

    def shallow_copy(self) -> "StateNode":
        out = StateNode(self.node, self.node_claim)
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        out.pod_keys = set(self.pod_keys)
        out.pod_usage = dict(self.pod_usage)
        out.daemon_usage = dict(self.daemon_usage)
        return out


@dataclass
class PodSchedulingTimes:
    first_seen: float = 0.0
    scheduling_decision: float = 0.0
    bound: float = 0.0


@dataclass
class NodePoolState:
    """Static-pool accounting (statenodepool.go:30-170): observed
    active/deleting claim counts plus in-flight launch reservations so
    concurrent (or informer-lagged) launch paths can't overshoot a
    static pool's replica count."""

    active: int = 0
    deleting: int = 0
    reserved: int = 0  # launches granted but not yet observed as claims


class Cluster:
    """The mirror (cluster.go:54-118)."""

    def __init__(self, kube: KubeClient):
        self.kube = kube
        self._lock = threading.RLock()
        self._by_provider: dict[str, StateNode] = {}
        self._by_name: dict[str, str] = {}          # node name -> provider id
        self._claim_keys: dict[str, str] = {}        # claim name -> provider id
        self._unpaired_claims: dict[str, StateNode] = {}
        self._bindings: dict[str, str] = {}          # pod key -> node name
        self._daemonsets: dict[str, DaemonSet] = {}
        self._antiaffinity_pods: dict[str, Pod] = {}
        self._unconsolidated_at: float = 0.0
        self._pod_times: dict[str, PodSchedulingTimes] = {}
        self._pool_state: dict[str, NodePoolState] = {}
        self._claim_pool: dict[str, tuple[str, bool]] = {}  # name -> (pool, deleting)

    # -- queries --------------------------------------------------------------

    def nodes(self) -> list[StateNode]:
        with self._lock:
            return list(self._by_provider.values()) + list(self._unpaired_claims.values())

    def node_for_name(self, name: str) -> Optional[StateNode]:
        with self._lock:
            pid = self._by_name.get(name)
            return self._by_provider.get(pid) if pid else None

    def node_for_key(self, name: str) -> Optional[StateNode]:
        """Resolve a node name OR an in-flight claim name — scheduling
        results key existing-node assignments by whichever the state
        node currently answers to (_state_node_key)."""
        with self._lock:
            pid = self._by_name.get(name) or self._claim_keys.get(name)
            if pid:
                return self._by_provider.get(pid)
            return self._unpaired_claims.get(name)

    def unpaired_claim_names(self) -> list[str]:
        """Names of claims tracked without a node yet (launched or
        launching capacity still materializing) — the in-flight set a
        crash-recovery pass re-adopts, and what restart-convergence
        tests assert drains to empty."""
        with self._lock:
            return sorted(self._unpaired_claims)

    def deep_copy_nodes(self) -> list[StateNode]:
        """Snapshot for a scheduling run (cluster.go:249)."""
        with self._lock:
            return [n.shallow_copy() for n in self.nodes()]

    def daemonsets(self) -> list[DaemonSet]:
        with self._lock:
            return list(self._daemonsets.values())

    def nodepool_resources(self) -> dict[str, ResourceList]:
        """Per-NodePool committed capacity (cluster.go:565)."""
        with self._lock:
            out: dict[str, ResourceList] = {}
            for node in self.nodes():
                pool = node.nodepool_name()
                if not pool or node.deleting():
                    continue
                out[pool] = resutil.merge(out.get(pool, {}), node.capacity())
            return out

    def nodepool_node_count(self, pool_name: str) -> int:
        with self._lock:
            return sum(
                1
                for n in self.nodes()
                if n.nodepool_name() == pool_name and not n.deleting()
            )

    # -- static-pool accounting (statenodepool.go:30-170) ----------------------

    def nodepool_state(self, pool_name: str) -> NodePoolState:
        with self._lock:
            return self._pool_state.setdefault(pool_name, NodePoolState())

    def reserve_node_count(self, pool_name: str, want: int, limit: int) -> int:
        """Grant up to `want` launch slots without exceeding `limit`
        total (active + deleting-excluded + already-reserved). The
        reservation holds until the claim is observed through the watch
        stream, so an informer-lagged second reconcile cannot
        double-launch (ReserveNodeCount semantics)."""
        with self._lock:
            state = self._pool_state.setdefault(pool_name, NodePoolState())
            granted = max(0, min(want, limit - state.active - state.reserved))
            state.reserved += granted
            return granted

    def release_node_reservation(self, pool_name: str, count: int = 1) -> None:
        with self._lock:
            state = self._pool_state.setdefault(pool_name, NodePoolState())
            state.reserved = max(0, state.reserved - count)

    def _track_claim(self, claim: NodeClaim) -> None:
        pool = claim.metadata.labels.get(NODEPOOL_LABEL, "")
        deleting = claim.metadata.deletion_timestamp is not None
        prev = self._claim_pool.get(claim.metadata.name)
        if prev == (pool, deleting):
            return
        if prev is not None:
            self._untrack_counts(*prev)
        self._claim_pool[claim.metadata.name] = (pool, deleting)
        if pool:
            state = self._pool_state.setdefault(pool, NodePoolState())
            if deleting:
                state.deleting += 1
            else:
                state.active += 1
                # a granted launch materialized: its reservation retires
                state.reserved = max(0, state.reserved - 1)

    def _untrack_counts(self, pool: str, deleting: bool) -> None:
        if not pool:
            return
        state = self._pool_state.setdefault(pool, NodePoolState())
        if deleting:
            state.deleting = max(0, state.deleting - 1)
        else:
            state.active = max(0, state.active - 1)

    def _untrack_claim(self, name: str) -> None:
        prev = self._claim_pool.pop(name, None)
        if prev is not None:
            self._untrack_counts(*prev)

    # -- consolidation timestamps (cluster.go:537-563) ------------------------

    def mark_unconsolidated(self, now: Optional[float] = None) -> None:
        with self._lock:
            self._unconsolidated_at = time.time() if now is None else now

    def consolidation_state(self) -> float:
        with self._lock:
            return self._unconsolidated_at

    # -- ingestion ------------------------------------------------------------

    def update_node(self, node: Node) -> None:
        with self._lock:
            pid = node.spec.provider_id
            if not pid:
                # a node we own must carry its providerID before it
                # enters state; an UNMANAGED (bring-your-own) node is
                # tracked under its name so its capacity is schedulable
                # (cluster.go:353-358)
                if node.metadata.labels.get(NODEPOOL_LABEL):
                    return
                pid = node.metadata.name
                if not pid:
                    return
            elif pid != node.metadata.name:
                # the node may have been ingested name-keyed before the
                # cloud controller stamped its providerID — MIGRATE the
                # entry (a delete-and-recreate would zero scheduling
                # state like nominated_until mid-window; a leftover
                # entry would double-count capacity forever)
                stale = self._by_provider.get(node.metadata.name)
                if (
                    stale is not None
                    and stale.node_claim is None
                    and self._by_name.get(node.metadata.name) == node.metadata.name
                ):
                    if pid not in self._by_provider:
                        self._by_provider[pid] = self._by_provider.pop(
                            node.metadata.name
                        )
                    else:
                        # a claim-paired entry already owns the real
                        # key; the name-keyed duplicate just goes
                        del self._by_provider[node.metadata.name]
            state = self._by_provider.get(pid)
            if state is None:
                claim_state = None
                for name, claim_pid in list(self._claim_keys.items()):
                    if claim_pid == pid:
                        claim_state = self._unpaired_claims.pop(name, None)
                if claim_state is not None:
                    state = claim_state
                else:
                    state = StateNode()
                self._by_provider[pid] = state
            state.node = node
            self._by_name[node.metadata.name] = pid
            self._recount_node_pods(state)
            self.mark_unconsolidated()

    def delete_node(self, node: Node) -> None:
        with self._lock:
            # resolve through the name index first: it tracks whatever
            # key the node currently lives under (its providerID, or
            # its name for BYO nodes, surviving providerID arrivals
            # and deletes whose cached object predates the stamp) —
            # a miss on any path would leak the entry's capacity
            pid = (
                self._by_name.get(node.metadata.name)
                or node.spec.provider_id
                or node.metadata.name
            )
            state = self._by_provider.get(pid)
            if state is None:
                return
            state.node = None
            self._by_name.pop(node.metadata.name, None)
            if state.node_claim is None:
                del self._by_provider[pid]
            self.mark_unconsolidated()

    def update_node_claim(self, claim: NodeClaim) -> None:
        with self._lock:
            self._track_claim(claim)
            pid = claim.status.provider_id
            old_pid = self._claim_keys.get(claim.metadata.name)
            if pid:
                self._claim_keys[claim.metadata.name] = pid
                state = self._by_provider.get(pid)
                if state is None:
                    state = self._unpaired_claims.pop(claim.metadata.name, None) or StateNode()
                    self._by_provider[pid] = state
                state.node_claim = claim
            else:
                state = self._unpaired_claims.get(claim.metadata.name)
                if state is None:
                    state = StateNode()
                    self._unpaired_claims[claim.metadata.name] = state
                state.node_claim = claim
            if old_pid and old_pid != pid:
                self._by_provider.pop(old_pid, None)
            self.mark_unconsolidated()

    def delete_node_claim(self, claim: NodeClaim) -> None:
        with self._lock:
            self._untrack_claim(claim.metadata.name)
            self._unpaired_claims.pop(claim.metadata.name, None)
            pid = self._claim_keys.pop(claim.metadata.name, None)
            if pid and pid in self._by_provider:
                state = self._by_provider[pid]
                state.node_claim = None
                if state.node is None:
                    del self._by_provider[pid]
            self.mark_unconsolidated()

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            key = pod.key
            if pod.is_terminal() or pod.is_terminating():
                self._unbind(key)
            elif pod.spec.node_name:
                old_node = self._bindings.get(key)
                if old_node != pod.spec.node_name:
                    self._unbind(key)
                    state = self.node_for_name(pod.spec.node_name)
                    if state is not None:
                        state.pod_keys.add(key)
                        usage = resutil.pod_requests(pod)
                        if pod.owner_kind() == "DaemonSet":
                            state.daemon_usage = resutil.merge(state.daemon_usage, usage)
                        else:
                            state.pod_usage = resutil.merge(state.pod_usage, usage)
                    self._bindings[key] = pod.spec.node_name
                times = self._pod_times.setdefault(key, PodSchedulingTimes())
                if not times.bound:
                    times.bound = time.time()
            else:
                times = self._pod_times.setdefault(key, PodSchedulingTimes())
                if not times.first_seen:
                    times.first_seen = time.time()
            if _has_required_anti_affinity(pod):
                self._antiaffinity_pods[key] = pod
            self.mark_unconsolidated()

    def delete_pod(self, pod: Pod) -> None:
        with self._lock:
            self._unbind(pod.key, pod=pod)
            self._antiaffinity_pods.pop(pod.key, None)
            self._pod_times.pop(pod.key, None)
            self.mark_unconsolidated()

    def _unbind(self, pod_key: str, pod: Optional[Pod] = None) -> None:
        node_name = self._bindings.pop(pod_key, None)
        if node_name is None:
            return
        state = self.node_for_name(node_name)
        if state is not None and pod_key in state.pod_keys:
            state.pod_keys.discard(pod_key)
            if pod is None:
                # deleted pods are gone from the store; callers on the
                # delete path pass the object so usage is released
                pod = self.kube.get_pod(*pod_key.split("/", 1))
            if pod is not None:
                usage = resutil.pod_requests(pod)
                if pod.owner_kind() == "DaemonSet":
                    state.daemon_usage = resutil.positive(
                        resutil.subtract(state.daemon_usage, usage)
                    )
                else:
                    state.pod_usage = resutil.positive(
                        resutil.subtract(state.pod_usage, usage)
                    )

    def _recount_node_pods(self, state: StateNode) -> None:
        """Rebuild usage for a node from current bindings."""
        name = state.name
        if not name:
            return
        state.pod_keys.clear()
        state.pod_usage = {}
        state.daemon_usage = {}
        for pod in self.kube.pods_on_node(name):
            if pod.is_terminal():
                continue
            state.pod_keys.add(pod.key)
            usage = resutil.pod_requests(pod)
            if pod.owner_kind() == "DaemonSet":
                state.daemon_usage = resutil.merge(state.daemon_usage, usage)
            else:
                state.pod_usage = resutil.merge(state.pod_usage, usage)
            self._bindings[pod.key] = name

    def update_daemonset(self, ds: DaemonSet) -> None:
        with self._lock:
            self._daemonsets[ds.key] = ds

    def delete_daemonset(self, ds: DaemonSet) -> None:
        with self._lock:
            self._daemonsets.pop(ds.key, None)

    def pods_with_anti_affinity(self) -> list[Pod]:
        with self._lock:
            return list(self._antiaffinity_pods.values())

    def pod_times(self, pod_key: str) -> PodSchedulingTimes:
        with self._lock:
            return self._pod_times.setdefault(pod_key, PodSchedulingTimes())

    def mark_pod_scheduling_decisions(self, pods: Iterable[Pod], now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            for pod in pods:
                self._pod_times.setdefault(pod.key, PodSchedulingTimes()).scheduling_decision = now

    def synced(self) -> bool:
        """The informer/state sync barrier (cluster.go:118-213): the
        mirror is synced when the watch stream is fully delivered AND
        every Node/NodeClaim the store knows is tracked here. Under
        async delivery this goes False the moment a mutation is queued
        and stays False until the informer pump catches up — the gate
        every provisioning/disruption reconcile checks before solving
        against the mirror."""
        if self.kube.pending_events(INFORMER_KINDS):
            return False
        # store snapshots taken BEFORE the cluster lock: watch dispatch
        # holds the kube lock while calling into cluster handlers
        # (kube->cluster order), so taking cluster->kube here would be
        # a lock-order inversion that can deadlock embedders running
        # the operator loop and API writes on separate threads
        store_claims = self.kube.node_claims()
        store_nodes = self.kube.nodes()
        with self._lock:
            for claim in store_claims:
                pid = claim.status.provider_id
                if pid:
                    state = self._by_provider.get(pid)
                    if state is None or state.node_claim is None:
                        return False
                elif claim.metadata.name not in self._unpaired_claims:
                    return False
            for node in store_nodes:
                # providerID-less unmanaged nodes are tracked under
                # their name (update_node) — the barrier must hold for
                # them too or a solve runs blind to their capacity
                pid = node.spec.provider_id or (
                    ""
                    if node.metadata.labels.get(NODEPOOL_LABEL)
                    else node.metadata.name
                )
                if pid and pid not in self._by_provider:
                    return False
            return True


def _has_required_anti_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return bool(aff and aff.pod_anti_affinity and aff.pod_anti_affinity.required)


def attach_informers(kube: KubeClient, cluster: Cluster) -> None:
    """Wire watch streams into the mirror (state/informer/*.go)."""

    def on_node(event: str, obj) -> None:
        if event == DELETED:
            cluster.delete_node(obj)
        else:
            cluster.update_node(obj)

    def on_claim(event: str, obj) -> None:
        if event == DELETED:
            cluster.delete_node_claim(obj)
        else:
            cluster.update_node_claim(obj)

    def on_pod(event: str, obj) -> None:
        if event == DELETED:
            cluster.delete_pod(obj)
        else:
            cluster.update_pod(obj)

    def on_daemonset(event: str, obj) -> None:
        if event == DELETED:
            cluster.delete_daemonset(obj)
        else:
            cluster.update_daemonset(obj)

    handlers = {
        "Node": on_node,
        "NodeClaim": on_claim,
        "Pod": on_pod,
        "DaemonSet": on_daemonset,
    }
    assert set(handlers) == set(INFORMER_KINDS)
    for kind in INFORMER_KINDS:
        kube.watch(kind, handlers[kind])
