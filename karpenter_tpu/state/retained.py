"""Retained fleet snapshots: the disruption engine's O(dirty) seam.

Every disruption scan used to rebuild fleet state from the store —
`cluster.deep_copy_nodes()` copied every StateNode, and each
simulation's Scheduler re-derived every node's `ExistingNodeInput`
(label parsing, daemon-reserve computation) from scratch, per probe,
per method, per poll. CvxCluster's lesson (PAPERS.md) applies here
exactly as it does to the provisioning tick: never re-derive what
didn't change.

`RetainedFleetSeam` retains, per stable node, BOTH halves of a
scheduling snapshot:

- a **shallow-copied StateNode row** (the same object
  `deep_copy_nodes` would produce), refreshed only when the kube
  watch stream marks the node dirty (a Pod event dirties its bound
  node; a NodeClaim event dirties claim + node keys; a DaemonSet
  event or a 410-driven relist invalidates everything). Rows share
  `node`/`node_claim` object references with live state exactly as a
  fresh copy does, and per serve the STATE-PLANE volatile scalars
  (`marked_for_deletion`, `nominated_until`) are re-synced — those
  are mutated by controllers directly, with no watch event to catch.
- a **retained `ExistingNodeInput`** built by the same
  `NodeInputBuilder` the Scheduler uses — handed to simulation
  Schedulers via their `existing_input_cache` seam so an unchanged
  node's input is a dict lookup instead of a rebuild.

Mutation discipline: a simulation's Scheduler commits pods onto the
served rows (`_commit_existing` mutates `pod_usage`/`pod_keys`).
Callers report those rows back through `note_mutated()` — the keys of
`results.existing_assignments` — and the seam re-copies exactly those
from live state before the next serve. Rows a simulation only READ
stay retained. (The batched probe solver never mutates its snapshot —
lanes are evaluated against encoded arrays — so a whole probe ladder
costs zero re-copies.)

Volatile nodes (unlaunched claims, unregistered nodes, empty keys)
are never retained: they are few, transition-heavy, and their inputs
depend on the per-call catalog.

Decision identity is oracle-enforced: on a cadence
(`KARPENTER_DISRUPTION_SNAPSHOT_AUDIT`, default every 16 serves) a
serve is compared field-for-field against the from-scratch build; any
mismatch invalidates the retained state, counts
`karpenter_disruption_snapshot_total{outcome="divergence"}`, and the
fresh build is served. `KARPENTER_DISRUPTION_SNAPSHOT=0` disables
retention entirely (every serve is the from-scratch build).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Iterable, Optional

from karpenter_tpu.kube.dirty import DirtyTracker
from karpenter_tpu.metrics.store import DISRUPTION_SNAPSHOT
from karpenter_tpu.provisioning.scheduler import _state_node_key
from karpenter_tpu.state.cluster import StateNode

log = logging.getLogger("karpenter.state.retained")

ENV_ENABLE = "KARPENTER_DISRUPTION_SNAPSHOT"
ENV_AUDIT = "KARPENTER_DISRUPTION_SNAPSHOT_AUDIT"


def retained_enabled() -> bool:
    return os.environ.get(ENV_ENABLE, "1").lower() not in (
        "0", "false", "off"
    )


def _claim_keys(event: str, claim) -> list[str]:
    keys = [claim.metadata.name]
    if claim.status.node_name:
        keys.append(claim.status.node_name)
    return keys


def _pod_node_keys(event: str, pod) -> list[str]:
    return [pod.spec.node_name] if pod.spec.node_name else []


class RetainedFleetSeam:
    def __init__(
        self,
        kube,
        cluster,
        pools_fn: Optional[Callable] = None,
        options=None,
    ):
        self.kube = kube
        self.cluster = cluster
        # zero-arg catalog source (Provisioner.ready_pools_with_types)
        # — consulted only when the input builder must be (re)built
        self.pools_fn = pools_fn
        self.options = options
        # audit cadence is a LIVE knob (ISSUE 17 satellite): re-read
        # from the env per serve unless a test pins an override
        self._audit_every_override: Optional[int] = None
        self._tracker = DirtyTracker(kube)
        self._tracker.watch("Node")
        self._tracker.watch("NodeClaim", key=_claim_keys)
        self._tracker.watch("Pod", key=_pod_node_keys)
        self._tracker.watch("DaemonSet", key=lambda e, o: ["*"])
        # PodDisruptionBudget movement invalidates the engine's cached
        # per-pod eviction verdicts (consumed via pdb_epoch below)
        self._tracker.watch("PodDisruptionBudget", key=lambda e, o: ["*"])
        self._rows: dict[str, StateNode] = {}
        self._inputs: dict = {}                # key -> ExistingNodeInput
        self._ver: dict[str, int] = {}         # watch-dirt generation
        self._built: dict[str, int] = {}       # version a row was built at
        self._epoch = 0                        # bumped on rebuild-all
        self.pdb_epoch = 0
        self._builder = None
        self._serves = 0
        self.hits = 0
        self.rebuilds = 0
        self.audits = 0
        self.divergences = 0

    # -- knobs ----------------------------------------------------------------

    @property
    def audit_every(self) -> int:
        """Serves between identity audits — KARPENTER_DISRUPTION_
        SNAPSHOT_AUDIT read per access (a deploy retuning the cadence
        must not need a restart), unless explicitly assigned."""
        if self._audit_every_override is not None:
            return self._audit_every_override
        from karpenter_tpu.solver.incremental import _env_float

        return int(_env_float(ENV_AUDIT, 16))

    @audit_every.setter
    def audit_every(self, value: Optional[int]) -> None:
        self._audit_every_override = None if value is None else int(value)

    # -- dirt -----------------------------------------------------------------

    def sync(self) -> None:
        """Drain watch dirt into per-key versions. Cheap; callers
        (the engine's candidate-core cache and fleet_snapshot) share
        one tracker through this method."""
        # node-keyed kinds first, through the SCOPED continuity latch:
        # a shard's lost stream dirties only that shard's rows (None
        # means the client can't scope it — whole-cache bust)
        shards = self._tracker.relisted_shards("Node", "NodeClaim", "Pod")
        if shards is None:
            self.invalidate()
        elif shards:
            self.invalidate_shards(shards)
        # fleet-wide kinds keep the merged (whole-cache) contract
        if self._tracker.relisted("DaemonSet", "PodDisruptionBudget"):
            self.invalidate()
        if self._tracker.drain("PodDisruptionBudget"):
            self.pdb_epoch += 1
        if self._tracker.drain("DaemonSet"):
            # every node's daemon reserve (and the builder's pinned
            # daemonset list) just moved
            self._epoch += 1
            self._inputs.clear()
            self._rows.clear()
            self._built.clear()
            self._builder = None
        for key in (
            self._tracker.drain("Node")
            | self._tracker.drain("NodeClaim")
            | self._tracker.drain("Pod")
        ):
            self._ver[key] = self._ver.get(key, 0) + 1

    def invalidate(self) -> None:
        self._rows.clear()
        self._inputs.clear()
        self._built.clear()
        self._ver.clear()
        self._epoch += 1
        self.pdb_epoch += 1
        self._builder = None
        self._tracker.clear()

    def invalidate_shards(self, shards: set[int]) -> None:
        """Shard-scoped bust (ISSUE 16): drop retained rows/inputs
        only for keys routed to the relisted shards, leaving every
        other shard's rows warm. Version bumps cover the union of row
        and version keys in the affected shards (the engine's
        candidate-core cache stamps entries with `node_version`, which
        can outlive a pruned row). `pdb_epoch` is bumped conservatively
        — the relist's diff events can't prove no PDB-relevant pod
        churn hid in the stale window — but the build epoch and the
        input builder survive, which is the whole point."""
        from karpenter_tpu.metrics.store import STATE_SHARD_INVALIDATIONS
        from karpenter_tpu.state.shards import shard_of

        for key in [
            k for k in set(self._rows) | set(self._ver)
            if shard_of(k) in shards
        ]:
            self._rows.pop(key, None)
            self._inputs.pop(key, None)
            self._built.pop(key, None)
            self._ver[key] = self._ver.get(key, 0) + 1
        self.pdb_epoch += 1
        STATE_SHARD_INVALIDATIONS.inc({"layer": "disruption_snapshot"})

    def note_mutated(self, keys: Iterable[str]) -> None:
        """A simulation committed pods onto these served rows; re-copy
        them from live state before the next serve."""
        for key in keys:
            self._ver[key] = self._ver.get(key, 0) + 1

    def node_version(self, key: str) -> tuple:
        """(epoch, watch generation) for one node — what the engine's
        candidate-core cache stamps its entries with."""
        return (self._epoch, self._ver.get(key, 0))

    # -- input building -------------------------------------------------------

    def _get_builder(self):
        if self._builder is None and self.pools_fn is not None:
            from karpenter_tpu.provisioning.scheduler import (
                NodeInputBuilder,
            )

            self._builder = NodeInputBuilder(
                self.pools_fn(),
                self.cluster.daemonsets(),
                self.options.ignore_dra_requests
                if self.options is not None else True,
            )
        return self._builder

    # -- serving --------------------------------------------------------------

    def fleet_snapshot(self) -> tuple[list[StateNode], dict]:
        """(snapshot rows in cluster order, retained-input cache).
        The rows are what `deep_copy_nodes()` would return; the input
        dict feeds `Scheduler(existing_input_cache=...)`. Retention is
        per stable node; volatile nodes get fresh copies and no cache
        entry."""
        if not retained_enabled():
            return self.cluster.deep_copy_nodes(), {}
        self.sync()
        self._serves += 1
        builder = self._get_builder()
        out: list[StateNode] = []
        inputs: dict = {}
        seen: set[str] = set()
        serve_hits = serve_rebuilds = 0
        # the whole walk runs under the cluster lock, exactly as
        # deep_copy_nodes holds it for its copy loop: informer threads
        # mutate pod_keys/pod_usage in place on the real stack, and an
        # unlocked shallow_copy would tear (or crash on) a row
        with self.cluster._lock:
            for n in self.cluster.nodes():
                key = _state_node_key(n)
                volatile = (
                    not key or n.node is None or not n.registered()
                )
                if volatile:
                    if key:
                        self._rows.pop(key, None)
                        self._inputs.pop(key, None)
                        self._built.pop(key, None)
                    out.append(n.shallow_copy())
                    continue
                seen.add(key)
                ver = self._ver.get(key, 0)
                row = self._rows.get(key)
                if (
                    row is None
                    or self._built.get(key) != ver
                    # an object-identity swap without a watch event (a
                    # resync replacing the mirror entry) must not
                    # serve a stale pair
                    or row.node is not n.node
                    or row.node_claim is not n.node_claim
                ):
                    row = n.shallow_copy()
                    self._rows[key] = row
                    self._built[key] = ver
                    if builder is not None and not n.deleting():
                        builder.invalidate(key)
                        self._inputs[key] = builder.existing_input(n)
                    else:
                        self._inputs.pop(key, None)
                    serve_rebuilds += 1
                else:
                    # state-plane scalars are mutated directly by
                    # controllers (taint marks, nomination windows)
                    # with no watch event — re-sync per serve
                    row.marked_for_deletion = n.marked_for_deletion
                    row.nominated_until = n.nominated_until
                    serve_hits += 1
                out.append(row)
                inp = self._inputs.get(key)
                if inp is not None and not n.deleting():
                    inputs[key] = inp
        for key in [k for k in self._rows if k not in seen]:
            self._rows.pop(key, None)
            self._inputs.pop(key, None)
            self._built.pop(key, None)
        # metric increments batched per SERVE (a per-row inc was
        # measurable against the very scan wall this seam shrinks)
        self.hits += serve_hits
        self.rebuilds += serve_rebuilds
        if serve_hits:
            DISRUPTION_SNAPSHOT.inc(
                {"outcome": "hit"}, value=float(serve_hits)
            )
        if serve_rebuilds:
            DISRUPTION_SNAPSHOT.inc(
                {"outcome": "rebuild"}, value=float(serve_rebuilds)
            )
        if self.audit_every > 0 and self._serves % self.audit_every == 0:
            fresh = self._audit(out, inputs)
            if fresh is not None:
                return fresh
        return out, inputs

    # -- oracle ---------------------------------------------------------------

    @staticmethod
    def _row_fp(row: StateNode) -> tuple:
        return (
            id(row.node),
            id(row.node_claim),
            row.marked_for_deletion,
            round(row.nominated_until, 6),
            tuple(sorted(row.pod_keys)),
            tuple(sorted(
                (k, round(v, 6)) for k, v in row.pod_usage.items()
            )),
            tuple(sorted(
                (k, round(v, 6)) for k, v in row.daemon_usage.items()
            )),
        )

    @staticmethod
    def _input_fp(inp) -> tuple:
        return (
            inp.name,
            inp.pool_name,
            inp.pod_count,
            tuple(inp.taints),
            inp.requirements.signature(),
            tuple(sorted(
                (k, round(v, 6)) for k, v in inp.available.items()
            )),
        )

    def _audit(self, served: list[StateNode], served_inputs: dict):
        """From-scratch build vs the retained serve. Returns the fresh
        (rows, inputs) on divergence — the caller serves those — or
        None when identity held."""
        from karpenter_tpu.provisioning.scheduler import (
            NodeInputBuilder,
            _state_node_key,
        )

        self.audits += 1
        DISRUPTION_SNAPSHOT.inc({"outcome": "audit"})
        fresh_builder = None
        if self.pools_fn is not None:
            fresh_builder = NodeInputBuilder(
                self.pools_fn(),
                self.cluster.daemonsets(),
                self.options.ignore_dra_requests
                if self.options is not None else True,
            )
        fresh_inputs: dict = {}
        # locked like the serve: the fresh copies and input rebuilds
        # must read a consistent mirror
        with self.cluster._lock:
            fresh_rows = self.cluster.deep_copy_nodes()
            ok = len(fresh_rows) == len(served)
            if ok:
                for fresh_n, got in zip(fresh_rows, served):
                    if self._row_fp(fresh_n) != self._row_fp(got):
                        ok = False
                        break
            if ok and fresh_builder is not None:
                for key in served_inputs:
                    node = self.cluster.node_for_key(key)
                    if node is None:
                        ok = False
                        break
                    want = fresh_builder.existing_input(node)
                    if self._input_fp(want) != self._input_fp(
                        served_inputs[key]
                    ):
                        ok = False
                        break
                    fresh_inputs[key] = want
        if ok:
            return None
        self.divergences += 1
        DISRUPTION_SNAPSHOT.inc({"outcome": "divergence"})
        log.error(
            "retained disruption snapshot diverged from the "
            "from-scratch build; invalidating retained rows and "
            "serving the fresh snapshot"
        )
        self.invalidate()
        return fresh_rows, {}

    # -- observability --------------------------------------------------------

    def status(self) -> dict:
        total = self.hits + self.rebuilds
        return {
            "enabled": retained_enabled(),
            "retained_rows": len(self._rows),
            "serves": self._serves,
            "row_hits": self.hits,
            "row_rebuilds": self.rebuilds,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "audits": self.audits,
            "divergences": self.divergences,
        }
