"""kwok-style simulated cloud provider.

Counterpart of kwok/cloudprovider/cloudprovider.go: `create` picks the
cheapest compatible offering and records a simulated instance;
`tick(now)` materializes Node objects for instances whose registration
delay has elapsed (fabricated nodes, no kubelet — the reference's kwok
pattern that lets hundred-node scale-ups run on a laptop). Nodes appear
with the `unregistered` NoExecute taint, capacity/allocatable from the
instance type, and Ready=True.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    ARCH_LABEL,
    CAPACITY_TYPE_LABEL,
    INSTANCE_TYPE_LABEL,
    NODEPOOL_LABEL,
    OS_LABEL,
    RESERVATION_ID_LABEL,
    TOPOLOGY_ZONE_LABEL,
    UNREGISTERED_NO_EXECUTE_TAINT,
)
from karpenter_tpu.apis.v1.nodeclaim import NodeClaim, NodeClaimStatus
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.fake import kwok_instance_types
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    order_by_price,
)
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import (
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
)
from karpenter_tpu.scheduling.requirement import Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils.resources import fits_declared


@dataclass
class _Instance:
    claim_name: str
    node_name: str
    instance_type: InstanceType
    labels: dict[str, str]
    created_at: float
    registered: bool = False
    terminated: bool = False


class KwokCloudProvider(CloudProvider):
    def __init__(
        self,
        kube: KubeClient,
        types: Optional[list[InstanceType]] = None,
        registration_delay: float = 0.0,
        clock=None,
    ):
        """`clock` supplies the time source for instance timestamps.
        Inject a simulated clock when driving tick() with simulated
        `now` values and a nonzero registration delay — otherwise
        created_at (wall) and now (simulated) come from different
        clocks and the delay comparison is meaningless."""
        self.kube = kube
        self.types = types if types is not None else kwok_instance_types()
        self.registration_delay = registration_delay
        self.clock = clock or time.time
        self._lock = threading.RLock()
        self._instances: dict[str, _Instance] = {}  # provider id -> instance
        self._counter = itertools.count(1)
        self._repair_policies: list = []
        # chaos hook (parity with the fake provider's error injection,
        # fake/cloudprovider.go): the next create() raises this once
        self.next_create_error: Optional[Exception] = None
        # provider ids of spot instances holding an interruption notice
        # (the cloud's rebalance/termination warning; consumed by the
        # interruption controller's poll)
        self.interrupted: set[str] = set()

    def restore(self) -> int:
        """Rehydrate instance state from the store after a restart —
        the checkpoint/resume analogue: claims (and their nodes) are
        the durable record, the provider's in-memory map is a cache.
        Returns the number of instances rebuilt."""
        with self._lock:
            by_name = {it.name: it for it in self.types}
            nodes_by_pid = {
                n.spec.provider_id: n for n in self.kube.nodes()
                if n.spec.provider_id
            }
            rebuilt = 0
            for claim in self.kube.node_claims():
                pid = claim.status.provider_id
                if not pid or pid in self._instances:
                    continue
                it = by_name.get(
                    claim.metadata.labels.get(INSTANCE_TYPE_LABEL, "")
                )
                if it is None:
                    continue
                node = nodes_by_pid.get(pid)
                self._instances[pid] = _Instance(
                    claim_name=claim.metadata.name,
                    node_name=(
                        node.metadata.name if node is not None
                        else pid.removeprefix("kwok://")
                    ),
                    instance_type=it,
                    labels=dict(claim.metadata.labels),
                    created_at=self.clock(),
                    registered=node is not None,
                )
                rebuilt += 1
            # never reuse a node-name sequence number from a prior life
            taken = [
                int(inst.node_name.rsplit("-", 1)[-1])
                for inst in self._instances.values()
                if inst.node_name.rsplit("-", 1)[-1].isdigit()
            ]
            if taken:
                self._counter = itertools.count(max(taken) + 1)
            return rebuilt

    # -- SPI ------------------------------------------------------------------

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        with self._lock:
            if self.next_create_error is not None:
                err, self.next_create_error = self.next_create_error, None
                raise err
            reqs = Requirements(
                Requirement(r.key, r.operator, r.values, r.min_values)
                for r in node_claim.spec.requirements
            )
            compatible = [
                it
                for it in self.types
                if it.requirements.intersects(reqs) is None
                and it.offerings.available().has_compatible(reqs)
                and fits_declared(node_claim.spec.resources, it.allocatable)
            ]
            if not compatible:
                raise InsufficientCapacityError(
                    f"no offering satisfies {node_claim.metadata.name}"
                )
            chosen = order_by_price(compatible, reqs)[0]
            offering = chosen.offerings.available().compatible(reqs).cheapest()
            seq = next(self._counter)
            node_name = f"{node_claim.metadata.name}-{seq}"
            provider_id = f"kwok://{node_name}"
            labels = {
                # every representative label of the chosen type lands
                # on the node (the reference's instance types expose
                # Requirements().Labels(); custom catalog labels like
                # accelerator families must be visible to selectors)
                **chosen.requirements.labels(),
                **node_claim.metadata.labels,
                INSTANCE_TYPE_LABEL: chosen.name,
                TOPOLOGY_ZONE_LABEL: offering.zone,
                CAPACITY_TYPE_LABEL: offering.capacity_type,
                ARCH_LABEL: chosen.requirements.get(ARCH_LABEL).any_value(),
                OS_LABEL: chosen.requirements.get(OS_LABEL).any_value() or "linux",
            }
            if offering.reservation_id:
                labels[RESERVATION_ID_LABEL] = offering.reservation_id
            self._instances[provider_id] = _Instance(
                claim_name=node_claim.metadata.name,
                node_name=node_name,
                instance_type=chosen,
                labels=labels,
                created_at=self.clock(),
            )
            out = NodeClaim(
                metadata=node_claim.metadata,
                spec=node_claim.spec,
                status=NodeClaimStatus(
                    provider_id=provider_id,
                    image_id="kwok-image",
                    capacity=dict(chosen.capacity),
                    allocatable=dict(chosen.allocatable),
                ),
                status_conditions=node_claim.status_conditions,
            )
            out.metadata.labels = labels
            return out

    def tick(self, now: Optional[float] = None) -> list[Node]:
        """Materialize Node objects for instances past the registration
        delay (kwok NodeRegistrationDelay, cloudprovider.go:74-83)."""
        now = self.clock() if now is None else now
        created = []
        with self._lock:
            for pid, inst in self._instances.items():
                if inst.registered or inst.terminated:
                    continue
                # created_at is wall clock while `now` may be simulated;
                # only gate when a delay is actually configured
                if (
                    self.registration_delay > 0
                    and now - inst.created_at < self.registration_delay
                ):
                    continue
                claim = self.kube.get_node_claim(inst.claim_name)
                taints = [UNREGISTERED_NO_EXECUTE_TAINT]
                if claim is not None:
                    taints += list(claim.spec.taints) + list(claim.spec.startup_taints)
                node = Node(
                    metadata=ObjectMeta(name=inst.node_name, namespace="",
                                        labels=dict(inst.labels)),
                    spec=NodeSpec(taints=taints, provider_id=pid),
                    status=NodeStatus(
                        capacity=dict(inst.instance_type.capacity),
                        allocatable=dict(inst.instance_type.allocatable),
                        conditions=[NodeCondition(type="Ready", status="True")],
                    ),
                )
                self.kube.create(node)
                inst.registered = True
                created.append(node)
        return created

    def delete(self, node_claim: NodeClaim) -> None:
        with self._lock:
            pid = node_claim.status.provider_id
            inst = self._instances.get(pid)
            if inst is None or inst.terminated:
                raise NodeClaimNotFoundError(pid)
            inst.terminated = True
            del self._instances[pid]
            self.interrupted.discard(pid)

    def reprice(self, now: float) -> int:
        """Advance spot offering prices to the deterministic hourly
        curve (fake.spot_price_at). 0 changes within one price hour, so
        the encoder cache's catalog fingerprint busts only when the
        curve actually moved."""
        from karpenter_tpu.cloudprovider.fake import reprice_spot

        with self._lock:
            return reprice_spot(self.types, now)

    def poll_interruptions(self, now: Optional[float] = None) -> list[str]:
        """One `cloud_interrupt` fault check per live spot instance, in
        sorted provider-id order (occurrence numbers map to instances
        deterministically). A firing `spot_interruption` rule is
        CONSUMED here — the instance gets an interruption notice
        surfaced through `self.interrupted`, exactly like a cloud's
        rebalance/termination warning. Returns newly noticed ids."""
        from karpenter_tpu.apis.v1.labels import CAPACITY_TYPE_SPOT
        from karpenter_tpu.metrics.store import SPOT_INTERRUPTIONS
        from karpenter_tpu.solver import faults as _faults

        newly: list[str] = []
        with self._lock:
            for pid in sorted(self._instances):
                if pid in self.interrupted:
                    continue
                inst = self._instances[pid]
                if inst.terminated:
                    continue
                if inst.labels.get(CAPACITY_TYPE_LABEL) != CAPACITY_TYPE_SPOT:
                    continue
                try:
                    _faults.fire("cloud_interrupt")
                except _faults.SpotInterruptionError:
                    self.interrupted.add(pid)
                    newly.append(pid)
                    SPOT_INTERRUPTIONS.inc({"provider": "kwok"})
                except _faults.FaultError as err:
                    # a mis-kinded spec (e.g. device_lost@cloud_interrupt)
                    # is consumed, not propagated: a chaos knob must
                    # never take the operator tick down
                    logging.getLogger(__name__).warning(
                        "ignoring non-interruption fault at "
                        "cloud_interrupt: %r", err,
                    )
        return newly

    def get(self, provider_id: str) -> NodeClaim:
        with self._lock:
            inst = self._instances.get(provider_id)
            if inst is None:
                raise NodeClaimNotFoundError(provider_id)
            claim = NodeClaim(metadata=ObjectMeta(name=inst.claim_name, namespace=""))
            claim.status.provider_id = provider_id
            claim.metadata.labels = dict(inst.labels)
            return claim

    def list(self) -> list[NodeClaim]:
        with self._lock:
            return [self.get(pid) for pid in list(self._instances)]

    def get_instance_types(self, node_pool: Optional[NodePool]) -> list[InstanceType]:
        return list(self.types)

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return ""

    def repair_policies(self) -> list:
        return list(self._repair_policies)

    def name(self) -> str:
        return "kwok"

    def get_supported_node_classes(self) -> list[str]:
        return ["KwokNodeClass"]
