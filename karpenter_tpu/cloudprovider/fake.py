"""Fake cloud provider + synthetic instance-type generators.

Counterpart of pkg/cloudprovider/fake (cloudprovider.go, instancetype.go):
an in-memory provider with configurable instance types and error
injection, plus the `instance_types(n)` diverse-catalog generator and a
kwok-style catalog (144 types across 3 zones, spot + on-demand priced)
used by the benchmark harness.
"""

from __future__ import annotations

import itertools
import logging
import threading
import zlib
from typing import Callable, Optional

from karpenter_tpu.apis.v1.labels import (
    ARCH_AMD64,
    ARCH_ARM64,
    ARCH_LABEL,
    CAPACITY_TYPE_LABEL,
    CAPACITY_TYPE_ON_DEMAND,
    CAPACITY_TYPE_RESERVED,
    CAPACITY_TYPE_SPOT,
    INSTANCE_TYPE_LABEL,
    NODEPOOL_LABEL,
    OS_LABEL,
    RESERVATION_ID_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_LAUNCHED,
    NodeClaim,
    NodeClaimStatus,
)
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    NodeClaimNotFoundError,
    Offering,
    Offerings,
    RepairPolicy,
)
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils.resources import CPU, MEMORY, PODS, ResourceList

# Extra well-known-ish labels used by the fake catalog (instancetype.go:33-38)
LABEL_INSTANCE_SIZE = "size"
LABEL_EXOTIC = "special"
LABEL_INTEGER = "integer"

GIB = 2**30
DEFAULT_ZONES = ("test-zone-1", "test-zone-2", "test-zone-3")


def price_from_resources(resources: ResourceList) -> float:
    """Deterministic synthetic price (fake PriceFromResources)."""
    return resources.get(CPU, 0.0) * 0.025 + resources.get(MEMORY, 0.0) / GIB * 0.001


# base spot discount vs on-demand (kwok's spot pricing ratio)
SPOT_DISCOUNT = 0.4


def spot_price_at(on_demand_price: float, zone: str, now: float) -> float:
    """Deterministic time-varying spot price: the on-demand price at
    the base SPOT_DISCOUNT, wobbled per (zone, hour) by up to ±12.5% —
    a seeded stand-in for real spot market drift. Pure function of its
    inputs, so two runs over the same simulated hours see identical
    curves (replay-identical bench arms)."""
    hour = int(now // 3600.0)
    wobble = (
        (zlib.crc32(f"{zone}:{hour}".encode()) % 1001) / 1000.0 - 0.5
    ) * 0.25
    return round(on_demand_price * SPOT_DISCOUNT * (1.0 + wobble), 6)


def reprice_spot(types: list[InstanceType], now: float) -> int:
    """Re-point every spot offering's price at the deterministic
    curve for `now` (each zone's on-demand sibling is the reference
    price). In-place: the encoder cache's catalog fingerprint covers
    offering prices, so a reprice busts it exactly like an overlay
    price change would. Returns the number of offerings updated."""
    updated = 0
    for it in types:
        od_by_zone = {
            o.zone: o.price
            for o in it.offerings
            if o.capacity_type == CAPACITY_TYPE_ON_DEMAND
        }
        for o in it.offerings:
            if o.capacity_type != CAPACITY_TYPE_SPOT:
                continue
            base = od_by_zone.get(o.zone)
            if base is None:
                continue
            price = spot_price_at(base, o.zone, now)
            if price != o.price:
                o.price = price
                updated += 1
    return updated


def make_instance_type(
    name: str,
    cpu: float = 4,
    memory: float = 4 * GIB,
    pods: float = 110,
    arch: str = ARCH_AMD64,
    os: str = "linux",
    zones: tuple[str, ...] = DEFAULT_ZONES,
    capacity_types: tuple[str, ...] = (CAPACITY_TYPE_SPOT, CAPACITY_TYPE_ON_DEMAND),
    price: Optional[float] = None,
    extra_resources: Optional[ResourceList] = None,
    extra_labels: Optional[dict[str, str]] = None,
    offerings: Optional[Offerings] = None,
    reservations: Optional[list[tuple[str, str, int]]] = None,
) -> InstanceType:
    """`reservations`: list of (reservation_id, zone, instance_count) —
    each becomes a reserved-capacity offering priced at ~0 (already
    paid for), bounded by its instance count."""
    capacity: ResourceList = {CPU: cpu, MEMORY: memory, PODS: pods}
    if extra_resources:
        capacity.update(extra_resources)
    base_price = price if price is not None else price_from_resources(capacity)
    if offerings is None:
        offerings = Offerings()
        for ct in capacity_types:
            for zone in zones:
                # spot trades at a discount; mild per-zone variation
                # (stable hash: Python's hash() is salted per process)
                mult = 0.4 if ct == CAPACITY_TYPE_SPOT else 1.0
                zone_mult = 1.0 + 0.01 * (zlib.crc32(zone.encode()) % 7)
                offerings.append(
                    Offering(
                        requirements=Requirements.from_labels(
                            {CAPACITY_TYPE_LABEL: ct, TOPOLOGY_ZONE_LABEL: zone}
                        ),
                        price=round(base_price * mult * zone_mult, 6),
                        available=True,
                    )
                )
        for rid, zone, count in reservations or ():
            offerings.append(
                Offering(
                    requirements=Requirements.from_labels(
                        {
                            CAPACITY_TYPE_LABEL: CAPACITY_TYPE_RESERVED,
                            TOPOLOGY_ZONE_LABEL: zone,
                            RESERVATION_ID_LABEL: rid,
                        }
                    ),
                    # reserved capacity is prepaid: marginal launch
                    # price is ~nothing (cloudprovider/types.go
                    # AdjustedPrice treats reserved as ~free)
                    price=base_price * 1e-4,
                    available=True,
                    reservation_capacity=count,
                )
            )
    reqs = Requirements(
        [
            Requirement(INSTANCE_TYPE_LABEL, IN, [name]),
            Requirement(ARCH_LABEL, IN, [arch]),
            Requirement(OS_LABEL, IN, [os]),
            Requirement(
                TOPOLOGY_ZONE_LABEL, IN, sorted({o.zone for o in offerings if o.available})
            ),
            Requirement(
                CAPACITY_TYPE_LABEL,
                IN,
                sorted({o.capacity_type for o in offerings if o.available}),
            ),
            Requirement(LABEL_INSTANCE_SIZE, IN, [_size_name(cpu)]),
        ]
    )
    for key, value in (extra_labels or {}).items():
        reqs.add(Requirement(key, IN, [value]))
    overhead = InstanceTypeOverhead(
        kube_reserved={CPU: 0.1, MEMORY: 0.1 * GIB},
    )
    return InstanceType(
        name=name, requirements=reqs, offerings=offerings, capacity=capacity, overhead=overhead
    )


def _size_name(cpu: float) -> str:
    if cpu <= 2:
        return "small"
    if cpu <= 8:
        return "medium"
    if cpu <= 32:
        return "large"
    return "xlarge"


def instance_types(count: int) -> list[InstanceType]:
    """Diverse synthetic catalog (fake InstanceTypes(n)): cycles cpu,
    memory ratio, arch and os options deterministically."""
    cpus = [1, 2, 4, 8, 16, 32, 48, 64, 96]
    mem_ratios = [2, 4, 8]  # GiB per vCPU
    archs = [ARCH_AMD64, ARCH_ARM64]
    oses = ["linux", "windows"]
    out = []
    combos = itertools.cycle(itertools.product(cpus, mem_ratios, archs, oses))
    for i in range(count):
        cpu, ratio, arch, os = next(combos)
        name = f"{_size_name(cpu)}-{cpu}-{ratio}x-{arch}-{os}-{i}"
        out.append(
            make_instance_type(
                name,
                cpu=float(cpu),
                memory=float(cpu * ratio * GIB),
                pods=float(min(110, cpu * 16)),
                arch=arch,
                os=os,
            )
        )
    return out


def heterogeneous_instance_types(count: int) -> list[InstanceType]:
    """Family-priced catalog: $/vCPU depends on the memory ratio the
    way real cloud families do (compute-optimized cheapest per vCPU,
    memory-optimized cheapest per GiB), plus a premium on the largest
    sizes. Unlike `instance_types` (whose price is LINEAR in resources
    — the reference's fake PriceFromResources — making greedy FFD
    near-optimal by construction), this curve gives bin-packing choices
    real dollar consequences: matching cpu-heavy and memory-heavy pods
    to the right family, or sharing a node between complementary
    shapes, measurably beats first-fit."""
    family_rate = {2: 0.031, 4: 0.040, 8: 0.055}  # $/vCPU by GiB-per-vCPU
    cpus = [1, 2, 4, 8, 16, 32, 48, 64, 96]
    out = []
    combos = itertools.cycle(
        itertools.product(cpus, (2, 4, 8), (ARCH_AMD64, ARCH_ARM64))
    )
    for i in range(count):
        cpu, ratio, arch = next(combos)
        price = cpu * family_rate[ratio] * (1.08 if cpu >= 48 else 1.0)
        out.append(
            make_instance_type(
                f"f{ratio}x-{_size_name(cpu)}-{cpu}-{arch}-{i}",
                cpu=float(cpu),
                memory=float(cpu * ratio * GIB),
                pods=float(min(110, cpu * 16)),
                arch=arch,
                price=price,
            )
        )
    return out


def kwok_instance_types() -> list[InstanceType]:
    """144-type kwok-style catalog: cpu x memory-ratio grid, amd64+arm64,
    3 zones, spot + on-demand (kwok/cloudprovider/instance_types.json)."""
    out = []
    for cpu in (1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256):
        for ratio in (2, 4, 8):
            for arch in (ARCH_AMD64, ARCH_ARM64):
                name = f"c-{cpu}x-{ratio}r-{arch}"
                out.append(
                    make_instance_type(
                        name,
                        cpu=float(cpu),
                        memory=float(cpu * ratio * GIB),
                        pods=float(min(110, max(8, cpu * 8))),
                        arch=arch,
                        os="linux",
                    )
                )
    return out


class FakeCloudProvider(CloudProvider):
    """In-memory provider with error injection (fake/cloudprovider.go)."""

    def __init__(self, types: Optional[list[InstanceType]] = None):
        self._lock = threading.RLock()
        self.types: list[InstanceType] = types if types is not None else instance_types(24)
        self.created: dict[str, NodeClaim] = {}  # provider_id -> claim copy
        self.create_calls: list[NodeClaim] = []
        self.delete_calls: list[NodeClaim] = []
        self.allowed_create_calls: int = 2**31
        self.next_create_error: Optional[Exception] = None
        self.instance_types_hook: Optional[
            Callable[[Optional[NodePool]], list[InstanceType]]
        ] = None
        self.drifted: str = ""
        self._repair_policies: list[RepairPolicy] = []
        self._counter = itertools.count(1)
        # provider ids of spot instances holding an interruption notice
        self.interrupted: set[str] = set()

    # -- SPI ------------------------------------------------------------------

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        with self._lock:
            self.create_calls.append(node_claim)
            if self.next_create_error is not None:
                err, self.next_create_error = self.next_create_error, None
                raise err
            if len(self.create_calls) > self.allowed_create_calls:
                raise Exception("create call limit exceeded")
            reqs = Requirements(
                Requirement(r.key, r.operator, r.values, r.min_values)
                for r in node_claim.spec.requirements
            )
            chosen = self._pick_instance_type(reqs, node_claim)
            offering = chosen.offerings.available().compatible(reqs).cheapest()
            provider_id = f"fake://{chosen.name}/{next(self._counter)}"
            labels = {
                INSTANCE_TYPE_LABEL: chosen.name,
                CAPACITY_TYPE_LABEL: offering.capacity_type,
                TOPOLOGY_ZONE_LABEL: offering.zone,
                ARCH_LABEL: chosen.requirements.get(ARCH_LABEL).any_value(),
                OS_LABEL: chosen.requirements.get(OS_LABEL).any_value(),
            }
            if offering.reservation_id:
                labels[RESERVATION_ID_LABEL] = offering.reservation_id
            if node_claim.metadata.labels.get(NODEPOOL_LABEL):
                labels[NODEPOOL_LABEL] = node_claim.metadata.labels[NODEPOOL_LABEL]
            out = NodeClaim(
                metadata=node_claim.metadata,
                spec=node_claim.spec,
                status=NodeClaimStatus(
                    provider_id=provider_id,
                    image_id="fake-image",
                    capacity=dict(chosen.capacity),
                    allocatable=dict(chosen.allocatable),
                ),
            )
            out.metadata.labels = {**node_claim.metadata.labels, **labels}
            out.status_conditions.set_true(COND_LAUNCHED)
            self.created[provider_id] = out
            return out

    def _pick_instance_type(self, reqs: Requirements, claim: NodeClaim) -> InstanceType:
        from karpenter_tpu.cloudprovider.types import order_by_price
        from karpenter_tpu.utils.resources import fits_declared

        compatible = [
            it
            for it in self.types
            if it.requirements.intersects(reqs) is None
            and it.offerings.available().has_compatible(reqs)
            and fits_declared(claim.spec.resources, it.allocatable)
        ]
        if not compatible:
            raise Exception(f"no compatible instance type for {claim.metadata.name}")
        return order_by_price(compatible, reqs)[0]

    def delete(self, node_claim: NodeClaim) -> None:
        with self._lock:
            self.delete_calls.append(node_claim)
            if node_claim.status.provider_id not in self.created:
                raise NodeClaimNotFoundError(node_claim.status.provider_id)
            del self.created[node_claim.status.provider_id]
            self.interrupted.discard(node_claim.status.provider_id)

    def reprice(self, now: float) -> int:
        """Advance spot offering prices to the deterministic curve for
        `now` (see spot_price_at). Returns offerings changed — 0 within
        one price hour, so the encoder cache's catalog fingerprint only
        busts when the curve actually moved."""
        with self._lock:
            return reprice_spot(self.types, now)

    def poll_interruptions(self, now: Optional[float] = None) -> list[str]:
        """One `cloud_interrupt` fault check per live spot instance, in
        sorted provider-id order (occurrence numbers map to instances
        deterministically). A firing `spot_interruption` rule is
        CONSUMED here: the instance gets an interruption notice —
        exactly a cloud's rebalance/termination warning — surfaced
        through `self.interrupted` for the interruption controller's
        normal poll. Returns the newly noticed provider ids."""
        from karpenter_tpu.metrics.store import SPOT_INTERRUPTIONS
        from karpenter_tpu.solver import faults as _faults

        newly: list[str] = []
        with self._lock:
            for pid in sorted(self.created):
                if pid in self.interrupted:
                    continue
                claim = self.created[pid]
                if (
                    claim.metadata.labels.get(CAPACITY_TYPE_LABEL)
                    != CAPACITY_TYPE_SPOT
                ):
                    continue
                try:
                    _faults.fire("cloud_interrupt")
                except _faults.SpotInterruptionError:
                    self.interrupted.add(pid)
                    newly.append(pid)
                    SPOT_INTERRUPTIONS.inc({"provider": "fake"})
                except _faults.FaultError as err:
                    # a mis-kinded spec (e.g. device_lost@cloud_interrupt)
                    # is consumed, not propagated: a chaos knob must
                    # never take the operator tick down
                    logging.getLogger(__name__).warning(
                        "ignoring non-interruption fault at "
                        "cloud_interrupt: %r", err,
                    )
        return newly

    def get(self, provider_id: str) -> NodeClaim:
        with self._lock:
            claim = self.created.get(provider_id)
            if claim is None:
                raise NodeClaimNotFoundError(provider_id)
            return claim

    def list(self) -> list[NodeClaim]:
        with self._lock:
            return list(self.created.values())

    def get_instance_types(self, node_pool: Optional[NodePool]) -> list[InstanceType]:
        if self.instance_types_hook is not None:
            return self.instance_types_hook(node_pool)
        return list(self.types)

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self.drifted

    def repair_policies(self) -> list[RepairPolicy]:
        return list(self._repair_policies)

    def name(self) -> str:
        return "fake"

    def get_supported_node_classes(self) -> list[str]:
        return ["TestNodeClass"]

    def reset(self) -> None:
        with self._lock:
            self.created.clear()
            self.create_calls.clear()
            self.delete_calls.clear()
            self.next_create_error = None
            self.allowed_create_calls = 2**31
