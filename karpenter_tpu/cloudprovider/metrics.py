"""Metrics decorator for the CloudProvider SPI.

Counterpart of pkg/cloudprovider/metrics/cloudprovider.go:81-180: every
SPI call is wrapped with duration and error counters labeled by method
and provider.
"""

from __future__ import annotations

import time

from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.metrics.store import REGISTRY

DURATION = REGISTRY.histogram(
    "karpenter_cloudprovider_duration_seconds",
    "Duration of cloud provider method calls",
)
ERRORS = REGISTRY.counter(
    "karpenter_cloudprovider_errors_total",
    "Cloud provider method errors",
)


class MetricsCloudProvider(CloudProvider):
    def __init__(self, inner: CloudProvider):
        self.inner = inner

    def _call(self, method: str, fn, *args, **kwargs):
        labels = {"method": method, "provider": self.inner.name()}
        start = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        except Exception as err:
            ERRORS.inc({**labels, "error": type(err).__name__})
            raise
        finally:
            DURATION.observe(time.perf_counter() - start, labels)

    def create(self, node_claim):
        return self._call("Create", self.inner.create, node_claim)

    def delete(self, node_claim):
        return self._call("Delete", self.inner.delete, node_claim)

    def get(self, provider_id):
        return self._call("Get", self.inner.get, provider_id)

    def list(self):
        return self._call("List", self.inner.list)

    def get_instance_types(self, node_pool):
        return self._call("GetInstanceTypes", self.inner.get_instance_types, node_pool)

    def is_drifted(self, node_claim):
        return self._call("IsDrifted", self.inner.is_drifted, node_claim)

    def repair_policies(self):
        return self.inner.repair_policies()

    # spot-tier hooks (optional on the SPI): forwarded so controllers
    # handed the decorated provider still see the notice/price surface
    def reprice(self, now):
        fn = getattr(self.inner, "reprice", None)
        return 0 if fn is None else fn(now)

    def poll_interruptions(self, now=None):
        fn = getattr(self.inner, "poll_interruptions", None)
        return [] if fn is None else self._call("PollInterruptions", fn, now)

    @property
    def interrupted(self):
        return getattr(self.inner, "interrupted", set())

    def name(self):
        return self.inner.name()

    def get_supported_node_classes(self):
        return self.inner.get_supported_node_classes()
