"""CloudProvider SPI and the InstanceType/Offering model.

Counterpart of pkg/cloudprovider/types.go: the 9-method provider
interface (types.go:72-100), InstanceType with memoized Allocatable
(types.go:181-219), Offerings keyed by (capacity-type, zone
[, reservation-id]) with price/availability (types.go:355-417), list
operations (order-by-price, compatible, minValues satisfaction,
truncation), and the typed error taxonomy (types.go:477-586).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from functools import cached_property
from typing import Iterable, Optional, Sequence, TYPE_CHECKING

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    CAPACITY_TYPE_RESERVED,
    CAPACITY_TYPE_SPOT,
    RESERVATION_ID_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.resources import ResourceList

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.apis.v1.nodeclaim import NodeClaim
    from karpenter_tpu.apis.v1.nodepool import NodePool
    from karpenter_tpu.kube.objects import Node


@dataclass
class Offering:
    """One purchasable variant of an instance type.

    Uniquely identified by capacity type + zone (+ reservation id for
    reserved capacity). `reservation_capacity` bounds concurrent use of
    a capacity reservation.
    """

    requirements: Requirements
    price: float
    available: bool = True
    reservation_capacity: int = 0

    @property
    def capacity_type(self) -> str:
        return self.requirements.get(CAPACITY_TYPE_LABEL).any_value()

    @property
    def zone(self) -> str:
        return self.requirements.get(TOPOLOGY_ZONE_LABEL).any_value()

    @property
    def reservation_id(self) -> str:
        if not self.requirements.has(RESERVATION_ID_LABEL):
            return ""
        return self.requirements.get(RESERVATION_ID_LABEL).any_value()

    def is_reserved(self) -> bool:
        return self.capacity_type == CAPACITY_TYPE_RESERVED

    def is_spot(self) -> bool:
        return self.capacity_type == CAPACITY_TYPE_SPOT


# -- interruption-adjusted pricing -------------------------------------------
#
# Spot capacity trades at a discount because it can be reclaimed; a
# decision layer that compares raw prices keeps churning workloads onto
# capacity about to be interrupted. KARPENTER_SPOT_PENALTY expresses
# the expected interruption cost as a price multiplier: the solver's
# encoded price matrices and consolidation's cheaper-than filter price
# spot offerings at price x (1 + penalty), while the raw price stays
# what the fleet actually pays (bench/validation economics).

SPOT_PENALTY_ENV = "KARPENTER_SPOT_PENALTY"

# parse memo keyed on the raw env value: effective_price sits in the
# encode hot loop (once per spot launch config), and re-floating the
# same string thousands of times per solve is pure waste
_penalty_memo: tuple[str, float] = ("", 0.0)


def interruption_penalty() -> float:
    """The configured spot interruption penalty (>= 0; 0 = raw
    prices). Read per call so chaos suites and the bench can flip it
    without rebuilding catalogs; the encoder cache folds the value
    into its catalog fingerprint."""
    global _penalty_memo
    raw = os.environ.get(SPOT_PENALTY_ENV, "")
    if raw == _penalty_memo[0]:
        return _penalty_memo[1]
    try:
        value = max(0.0, float(raw))
    except ValueError:
        value = 0.0
    _penalty_memo = (raw, value)
    return value


def effective_price(offering: "Offering") -> float:
    """The decision-layer price of an offering: raw for on-demand and
    reserved capacity, interruption-penalized for spot."""
    if offering.is_spot():
        return offering.price * (1.0 + interruption_penalty())
    return offering.price


class Offerings(list):
    """Decorated list of Offering (types.go:419-474)."""

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(
            o for o in self if reqs.intersects(o.requirements) is None
        )

    def has_compatible(self, reqs: Requirements) -> bool:
        return any(reqs.intersects(o.requirements) is None for o in self)

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price, default=None)

    def most_expensive(self) -> Optional[Offering]:
        return max(self, key=lambda o: o.price, default=None)

    def worst_launch_price(self, reqs: Requirements) -> float:
        """Highest price a launch could resolve to given requirements
        (types.go:459-474): max over compatible available offerings."""
        compatible = self.available().compatible(reqs)
        worst = compatible.most_expensive()
        return worst.price if worst else math.inf


@dataclass
class InstanceTypeOverhead:
    kube_reserved: ResourceList = field(default_factory=dict)
    system_reserved: ResourceList = field(default_factory=dict)
    eviction_threshold: ResourceList = field(default_factory=dict)

    def total(self) -> ResourceList:
        return resutil.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


@dataclass
class InstanceType:
    name: str
    requirements: Requirements
    offerings: Offerings
    capacity: ResourceList
    overhead: InstanceTypeOverhead = field(default_factory=InstanceTypeOverhead)

    @cached_property
    def allocatable(self) -> ResourceList:
        """capacity - overhead, clamped at zero (types.go:181-219)."""
        return resutil.positive(resutil.subtract(self.capacity, self.overhead.total()))

    def __repr__(self) -> str:
        return f"InstanceType({self.name})"


def order_by_price(types: Sequence[InstanceType], reqs: Requirements) -> list[InstanceType]:
    """Sort by cheapest compatible available offering (types.go:221-241)."""

    def price(it: InstanceType) -> float:
        cheapest = it.offerings.available().compatible(reqs).cheapest()
        return cheapest.price if cheapest else math.inf

    return sorted(types, key=lambda it: (price(it), it.name))


def compatible(types: Iterable[InstanceType], reqs: Requirements) -> list[InstanceType]:
    return [it for it in types if it.requirements.intersects(reqs) is None]


def min_values_coverage(
    types: Sequence[InstanceType], reqs: Requirements
) -> dict[str, int]:
    """Per floored key, the count of distinct allowed values covered
    across the instance types — the quantity SatisfiesMinValues
    compares floors against (types.go:284-318), and the count a
    BestEffort relaxation lowers an unsatisfiable floor to
    (nodeclaim.go:147-150)."""
    out: dict[str, int] = {}
    for req in reqs:
        if req.min_values is None:
            continue
        values: set[str] = set()
        for it in types:
            it_req = it.requirements.get(req.key)
            if it_req.operator() == "In":
                values.update(v for v in it_req.value_list() if req.has(v))
        out[req.key] = len(values)
    return out


def satisfies_min_values(
    types: Sequence[InstanceType], reqs: Requirements
) -> tuple[int, Optional[str]]:
    """Check minValues flexibility floors against an instance-type set.

    Returns (max satisfiable minValues count, error string or None) —
    mirrors InstanceTypes.SatisfiesMinValues (types.go:284-318): for
    each requirement with minValues, count distinct values covered
    across the instance types.
    """
    if not reqs.has_min_values():
        return (len(types), None)
    incompatible_key = ""
    max_satisfiable = len(types)
    coverage = min_values_coverage(types, reqs)
    for req in reqs:
        if req.min_values is None:
            continue
        covered = coverage.get(req.key, 0)
        if covered < req.min_values:
            incompatible_key = req.key
            max_satisfiable = min(max_satisfiable, covered)
    if incompatible_key:
        return (
            max_satisfiable,
            f"minValues requirement is not met for label {incompatible_key}",
        )
    return (len(types), None)


def truncate(
    types: Sequence[InstanceType], reqs: Requirements, max_items: int
) -> list[InstanceType]:
    """Truncate a price-ordered list to max_items, keeping minValues
    satisfiable (types.go:322-352)."""
    if len(types) <= max_items:
        return list(types)
    truncated = list(types[:max_items])
    if reqs.has_min_values():
        _, err = satisfies_min_values(truncated, reqs)
        if err is not None:
            raise ValueError(f"truncating instance types breaks minValues: {err}")
    return truncated


# ---------------------------------------------------------------- errors


class CloudProviderError(Exception):
    """Base for typed SPI errors."""


class NodeClaimNotFoundError(CloudProviderError):
    pass


class InsufficientCapacityError(CloudProviderError):
    """ICE — the offering cannot be fulfilled right now."""


class NodeClassNotReadyError(CloudProviderError):
    pass


class CreateError(CloudProviderError):
    def __init__(self, message: str, reason: str = "LaunchFailed"):
        super().__init__(message)
        self.reason = reason


@dataclass
class RepairPolicy:
    """Unhealthy-node condition the provider wants remediated
    (types.go RepairPolicy)."""

    condition_type: str
    condition_status: str
    toleration_duration: float  # seconds


class CloudProvider:
    """The 9-method SPI (types.go:72-100). Providers subclass this."""

    def create(self, node_claim: "NodeClaim") -> "NodeClaim":
        """Launch capacity for the claim; returns a claim whose status
        (provider_id, capacity, allocatable, labels) is populated."""
        raise NotImplementedError

    def delete(self, node_claim: "NodeClaim") -> None:
        raise NotImplementedError

    def get(self, provider_id: str) -> "NodeClaim":
        raise NotImplementedError

    def list(self) -> list["NodeClaim"]:
        raise NotImplementedError

    def get_instance_types(self, node_pool: "NodePool") -> list[InstanceType]:
        raise NotImplementedError

    def is_drifted(self, node_claim: "NodeClaim") -> str:
        """Non-empty drift reason if the claim no longer matches its
        nodeclass; empty string otherwise."""
        raise NotImplementedError

    def repair_policies(self) -> list[RepairPolicy]:
        return []

    def name(self) -> str:
        raise NotImplementedError

    def get_supported_node_classes(self) -> list[str]:
        return []
